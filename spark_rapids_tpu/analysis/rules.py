"""tpulint rule passes: one class per engine invariant.

Each rule is a pure function of one file's AST (`FileContext` in, raw
`Finding`s out); suppressions and the baseline are applied by the
engine (core.py), so a rule never needs to know about either.  The
rules encode invariants established by PRs 1-10 — the PR that learned
each lesson is named in the rule docstring and in docs/dev-guide.md.

Static analysis is approximate by design: a rule fires on the lexical
shape of a violation.  Where the shape is legitimately reachable by
safe code (a host-side `np.asarray`, a daemon server parked on its
socket), the remedy is a per-line suppression WITH a reason — which is
itself enforced (`bad-suppress`).
"""
from __future__ import annotations

import ast
import re
from typing import Optional

from spark_rapids_tpu.analysis.core import FileContext, Finding


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _receiver(call_func: ast.AST) -> Optional[str]:
    """Dotted receiver of a method call ('self._queue' for
    self._queue.get), else None (computed receivers)."""
    if isinstance(call_func, ast.Attribute):
        return dotted(call_func.value)
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _is_literalish(node: ast.AST) -> bool:
    """Constant-ish expressions that cannot hold a device array."""
    return isinstance(node, (ast.Constant, ast.List, ast.Tuple,
                             ast.Dict, ast.Set, ast.ListComp,
                             ast.GeneratorExp, ast.JoinedStr))


class Rule:
    rule_id = "?"
    doc = ""

    def check(self, ctx: FileContext) -> list[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.rule_id, ctx.relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
class HostSyncRule(Rule):
    """Rule 1 (PR 2, the host-sync diet): a device->host blocking
    materialization on a hot path (exec/, ops/, shuffle/, exprs/,
    plan/) must be accounted via `utils.checks.note_host_sync` — the
    enclosing function must call it (or the site carries a reasoned
    suppression when the value is host-resident).  Detected shapes:
    `np.asarray(...)`, `.item()`, `jax.device_get(...)`, `.to_py()`,
    `.block_until_ready()` — and therefore also the `int()/float()/
    bool()` wrappers around them."""

    rule_id = "host-sync"
    doc = ("device->host materializations on hot paths must route "
           "through utils.checks.note_host_sync(site=...)")

    _NP_NAMES = {"np", "numpy", "_np", "onp"}

    def check(self, ctx: FileContext) -> list[Finding]:
        if not ctx.is_hot_path:
            return []
        out: list[Finding] = []
        self._walk(ctx, ctx.tree, noted=False, out=out)
        return out

    @staticmethod
    def _has_note(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d is not None and d.split(".")[-1] == "note_host_sync":
                    return True
        return False

    def _walk(self, ctx, node, noted, out):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            noted = noted or self._has_note(node)
        elif isinstance(node, ast.Call) and not noted:
            m = self._sync_kind(node)
            if m is not None:
                out.append(self.finding(
                    ctx, node,
                    f"{m} is a blocking device->host readback; "
                    "call utils.checks.note_host_sync(site=...) in "
                    "this function (or suppress with a reason if "
                    "the value is host-resident)"))
        for child in ast.iter_child_nodes(node):
            self._walk(ctx, child, noted, out)

    def _sync_kind(self, call: ast.Call) -> Optional[str]:
        f = call.func
        d = dotted(f)
        if d == "jax.device_get" or d == "device_get":
            return "jax.device_get()"
        if isinstance(f, ast.Attribute):
            if (f.attr == "asarray"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in self._NP_NAMES):
                if call.args and _is_literalish(call.args[0]):
                    return None
                return f"{f.value.id}.asarray()"
            if f.attr == "item" and not call.args:
                return ".item()"
            if f.attr == "to_py":
                return ".to_py()"
            if f.attr == "block_until_ready":
                return ".block_until_ready()"
        return None


# ---------------------------------------------------------------------------
#: dotted-name suffixes that are sanctioned cancellable waits — the
#: watchdog's bounded-poll helpers (PR 4) and the seeded injectors,
#: which sleep cancellably by construction
_CANCELLABLE = ("cancellable_sleep", "cancellable_wait",
                "check_cancelled", "maybe_hang", "maybe_slow")


def _is_cancellable_helper(call: ast.Call) -> bool:
    d = dotted(call.func)
    return d is not None and d.split(".")[-1] in _CANCELLABLE


def _queue_style_get(call: ast.Call) -> bool:
    """`.get()` shapes that BLOCK: zero-arg, or block=True/positional
    True without a timeout.  `d.get(key[, default])` is dict access."""
    if _kw(call, "timeout") is not None:
        return False
    if not call.args and not call.keywords:
        return True
    blk = _kw(call, "block")
    if blk is not None:
        return not (isinstance(blk, ast.Constant) and blk.value is False)
    if (len(call.args) == 1 and isinstance(call.args[0], ast.Constant)
            and call.args[0].value is True):
        return True
    return False


class BlockingWhileHoldingRule(Rule):
    """Rule 2 (PR 2/6): code lexically inside a `with ...held():`
    region (the task holds the TPU semaphore) must not call anything
    that can block — queue get/put, socket recv, Event.wait, sleep,
    lock acquire, thread join — without first entering
    `TpuSemaphore.yielded()` or using a cancellable watchdog wait.  A
    task parked while holding the semaphore starves every other
    query's device access (the fair-share rewrite made the semaphore
    the engine's admission point, which makes holding-while-blocked
    strictly worse than pre-PR-6)."""

    rule_id = "sem-blocking"
    doc = ("blocking calls inside a semaphore-held region must use "
           "TpuSemaphore.yielded() or a cancellable watchdog wait")

    _BLOCK_ATTRS = {"get", "put", "recv", "wait", "acquire", "join",
                    "sleep"}

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        self._walk(ctx, ctx.tree, held=False, out=out)
        return out

    def _walk(self, ctx, node, held, out):
        if isinstance(node, ast.With):
            attrs = {c.func.attr for c in
                     (i.context_expr for i in node.items)
                     if isinstance(c, ast.Call)
                     and isinstance(c.func, ast.Attribute)}
            if "yielded" in attrs:
                held = False     # the hold is released for this body
            elif "held" in attrs:
                held = True
            for b in node.body:
                self._walk(ctx, b, held, out)
            return
        if (isinstance(node, ast.Call) and held
                and not _is_cancellable_helper(node)):
            m = self._blocking_kind(node)
            if m is not None:
                out.append(self.finding(
                    ctx, node,
                    f"{m} can block while the TPU semaphore is "
                    "held; wrap the wait in TpuSemaphore.yielded() "
                    "or use a cancellable watchdog wait"))
        for child in ast.iter_child_nodes(node):
            self._walk(ctx, child, held, out)

    def _blocking_kind(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name) and f.id == "sleep":
            return "sleep()"
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr not in self._BLOCK_ATTRS:
            return None
        recv = _receiver(f) or ""
        last = recv.split(".")[-1] if recv else ""
        if f.attr == "get":
            if last[:1].isupper():          # Singleton.get()
                return None
            if not _queue_style_get(call):  # dict.get(key)
                return None
            return ".get()"
        if f.attr == "join":
            if call.args:                   # sep.join(...) / path.join
                return None
            return ".join()"
        if f.attr == "sleep" and last not in ("time", ""):
            return None
        return f".{f.attr}()"


# ---------------------------------------------------------------------------
class UnboundedWaitRule(Rule):
    """Rule 3 (PR 4): every indefinite wait in the engine must be a
    bounded poll + CancelToken check — a `wait()`/`get()`/`join()`/
    `acquire()` with no timeout, or a socket `recv` in a function with
    no cancellation/timeout discipline, can outlive its query and
    either hang the process or leak the thread past watchdog
    cancellation."""

    rule_id = "unbounded-wait"
    doc = ("wait()/get()/join()/acquire() need a timeout (bounded "
           "poll + CancelToken check); recv needs settimeout or "
           "check_cancelled in scope")

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        self._walk(ctx, ctx.tree, guarded=False, out=out)
        return out

    @staticmethod
    def _fn_guards_recv(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                d = dotted(n.func) or ""
                leaf = d.split(".")[-1]
                if leaf in ("check_cancelled", "settimeout"):
                    return True
        return False

    def _walk(self, ctx, node, guarded, out):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            guarded = guarded or self._fn_guards_recv(node)
        elif isinstance(node, ast.Call):
            m = self._unbounded_kind(node, guarded)
            if m is not None:
                out.append(self.finding(
                    ctx, node,
                    f"{m} — every indefinite wait must be a "
                    "bounded poll + CancelToken check (see "
                    "utils.watchdog.cancellable_wait/"
                    "cancellable_sleep)"))
        for child in ast.iter_child_nodes(node):
            self._walk(ctx, child, guarded, out)

    def _unbounded_kind(self, call: ast.Call,
                        guarded: bool) -> Optional[str]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        recv = _receiver(f) or ""
        last = recv.split(".")[-1] if recv else ""
        a = f.attr
        no_args = not call.args and not call.keywords
        if a == "wait":
            to = call.args[0] if call.args else _kw(call, "timeout")
            if to is None and no_args:
                return ".wait() without a timeout"
            if (isinstance(to, ast.Constant) and to.value is None):
                return ".wait(None) is indefinite"
            return None
        if a == "join" and no_args:
            return ".join() without a timeout"
        if a == "get":
            if last[:1].isupper():
                return None
            if _queue_style_get(call):
                return ".get() without a timeout"
            return None
        if a == "acquire":
            if _kw(call, "timeout") is not None or call.args:
                return None
            blk = _kw(call, "blocking")
            if (isinstance(blk, ast.Constant) and blk.value is False):
                return None
            if no_args or blk is not None:
                return ".acquire() without a timeout"
            return None
        if a == "recv" and not guarded:
            return (".recv() in a function with neither settimeout "
                    "nor check_cancelled")
        return None


# ---------------------------------------------------------------------------
_CONF_KEY_RE = re.compile(r"^spark\.rapids\.[A-Za-z0-9_.]+$")


class ConfDisciplineRule(Rule):
    """Rule 4 (PR 2's captured-conf bug class, closed at the resolver
    in PR 6): (a) every `spark.rapids.*` string literal must be a key
    registered in config.py — an unregistered literal is a typo'd or
    undocumented conf that silently resolves to its hardcoded default;
    (b) plan/ node constructors and class bodies must not resolve
    confs (`get_active_conf`) — conf values captured at plan build
    leak one session's settings into another's execution (the q15
    f32/f64 mismatch); resolve at execute_partitions/kernel-build
    time instead."""

    rule_id = "conf-discipline"
    doc = ("spark.rapids.* literals must be registered in config.py; "
           "plan/ constructors must not resolve confs")

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        if not ctx.relpath.endswith("spark_rapids_tpu/config.py"):
            self._check_literals(ctx, ctx.tree, out)
        if ctx.in_package("plan"):
            self._check_plan_init(ctx, out)
        return out

    def _check_literals(self, ctx, node, out, in_fstring=False):
        for child in ast.iter_child_nodes(node):
            if (isinstance(child, ast.Constant)
                    and isinstance(child.value, str)
                    and not in_fstring
                    and _CONF_KEY_RE.match(child.value)
                    and child.value not in ctx.conf_keys):
                out.append(self.finding(
                    ctx, child,
                    f"conf key '{child.value}' is not registered in "
                    "config.py — register it with conf(...) so it is "
                    "typed, documented, and covered by the configs.md "
                    "drift gate"))
            self._check_literals(
                ctx, child, out,
                in_fstring or isinstance(child, ast.JoinedStr))

    def _check_plan_init(self, ctx, out):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if stmt.name not in ("__init__", "__post_init__"):
                        continue
                for call in ast.walk(stmt):
                    if (isinstance(call, ast.Call)
                            and (dotted(call.func) or "")
                            .split(".")[-1] == "get_active_conf"):
                        out.append(self.finding(
                            ctx, call,
                            "conf lookup in a plan/ node constructor "
                            "or class body: confs must resolve at "
                            "execution time (execute_partitions / "
                            "kernel build), never plan build — the "
                            "PR 2 captured-conf bug class"))


# ---------------------------------------------------------------------------
class CompileUnderLockRule(Rule):
    """Rule 5 (PR 2/7): XLA trace/compile runs seconds-to-minutes, so
    it must never happen inside a `with <lock>:` body — KernelCache's
    single-flight path exists precisely so concurrent builders wait on
    an Event while the compile runs OUTSIDE the lock.  A jit (or a
    KernelCache build, which may compile) under a lock serializes
    every other query behind one compile."""

    rule_id = "compile-under-lock"
    doc = ("no jax.jit / kernel build inside a 'with lock:' body — "
           "compile outside the lock (KernelCache single-flight)")

    _COMPILE_ATTRS = {"jit", "pallas_call", "get_or_build",
                      "_build_watched"}

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        self._walk(ctx, ctx.tree, locked=False, out=out)
        return out

    @staticmethod
    def _is_lock_expr(expr: ast.AST) -> bool:
        d = dotted(expr)
        if d is None:
            return False
        last = d.split(".")[-1].lower()
        return "lock" in last or last == "_cv"

    def _walk(self, ctx, node, locked, out):
        if isinstance(node, ast.With):
            locked = locked or any(
                self._is_lock_expr(i.context_expr)
                for i in node.items)
            for b in node.body:
                self._walk(ctx, b, locked, out)
            return
        if isinstance(node, ast.Call) and locked:
            d = dotted(node.func) or ""
            leaf = d.split(".")[-1]
            if leaf in self._COMPILE_ATTRS:
                out.append(self.finding(
                    ctx, node,
                    f"{leaf}() inside a 'with lock:' body — XLA "
                    "compiles run seconds-to-minutes; compile "
                    "outside the lock (see KernelCache's "
                    "single-flight path)"))
        for child in ast.iter_child_nodes(node):
            self._walk(ctx, child, locked, out)


# ---------------------------------------------------------------------------
class CollectiveDisciplineRule(Rule):
    """Rule 6 (PR 11/12, the mesh lanes): a mesh collective
    (`lax.all_to_all` / `psum` / `all_gather` / `ppermute`) blocks
    EVERY participant when one goes dark, so each dispatch must run
    under the collective-class watchdog (`watched_collective`,
    parallel/collective_exchange.py) — which also feeds the movement
    ledger's collective edge.  A call site is sanctioned when it is
    (a) lexically inside a `watched_collective(...)` argument (the
    dispatch thunk), or (b) inside an SPMD body registered with the
    watchdog by construction: a function passed to `shard_map`, any
    function it (transitively, same file) calls, or a function nested
    inside one — those run INSIDE a dispatch the caller already
    watches.  Anything else is a naked collective: a hang there is
    invisible to the watchdog and unaccounted by the ledger."""

    rule_id = "collective-discipline"
    doc = ("lax.all_to_all/psum/all_gather/ppermute must run under "
           "watched_collective or inside a shard_map/SPMD body")

    _COLLECTIVES = {"all_to_all", "psum", "all_gather", "ppermute"}

    def check(self, ctx: FileContext) -> list[Finding]:
        defs: dict[str, list] = {}          # name -> def nodes
        calls_in: dict[int, set] = {}       # id(def) -> called names
        nested_in: dict[int, set] = {}      # id(def) -> nested def names
        seeds: set = set()                  # shard_map/watched fn names
        sites: list = []                    # (node, def-name chain, watched?)

        def leaf(call) -> str:
            d = dotted(call.func)
            return d.split(".")[-1] if d else ""

        def walk(node, fn_stack, watched):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                for f in fn_stack:
                    nested_in.setdefault(id(f), set()).add(node.name)
                fn_stack = fn_stack + [node]
            elif isinstance(node, ast.Call):
                name = leaf(node)
                if name in ("shard_map", "watched_collective"):
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        if isinstance(a, ast.Name):
                            seeds.add(a.id)
                    if name == "watched_collective":
                        # the dispatch thunk (usually a lambda) and
                        # everything lexically inside it is watched
                        watched = True
                elif name in self._COLLECTIVES:
                    sites.append((node, [f.name for f in fn_stack],
                                  watched))
                if fn_stack and name:
                    calls_in.setdefault(id(fn_stack[-1]),
                                        set()).add(name)
            for child in ast.iter_child_nodes(node):
                walk(child, fn_stack, watched)

        walk(ctx.tree, [], False)

        # closure: a seed body sanctions everything it calls (same
        # file) and every function nested inside it
        sanctioned: set = set()
        work = list(seeds)
        while work:
            name = work.pop()
            if name in sanctioned:
                continue
            sanctioned.add(name)
            for d in defs.get(name, []):
                for callee in calls_in.get(id(d), ()):
                    if callee in defs and callee not in sanctioned:
                        work.append(callee)
                for nested in nested_in.get(id(d), ()):
                    if nested not in sanctioned:
                        work.append(nested)

        out: list[Finding] = []
        for node, chain, watched in sites:
            if watched or any(n in sanctioned for n in chain):
                continue
            out.append(self.finding(
                ctx, node,
                f"{leaf_name(node)} is a mesh collective outside "
                "watched_collective and outside any shard_map/SPMD "
                "body — a wedged dispatch here blocks every mesh "
                "participant invisibly; wrap the dispatch in "
                "parallel.collective_exchange.watched_collective"))
        return out


def leaf_name(call: ast.Call) -> str:
    d = dotted(call.func)
    return (d.split(".")[-1] + "()") if d else "<collective>()"


ALL_RULES = [HostSyncRule(), BlockingWhileHoldingRule(),
             UnboundedWaitRule(), ConfDisciplineRule(),
             CompileUnderLockRule(), CollectiveDisciplineRule()]


def rule_ids() -> list[str]:
    return [r.rule_id for r in ALL_RULES]
