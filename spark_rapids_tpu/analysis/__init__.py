"""tpulint: AST-based static enforcement of the engine's invariants.

PRs 1-10 accumulated hard-won runtime disciplines — the host-sync diet
(PR 2), never block while holding the TPU semaphore without
`yielded()` (PR 2/6), every indefinite wait is a bounded poll + cancel
check (PR 4), confs resolve at execution time rather than plan build
(the PR 2 captured-conf bug class), compile outside the lock (PR 2/7).
Until now they were enforced only by soak tests that catch violations
probabilistically; this package makes each one a merge-blocking static
check (Theseus's "engineer the discipline in" applied to correctness
tooling).  See docs/dev-guide.md for the rule catalogue.

Usage:  python scripts/lint.py [--format json] [paths...]
"""
from spark_rapids_tpu.analysis.core import (  # noqa: F401
    Finding, LintResult, load_baseline, run_lint, write_baseline)
from spark_rapids_tpu.analysis.reporters import (  # noqa: F401
    format_json, format_text, summary_line)
from spark_rapids_tpu.analysis.rules import ALL_RULES, rule_ids  # noqa: F401
