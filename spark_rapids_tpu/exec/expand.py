"""Expand and Generate operators (reference `GpuExpandExec.scala` 202 LoC,
`GpuGenerateExec.scala` 194 LoC).

ExpandExec: each input row emits one output row per projection list —
the grouping-sets/rollup/cube building block.  On TPU the expansion is a
static-fan-out gather: output capacity = capacity * num_projections, and
every projection's expressions evaluate over the same input batch (one
fused kernel).

GenerateExec: explode over an inline array of expressions
(`explode(array(e1..eN))`, the pattern the reference accelerates at this
snapshot — there is no first-class array column type in the v0 matrix).
"""
from __future__ import annotations

from typing import Iterator, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exec.base import (
    TpuExec, UnaryExecBase, batch_signature, make_eval_context)
from spark_rapids_tpu.exprs.base import Expression, output_name
from spark_rapids_tpu.utils import metrics as M


class ExpandExec(UnaryExecBase):
    def __init__(self, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str], child: TpuExec):
        super().__init__(child)
        child_schema = child.output_schema()
        self.projections = [list(p) for p in projections]
        self._bound = [[e.bind(child_schema) for e in p]
                       for p in self.projections]
        dts = [b.data_type(child_schema) for b in self._bound[0]]
        for p in self._bound[1:]:
            for i, b in enumerate(p):
                dt = b.data_type(child_schema)
                if dt != dts[i]:
                    dts[i] = T.common_type(dts[i], dt)
        self._schema = T.Schema(tuple(
            T.Field(n, dt) for n, dt in zip(names, dts)))

    @property
    def coalesce_after(self) -> bool:
        return True

    def output_schema(self):
        return self._schema

    def describe(self):
        return f"ExpandExec({len(self.projections)} projections)"

    def cache_scope(self):
        from spark_rapids_tpu.exprs.base import fingerprint
        return (fingerprint(self._bound), fingerprint(self._schema))

    def _kernel(self, batch: ColumnarBatch):
        key = ("expand", batch_signature(batch))

        def build():
            cap = batch.capacity
            nproj = len(self._bound)
            out_cap = cap * nproj

            @jax.jit
            def kernel(columns, num_rows):
                ctx = make_eval_context(columns, cap, num_rows)
                # evaluate every projection, then interleave rows:
                # output row r*nproj + p = projection p of input row r
                per_proj = []
                for p in self._bound:
                    cols = []
                    for e, f in zip(p, self._schema.fields):
                        v = e.eval(ctx)
                        from spark_rapids_tpu.exprs.base import promote
                        if not f.dtype.is_string and v.dtype != f.dtype:
                            v = promote(v, f.dtype)
                        cols.append(v)
                    per_proj.append(cols)
                k = jnp.arange(out_cap)
                src_row = k // nproj
                src_proj = k % nproj
                valid = src_row < num_rows
                out_cols = []
                for ci, f in enumerate(self._schema.fields):
                    if f.dtype.is_string:
                        from spark_rapids_tpu.columnar.vector import \
                            _pad_chars
                        cc = max(per_proj[p][ci].char_cap
                                 for p in range(nproj))
                        vs = [_pad_chars(per_proj[p][ci], cc)
                              for p in range(nproj)]
                        data = jnp.stack([v.data for v in vs])
                        vald = jnp.stack([v.validity for v in vs])
                        lens = jnp.stack([v.lengths for v in vs])
                        d = data[src_proj, jnp.where(valid, src_row, 0)]
                        va = vald[src_proj,
                                  jnp.where(valid, src_row, 0)] & valid
                        ln = lens[src_proj, jnp.where(valid, src_row, 0)]
                        out_cols.append(ColumnVector(
                            f.dtype, d, va, jnp.where(valid, ln, 0)))
                    else:
                        data = jnp.stack(
                            [per_proj[p][ci].data for p in range(nproj)])
                        vald = jnp.stack(
                            [per_proj[p][ci].validity
                             for p in range(nproj)])
                        d = data[src_proj, jnp.where(valid, src_row, 0)]
                        va = vald[src_proj,
                                  jnp.where(valid, src_row, 0)] & valid
                        out_cols.append(ColumnVector(f.dtype, d, va))
                return out_cols

            return kernel

        return self.kernels.get_or_build(
            key, build, meta=self.kp_meta("expand"))

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        nproj = len(self._bound)
        for batch in batches:
            batch = batch.dense()
            with self.metrics.timed(M.TOTAL_TIME):
                kern = self._kernel(batch)
                cols = kern(batch.columns, batch.num_rows_i32)
                rows = (batch.num_rows * nproj if batch.num_rows_known
                        else batch.num_rows_i32 * nproj)
                out = ColumnarBatch(self._schema, list(cols),
                                    rows, batch.checks)
                self.update_output_metrics(out)
            yield out


class GenerateExec(UnaryExecBase):
    """explode(array(e1..eN)) [+ posexplode]: each row emits N rows with
    (pos?, value); `outer=True` emits one null row for empty arrays (not
    representable here since N is static and > 0)."""

    def __init__(self, element_exprs: Sequence[Expression],
                 child: TpuExec, include_pos: bool = False,
                 value_name: str = "col", retained: Sequence[str] = None):
        super().__init__(child)
        child_schema = child.output_schema()
        self.include_pos = include_pos
        self._bound = [e.bind(child_schema) for e in element_exprs]
        dt = self._bound[0].data_type(child_schema)
        for b in self._bound[1:]:
            d2 = b.data_type(child_schema)
            if d2 != dt:
                dt = T.common_type(dt, d2)
        self.retained = list(retained) if retained is not None else \
            list(child_schema.names)
        fields = [child_schema.field(n) for n in self.retained]
        if include_pos:
            fields.append(T.Field("pos", T.INT32))
        fields.append(T.Field(value_name, dt))
        self._schema = T.Schema(tuple(fields))
        # as an n-projection expand: projection p = retained + [p, e_p]
        from spark_rapids_tpu.exprs.base import AttributeReference, Literal
        projections = []
        for p, e in enumerate(element_exprs):
            proj = [AttributeReference(n) for n in self.retained]
            if include_pos:
                proj.append(Literal(p, T.INT32))
            proj.append(e)
            projections.append(proj)
        self._expand = ExpandExec(projections,
                                  [f.name for f in fields], child)

    @property
    def coalesce_after(self) -> bool:
        return True

    def output_schema(self):
        return self._schema

    def describe(self):
        return (f"GenerateExec(explode[{len(self._bound)}], "
                f"pos={self.include_pos})")

    def process_partition(self, batches):
        return self._expand.process_partition(batches)
