"""Batch coalescing (reference `GpuCoalesceBatches.scala`): concatenate
small batches up to a CoalesceGoal — TargetSize(bytes) or
RequireSingleBatch.  On TPU this additionally *re-buckets* capacity, which
is what keeps the kernel compile cache small after filters shrink batches.
"""
from __future__ import annotations

from typing import Iterator, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.vector import bucket_capacity
from spark_rapids_tpu.exec.base import (
    CoalesceGoal, RequireSingleBatch, TargetSize, TpuExec, UnaryExecBase)
from spark_rapids_tpu.utils import metrics as M


#: a lazy (deferred-selection) batch passes through coalesce un-sliced
#: while its capacity is within this multiple of the row cap — bounded
#: so row-exploding join/expand outputs still slice (their downstream
#: compile cost is what the split pipeline contains)
LAZY_PASS_MULT = 8


def coalesce_iterator(batches: Iterator[ColumnarBatch],
                      goal: CoalesceGoal,
                      schema: T.Schema,
                      metrics,
                      max_rows: int = None) -> Iterator[ColumnarBatch]:
    """The AbstractGpuCoalesceIterator analog.  `max_rows` (resolved by
    the caller at plan time — the draining thread may not carry the
    session conf) caps emitted batch row counts for TargetSize goals.

    Pass-through EXCEPTION to the row cap: a LAZY batch (row count
    still a device scalar) whose capacity is within `LAZY_PASS_MULT` x
    `max_rows` is emitted WHOLE — uncounted and un-sliced — because its
    memory is already allocated (slicing duplicates, not frees) and the
    count sync (~150ms tunnel round trip) would dominate post-filter
    pipelines.  Consumers that size work by rows must therefore treat
    batch CAPACITY as the bound for lazy batches; the exchange's
    oversized-batch shard guard (shuffle/exchange.py) does exactly
    that so an up-to-8x lazy batch cannot land whole on one chip."""
    if isinstance(goal, RequireSingleBatch):
        got = [b for b in batches if b.maybe_nonempty()]
        if not got:
            from spark_rapids_tpu.columnar.batch import empty_batch
            yield empty_batch(schema)
            return
        out = concat_batches(got) if len(got) > 1 else _rebucket(got[0])
        metrics.add(M.NUM_OUTPUT_BATCHES, 1)
        metrics.add(M.NUM_OUTPUT_ROWS, out._rows)
        yield out
        return

    target = goal.bytes if isinstance(goal, TargetSize) else 1 << 31
    if max_rows is None:
        from spark_rapids_tpu import config as C
        max_rows = C.get_active_conf()[C.MAX_BATCH_ROWS]
    pending: list[ColumnarBatch] = []
    pending_bytes = 0
    pending_rows = 0
    for big in batches:
        metrics.add(M.NUM_INPUT_BATCHES, 1)
        metrics.add(M.NUM_INPUT_ROWS, big._rows)
        if not big.maybe_nonempty():
            continue
        # row cap keeps capacities inside the bounded bucket set so
        # downstream kernels reuse compiled shapes; oversized batches
        # (row-expanding joins/expand) are sliced, not forwarded
        # lazy slicing: materializing every slice up front would hold a
        # second full copy of an oversized batch on device at once
        # lazy batches are sized by CAPACITY (a safe upper bound on
        # rows) so accumulation stays sync-free.  A lazy batch whose
        # capacity moderately exceeds the row cap passes through WHOLE:
        # its memory is already allocated (slicing duplicates, not
        # frees), every exec consumes deferred-selection batches, and
        # the sync (~150ms tunnel round trip) + two gather rounds per
        # batch dominated post-filter pipelines (q27 paid 13 syncs +
        # ~450ms here).  Only a cap past LAZY_PASS_MULT x the row cap —
        # the row-exploding join/expand shapes whose downstream compile
        # cost the bounded split pipeline exists to contain — pays the
        # count sync and slices.
        lazy_bounded = (not big.num_rows_known and
                        big.capacity <= LAZY_PASS_MULT * max_rows)
        # reading num_rows on a lazy batch is a count SYNC — only the
        # must-slice shape (lazy + cap past the pass-through bound) pays
        # it; per-piece accounting below recomputes its own size
        big_rows = big.num_rows if not lazy_bounded else None
        if lazy_bounded or big_rows <= max_rows:
            pieces = (big,)
        else:
            # densify ONCE before slicing: ColumnarBatch.slice on a
            # sparse batch would re-run the full-capacity compaction
            # gather per slice
            dense_big = big.dense()
            pieces = (dense_big.slice(lo, min(max_rows,
                                              dense_big.num_rows - lo))
                      for lo in range(0, dense_big.num_rows, max_rows))
        for b in pieces:
            b_rows = (b.num_rows if b.num_rows_known else b.capacity)
            est = _row_bytes(b) * b_rows
            if pending and (pending_bytes + est > target or
                            pending_rows + b_rows > max_rows):
                yield _emit(pending, metrics)
                pending, pending_bytes, pending_rows = [], 0, 0
            pending.append(b)
            pending_bytes += est
            pending_rows += b_rows
    if pending:
        yield _emit(pending, metrics)


def _row_bytes(b: ColumnarBatch) -> int:
    total = 0
    for f, c in zip(b.schema.fields, b.columns):
        if f.dtype.is_string:
            total += c.char_cap + 5
        else:
            total += f.dtype.storage_dtype.itemsize + 1
    return max(total, 1)


def _rebucket(b: ColumnarBatch) -> ColumnarBatch:
    """Shrink an over-padded batch into its tight bucket (e.g. after a
    selective filter) so downstream kernels compile for a smaller shape."""
    if not b.num_rows_known:
        return b  # tightening needs the count; not worth a ~150ms sync
    tight = bucket_capacity(b.num_rows)
    if tight < b.capacity:
        return b.with_capacity(tight)
    return b


def _emit(pending: list[ColumnarBatch], metrics) -> ColumnarBatch:
    # sparse_ok: the single-batch pass-through path already hands
    # deferred-selection batches to the same downstream consumers, so
    # the merged batch may stay sparse too (no per-input dense gathers)
    out = concat_batches(pending, sparse_ok=True) if len(pending) > 1 \
        else _rebucket(pending[0])
    metrics.add(M.NUM_OUTPUT_BATCHES, 1)
    metrics.add(M.NUM_OUTPUT_ROWS, out._rows)
    return out


class CoalesceBatchesExec(UnaryExecBase):
    """Reference GpuCoalesceBatches exec node, inserted by the transition
    pass per each operator's childrenCoalesceGoal."""

    def __init__(self, goal: CoalesceGoal, child: TpuExec,
                 max_rows: "Optional[int]" = None):
        super().__init__(child)
        self.goal = goal
        from spark_rapids_tpu import config as C
        # the session conf's cap is passed by the transition pass;
        # resolved at plan time because the draining thread may not
        # carry the conf
        self._max_rows = (max_rows if max_rows is not None
                          else C.get_active_conf()[C.MAX_BATCH_ROWS])

    def output_schema(self):
        return self.child.output_schema()

    def describe(self):
        return f"CoalesceBatchesExec({self.goal})"

    def process_partition(self, batches):
        # coalesce is a pipeline break: its producer side (the child's
        # batches + the concat/re-bucket dispatches) runs ahead on a
        # prefetch thread while the downstream consumer computes.  The
        # conf is resolved HERE (execution time, inside collect()'s
        # session) — never at plan build, where the session conf is not
        # installed and a captured default would leak to the producer
        # thread and flip conf-gated kernel lanes (observed as q15's
        # f32-vs-f64 aggregation mismatch).
        from spark_rapids_tpu.exec.pipeline import maybe_prefetch
        return maybe_prefetch(
            coalesce_iterator(batches, self.goal, self.output_schema(),
                              self.metrics, max_rows=self._max_rows),
            label="coalesce", metrics=self.metrics)
