"""Hash aggregation (reference `aggregate.scala:312` GpuHashAggregateExec).

The reference runs cuDF groupby per batch, then concatenates partial
results and re-merges until one batch remains.  The TPU version keeps the
same two-phase shape with sort-based segments:

  per input batch : sort rows by group keys -> segment ids -> update aggs
  on exhaustion   : concat partials -> sort -> merge aggs -> evaluate

Modes mirror Spark: Partial (update only, emits keys+intermediates),
Final (merge intermediates, evaluate), Complete (update+evaluate in one
node — used for single-stage local plans).  The reduction path (no group
keys) skips the sort entirely and uses masked whole-batch reductions.
"""
from __future__ import annotations

import dataclasses
import enum
import logging
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.vector import (ColumnVector,
                                              bucket_capacity)
from spark_rapids_tpu.exec.base import (
    SchemaOnlyExec as _SchemaOnly, TpuExec, UnaryExecBase,
    batch_signature, make_eval_context)
from spark_rapids_tpu.exprs.aggregates import (
    AggAlias, AggContext, AggregateFunction)
from spark_rapids_tpu.exprs.base import Expression, output_name
from spark_rapids_tpu.ops.sort_encode import (hash_sort_bounds,
                                              sort_with_bounds,
                                              wide_key_set)
from spark_rapids_tpu.utils import checks as CK
from spark_rapids_tpu.utils import metrics as M

log = logging.getLogger("spark_rapids_tpu.aggregate")


class AggMode(enum.Enum):
    PARTIAL = "partial"
    FINAL = "final"
    COMPLETE = "complete"


def _to_alias(a, i: int) -> AggAlias:
    if isinstance(a, AggAlias):
        return a
    return AggAlias(a, f"agg{i}")


class HashAggregateExec(UnaryExecBase):
    def __init__(self, group_exprs: Sequence[Expression],
                 aggregates: Sequence,
                 child: TpuExec,
                 mode: AggMode = AggMode.COMPLETE,
                 pre_stage=None):
        super().__init__(child)
        self.mode = mode
        self.group_exprs = list(group_exprs)
        self.aggregates = [_to_alias(a, i) for i, a in enumerate(aggregates)]
        #: whole-stage fusion (plan/fusion.py ComposedStage): a fused
        #: project/filter chain evaluated INSIDE every update-lane
        #: kernel before grouping — group/input expressions bind
        #: against the stage's output schema while batches arrive in
        #: the raw child schema.  Update/complete phases only (a FINAL
        #: merge reads positional intermediates, never raw inputs).
        self._pre_stage = pre_stage
        self._fused_event_done = False
        if pre_stage is not None:
            assert mode != AggMode.FINAL, \
                "pre_stage fusion applies to update lanes only"
        child_schema = (pre_stage.schema if pre_stage is not None
                        else child.output_schema())
        self._child_schema = child_schema
        self._bound_groups = [e.bind(child_schema) for e in self.group_exprs]
        self._group_fields = tuple(
            T.Field(output_name(e, i), b.data_type(child_schema))
            for i, (e, b) in enumerate(
                zip(self.group_exprs, self._bound_groups)))

        self._funcs = [a.func for a in self.aggregates]
        self._inter_offsets = []
        if mode == AggMode.FINAL:
            # child emits keys + intermediates; resolve types positionally
            # (original input columns are gone from the partial schema)
            off = len(self._group_fields)
            self._inter_types = []
            for f in self._funcs:
                n = f.num_intermediates
                self._inter_offsets.append((off, off + n))
                self._inter_types.append(tuple(
                    child_schema.fields[i].dtype for i in range(off, off + n)))
                off += n
        else:
            self._bound_inputs = [
                [e.bind(child_schema) for e in f.input_exprs()]
                for f in self._funcs]
            self._inter_types = [
                tuple(f.intermediate_types(child_schema))
                for f in self._funcs]
            off = len(self._group_fields)
            for ts in self._inter_types:
                self._inter_offsets.append((off, off + len(ts)))
                off += len(ts)

        # output schema
        fields = list(self._group_fields)
        if mode == AggMode.PARTIAL:
            for a, ts in zip(self.aggregates, self._inter_types):
                for j, it in enumerate(ts):
                    fields.append(T.Field(f"{a.name}#{j}", it))
        elif mode == AggMode.FINAL:
            for a, ts in zip(self.aggregates, self._inter_types):
                fields.append(
                    T.Field(a.name, a.func.result_from_intermediates(ts)))
        else:
            for a in self.aggregates:
                fields.append(
                    T.Field(a.name, a.func.result_type(child_schema)))
        self._schema = T.Schema(tuple(fields))
        # static qualification for the dictionary fast path, computed
        # once (None = never applicable for this exec)
        self._dict_qual = self._dict_plan()
        self._dict_range_misses = 0
        # banded windowed-MXU lane: every aggregate must be expressible
        # as per-group f32 sums (keys are unrestricted — reps travel as
        # first-row-index limbs)
        self._banded_qual = all(
            type(f).__name__ in ("Sum", "Count", "Average")
            for f in self._funcs)
        # padded dictionary width (int for a single key; tuple of
        # per-key pads for the composite multi-key path), sized from a
        # one-time first-batch range probe (None until probed)
        self._dict_gpad: Optional[object] = None

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self):
        keys = ", ".join(f.name for f in self._group_fields)
        aggs = ", ".join(a.name for a in self.aggregates)
        fused = "" if self._pre_stage is None else \
            f", fused=[{self._pre_stage.describe_ops()}]"
        return (f"HashAggregateExec(mode={self.mode.value}, "
                f"keys=[{keys}], aggs=[{aggs}]{fused})")

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe()
        if self._pre_stage is not None:
            # EXPLAIN prints the fusion group's member operators
            for m in self._pre_stage.members:
                s += "\n" + "  " * (indent + 1) + "* " + m.describe()
        for c in self._children:
            s += "\n" + c.tree_string(indent + 1)
        return s

    @property
    def fused_members(self):
        """(describe, MetricSet) per fused member op, for the
        EXPLAIN-with-metrics breakdown; empty when unfused."""
        if self._pre_stage is None:
            return []
        return [(m.describe(), m.metrics)
                for m in self._pre_stage.members]

    def cache_scope(self):
        from spark_rapids_tpu.exprs.base import fingerprint
        return (self.mode.name, fingerprint(self._bound_groups),
                fingerprint(self._funcs),
                fingerprint(getattr(self, "_bound_inputs", None)),
                fingerprint(self._inter_types),
                fingerprint(self._child_schema),
                self._pre_stage.fingerprint()
                if self._pre_stage is not None else ("~",))

    def _make_ctx(self, columns, cap, num_rows, mask=None):
        """Kernel-trace eval context; with a fused pre-stage the raw
        child columns first flow through the composed project/filter
        DAG inside the SAME jit (plan/fusion.py eval_stage_ctx)."""
        ctx = make_eval_context(columns, cap, num_rows, mask)
        if self._pre_stage is not None:
            from spark_rapids_tpu.plan import fusion as FZ
            ctx = FZ.eval_stage_ctx(self._pre_stage, ctx)
        return ctx

    def _charge_pre_stage(self, t0: Optional[float]) -> None:
        """Fused-member metric/event bookkeeping per dispatched batch;
        the FIRST dispatch (trace + compile happen synchronously on a
        jit's first call) also emits the profiler's stage_fused
        event."""
        if self._pre_stage is None:
            return
        import time as _time
        for m in self._pre_stage.members:
            m.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
        if not self._fused_event_done and t0 is not None:
            self._fused_event_done = True
            from spark_rapids_tpu.utils import profile as P
            P.event(P.EV_STAGE_FUSED,
                    members=self._pre_stage.member_names()
                    + [type(self).__name__],
                    exprs=self._pre_stage.expr_count,
                    compile_ms=round(
                        (_time.perf_counter() - t0) * 1e3, 2))

    # -- kernels ------------------------------------------------------------
    #: past this many estimated packed sort words the grouping sort
    #: routes through the 2-word murmur3 hash lane — wide key sets
    #: (string groupers emit one 9-bit key per char position) would
    #: otherwise trace a sort chain whose XLA compile time and memory
    #: scale with total key WIDTH (TPC-DS q64's 15-key string grouper
    #: is ~100 words: minutes of compile, GBs of arena, per schema)
    #: alias of the shared routing threshold so both grouping
    #: lanes (aggregate group-by, window partition-by) tune together
    from spark_rapids_tpu.ops.sort_encode import \
        HASH_GROUP_MIN_WORDS as HASH_GROUP_MIN_WORDS

    def _use_hash_grouping(self, batch: ColumnarBatch) -> bool:
        # the deopt retry must produce guaranteed-valid results (there
        # is no second retry — see utils/checks.py), so it always takes
        # the lexicographic lane, like _compact_groups
        if getattr(self, "_hash_group_disabled", False) or CK.is_retrying():
            return False
        from spark_rapids_tpu import config as C
        if not C.get_active_conf()[C.HASH_GROUPING_ENABLED]:
            return False
        # with a fused pre-stage the batch carries the RAW child
        # columns, so ordinal-based column inspection would read the
        # wrong column — route through the dtype-only estimate
        return wide_key_set(self._bound_groups,
                            None if self._pre_stage is not None
                            else batch,
                            self._child_schema,
                            self.HASH_GROUP_MIN_WORDS)

    #: cap bound for the banded lane: first-row indices travel as two
    #: 11-bit f32 limbs (exact one-hot sums), covering rows < 2^22;
    #: f32-exact group counts need < 2^24 anyway
    BANDED_MAX_CAP = 1 << 22

    def _banded_float_measures(self, phase: str) -> bool:
        """True when this exec+phase would put FLOATING values through
        the f32 banded accumulator (needs the variableFloatAgg
        tolerance; integral measures ride the exact-or-deopt
        certificate instead)."""
        if phase == "merge":
            return any(t.is_floating for ts in self._inter_types
                       for t in ts)
        return any(e.data_type(self._child_schema).is_floating
                   for bins in self._bound_inputs for e in bins)

    def _use_banded(self, batch: ColumnarBatch, phase: str) -> bool:
        if not self._banded_qual or \
                getattr(self, "_banded_disabled", False):
            return False
        if CK.is_retrying():
            # the deopt retry must be guaranteed-valid; certificate
            # lanes cannot be the last resort
            return False
        from spark_rapids_tpu import config as C
        conf = C.get_active_conf()
        if not conf[C.BANDED_GROUPBY_ENABLED]:
            return False
        cap = batch.capacity
        if cap % 128 or cap > self.BANDED_MAX_CAP:
            return False
        if self._banded_float_measures(phase) and \
                not conf[C.VARIABLE_FLOAT_AGG]:
            return False
        return True

    def _disable_banded(self) -> None:
        self._banded_disabled = True
        me = getattr(self, "_merge_exec", None)
        if me is not None:
            me._banded_disabled = True

    def _register_banded_check(self, cert, checks: tuple) -> tuple:
        """Deferred exactness deopt for the banded lane (None = lane
        not taken, nothing to check)."""
        return CK.register_deopt(cert,
                                 f"bandedGroupby[exec {self.exec_id}]",
                                 self._disable_banded, checks)

    def _disable_hash_grouping(self) -> None:
        # a 64-bit murmur3 collision between two distinct key tuples
        # (detected exactly by the in-kernel boundary/hash cross-check)
        # deopts this exec to the lexicographic lane for good
        self._hash_group_disabled = True

    def _groupby_kernel(self, batch: ColumnarBatch, phase: str,
                        wcap: Optional[int] = None):
        """phase: 'update' (raw inputs) or 'merge' (intermediates).
        `wcap`: compact GROUP width — when set, every per-group gather
        and output column runs at wcap instead of full row capacity
        (a 2M-row batch with 1K groups spent ~1/3 of its kernel on
        full-capacity group materialization), and the kernel reports
        `num_groups > wcap` as a deferred excess flag (same
        escalate-and-retry contract as _compact_groups)."""
        use_hash = self._use_hash_grouping(batch)
        use_banded = self._use_banded(batch, phase)
        key = ("agg", phase, use_hash, use_banded, wcap,
               batch_signature(batch))
        kp_members = (self._pre_stage.member_names()
                      if self._pre_stage is not None else None)

        def build():
            cap = batch.capacity
            out_cap = wcap if wcap is not None else cap
            bound_groups = self._bound_groups
            funcs = self._funcs

            @jax.jit
            def kernel(columns, num_rows, mask=None):
                ctx = self._make_ctx(columns, cap, num_rows, mask)
                keys = [e.eval(ctx) for e in bound_groups]
                if use_hash:
                    perm, sorted_valid, bounds, collision = \
                        hash_sort_bounds([(k, True, True) for k in keys],
                                         ctx.row_mask)
                else:
                    perm, sorted_valid, bounds, _ = sort_with_bounds(
                        [(k, True, True) for k in keys], ctx.row_mask)
                    collision = None
                seg_ids = jnp.cumsum(bounds.astype(jnp.int32)) - 1
                num_groups = bounds.sum().astype(jnp.int32)
                excess = (num_groups > out_cap) if wcap is not None \
                    else None
                grp_valid = jnp.arange(out_cap) < num_groups

                if phase == "update":
                    inputs_per_f = [
                        [e.eval(ctx) for e in bins]
                        for bins in self._bound_inputs]
                    flat = [v for ins in inputs_per_f for v in ins]
                else:
                    inputs_per_f = [
                        [ctx.columns[i] for i in range(lo, hi)]
                        for lo, hi in self._inter_offsets]
                    flat = [v for ins in inputs_per_f for v in ins]
                # grouped-stream reorder: ALL 4-byte value streams plus
                # the packed validity word ride ONE stacked gather and
                # f64 streams another (random access costs ~70ns per
                # ROW, not per byte — a 4-measure agg paid 4 gathers
                # here before)
                from spark_rapids_tpu.columnar.vector import \
                    gather_columns_grouped
                sorted_flat = gather_columns_grouped(flat, perm,
                                                     sorted_valid)
                it = iter(sorted_flat)
                sorted_per_f = [[next(it) for _ in ins]
                                for ins in inputs_per_f]

                if use_banded:
                    out_cols, first_idx, cert = self._banded_aggregate(
                        phase, sorted_per_f, sorted_valid, bounds,
                        seg_ids, grp_valid, cap, out_cap)
                    rep_idx = jnp.take(perm, first_idx, mode="clip")
                    key_cols = [k.gather(rep_idx, grp_valid)
                                for k in keys]
                    return (key_cols + out_cols, num_groups, collision,
                            excess, cert)

                # group key representatives: first row of each segment
                from spark_rapids_tpu.ops.sort_encode import \
                    masked_positions
                first_idx = masked_positions(bounds, out_cap,
                                             fill_value=cap - 1)
                # per-segment LAST sorted row: one before the next
                # segment's start; the last real segment (which also
                # absorbs trailing invalid rows' segment ids) ends at
                # cap-1 — aggregates fill invalid rows with identities
                nxt = jnp.concatenate(
                    [first_idx[1:],
                     jnp.full((1,), cap, first_idx.dtype)])
                ends = jnp.where(jnp.arange(out_cap) >= num_groups - 1,
                                 cap - 1, nxt - 1).astype(jnp.int32)
                actx = AggContext(seg_ids, cap, sorted_valid, bounds,
                                  ends, out_capacity=out_cap)

                out_cols = []
                # representatives via index COMPOSITION: one i32 gather
                # (perm at first_idx) + one gather per key column — the
                # sorted_keys detour re-gathered every key column at
                # full cap twice (random-access streams are the
                # dominant kernel cost at ~70ns/row on this chip)
                rep_idx = jnp.take(perm, first_idx, mode="clip")
                for k in keys:
                    out_cols.append(k.gather(rep_idx, grp_valid))

                # ONE cross-function segmented scan per round (each
                # function's operands batch into a shared _segscan —
                # a q1-shaped aggregate ran 8 separate 2M-row scan
                # dispatches at ~100ms each before)
                from spark_rapids_tpu.exprs.aggregates import \
                    run_agg_phase
                for outs in run_agg_phase(actx, funcs, sorted_per_f,
                                          phase):
                    out_cols.extend(
                        ColumnVector(o.dtype, o.data,
                                     o.validity & grp_valid,
                                     o.lengths) for o in outs)
                return out_cols, num_groups, collision, excess, None

            return kernel

        # update-lane kernels of a fused aggregate carry the composed
        # pre-stage's member names, so the kernel table attributes the
        # inlined project/filter work to this kernel too
        return self.kernels.get_or_build(
            key, build,
            meta=self.kp_meta(f"agg-{phase}", members=kp_members))

    def _banded_aggregate(self, phase, sorted_per_f, sorted_valid,
                          bounds, seg_ids, grp_valid, cap, out_cap):
        """Banded windowed-MXU aggregation over the sorted rows (see
        ops/grouped_window.py): every Sum/Count/Average measure —
        plus two 11-bit first-row-index limbs for key recovery —
        accumulates per group in ONE windowed kernel + merge matmul.
        Replaces masked_positions (a second full sort at high group
        counts), the segmented scans, and the full-width ends
        machinery.  Returns (agg columns, first_idx, cert_flag):
        cert_flag (device bool or None) reports an integral measure
        whose f32 accumulation may have rounded — the caller registers
        it as a deferred deopt (reference parity: cuDF hash groupby is
        exact; this lane is exact-or-retry)."""
        from spark_rapids_tpu.ops.grouped_window import window_group_sums
        from spark_rapids_tpu.ops.pallas_kernels import _on_tpu

        measures: list = []
        specs: list = []
        cert_ids: list = []

        def add(arr) -> int:
            measures.append(arr.astype(jnp.float32))
            return len(measures) - 1

        def value_measure(p: ColumnVector, ok):
            """f32 measure of a column's values, zeroed where not ok;
            prefers the i32 narrow shadow (64-bit elementwise is
            50-100x slower on this chip)."""
            if p.narrow is not None and not p.dtype.is_floating:
                raw = p.narrow
            else:
                raw = p.data
            v32 = raw.astype(jnp.float32)
            return jnp.where(ok, v32, jnp.float32(0))

        rv = sorted_valid
        for f, ins, its in zip(self._funcs, sorted_per_f,
                               self._inter_types):
            nm = type(f).__name__
            if nm == "Count":
                if phase == "merge":
                    (p,) = ins
                    ok = p.validity & rv
                    mi = add(value_measure(p, ok))
                    cert_ids.append(mi)  # counts are nonnegative
                    specs.append(("count", mi, None))
                else:
                    ok = rv if f.child is None \
                        else (ins[0].validity & rv)
                    specs.append(("count", add(ok), None))
            elif nm == "Sum":
                (p,) = ins
                ok = p.validity & rv
                mi = add(value_measure(p, ok))
                fi = add(ok)
                if not its[0].is_floating:
                    cert_ids.append(add(jnp.abs(measures[mi])))
                specs.append(("sum", mi, fi))
            else:  # Average: intermediates (f64 sum, i64 count)
                if phase == "merge":
                    s_p, c_p = ins
                    ok = rv
                    ms = add(value_measure(s_p, ok))
                    mc = add(value_measure(c_p, ok))
                    cert_ids.append(mc)
                    specs.append(("avg", ms, mc))
                else:
                    (p,) = ins
                    ok = p.validity & rv
                    mi = add(value_measure(p, ok))
                    fi = add(ok)
                    if not p.dtype.is_floating:
                        cert_ids.append(add(jnp.abs(measures[mi])))
                    specs.append(("avg", mi, fi))

        isf32 = bounds.astype(jnp.float32)
        iota = jnp.arange(cap, dtype=jnp.int32)
        li = add((iota & 2047).astype(jnp.float32) * isf32)
        hi = add((iota >> 11).astype(jnp.float32) * isf32)

        sums = window_group_sums(seg_ids, tuple(measures),
                                 out_cap=out_cap, capacity=cap,
                                 interpret=not _on_tpu())

        def col(i):
            return sums[:, i]

        # exactly one first-row hit per group -> limb sums are the limb
        # values themselves, exact in f32
        first_idx = jnp.clip(
            (col(li) + col(hi) * jnp.float32(2048)).astype(jnp.int32),
            0, cap - 1)
        cert = None
        if cert_ids:
            bad = jnp.zeros((), bool)
            thresh = jnp.float32(1 << 23)
            for ci in cert_ids:
                bad = bad | jnp.any(
                    jnp.where(grp_valid, col(ci), 0.0) >= thresh)
            cert = bad

        out_cols: list = []
        for (kind, mi, fi), its in zip(specs, self._inter_types):
            if kind == "count":
                c = jnp.round(col(mi)).astype(jnp.int64)
                out_cols.append(ColumnVector(T.INT64, c, grp_valid))
            elif kind == "sum":
                has = (col(fi) > 0) & grp_valid
                dt = its[0]
                if dt.is_floating:
                    data = col(mi).astype(jnp.float64)
                else:
                    data = jnp.round(col(mi)).astype(jnp.int64)
                out_cols.append(ColumnVector(dt, data, has))
            else:  # avg: (f64 sum, i64 count)
                out_cols.append(ColumnVector(
                    T.FLOAT64, col(mi).astype(jnp.float64), grp_valid))
                out_cols.append(ColumnVector(
                    T.INT64, jnp.round(col(fi)).astype(jnp.int64),
                    grp_valid))
        return out_cols, first_idx, cert

    def _kernel_compact_cap(self, batch: ColumnarBatch) -> Optional[int]:
        """Compact group width for the kernel, or None (full-width
        output).  Mirrors _compact_groups' policy: the deopt retry is
        the last chance and must be guaranteed-valid, so it always runs
        uncompacted; escalation is learned per exec instance."""
        if CK.is_retrying():
            return None
        tc = getattr(self, "_compact_cap", self.COMPACT_GROUPS_CAP)
        if tc > self.COMPACT_GROUPS_MAX or batch.capacity <= tc:
            return None
        return tc

    def _register_excess_check(self, excess, wcap: Optional[int],
                               checks: tuple) -> tuple:
        if excess is None:
            return checks
        chk = CK.register(CK.BatchCheck(
            excess, origin=f"aggCompactGroups[exec {self.exec_id}]",
            recover=lambda cap=wcap: self._escalate_compact(cap)))
        return checks + (chk,)

    def _register_collision_check(self, collision, checks: tuple) -> tuple:
        """Deferred 64-bit-collision deopt for the hash-grouping lane
        (None = lexicographic lane, nothing to check)."""
        return CK.register_deopt(collision,
                                 f"hashGroupby[exec {self.exec_id}]",
                                 self._disable_hash_grouping, checks)

    def _evaluate_kernel(self, batch: ColumnarBatch):
        """Final projection: intermediates -> results (no regrouping)."""
        key = ("agg-eval", batch_signature(batch))

        def build():
            cap = batch.capacity
            funcs = self._funcs
            n_groups_cols = len(self._group_fields)

            @jax.jit
            def kernel(columns, num_rows):
                out = list(columns[:n_groups_cols])
                off = n_groups_cols
                for f in funcs:
                    n = f.num_intermediates
                    parts = columns[off: off + n]
                    off += n
                    out.append(f.evaluate(parts, self._child_schema))
                return out

            return kernel

        return self.kernels.get_or_build(
            key, build, meta=self.kp_meta("agg-eval"))

    # -- dictionary fast path (conf-gated) -----------------------------------
    def _dict_plan(self):
        """Static qualification for the sort-free dictionary path:
        1..3 integral keys (multi-key folds into one composite slot id),
        Sum/Count/Average over float inputs (variableFloatAgg-gated f32
        accumulation) or INTEGRAL inputs (exact-or-deopt: an in-kernel
        f32-exactness certificate, no conf gate).
        Returns (plan, measures) or None."""
        if self.mode == AggMode.FINAL or \
                not 1 <= len(self._bound_groups) <= 3:
            return None
        if not all(f.dtype.is_integral for f in self._group_fields):
            return None
        plan, measures = [], []
        self._dict_float = False
        for f, bins in zip(self._funcs, self._bound_inputs):
            name = type(f).__name__
            if name == "Count":
                if bins:
                    plan.append(("count_expr", len(measures)))
                    measures.append(("flag", bins[0]))
                else:
                    plan.append(("count_star", None))
            elif name in ("Sum", "Average"):
                dt = bins[0].data_type(self._child_schema)
                if dt.is_floating:
                    self._dict_float = True
                    plan.append((name.lower(), len(measures)))
                    measures.append(("val", bins[0]))
                    measures.append(("flag", bins[0]))
                elif dt.is_integral:
                    # exact-or-deopt: f32 accumulation of integers is
                    # EXACT while every intermediate fits 2^24, which
                    # the kernel certifies per group by accumulating
                    # sum(|v|) alongside (inexactness cannot hide:
                    # f32 adds of nonnegative ints round monotonically,
                    # so a true sum >= 2^23 reads >= ~2^23).  No
                    # variableFloatAgg gate — results are bit-exact or
                    # the deferred check deopts to the sort lane.
                    plan.append((name.lower() + "_int", len(measures)))
                    measures.append(("val", bins[0]))
                    measures.append(("flag", bins[0]))
                    measures.append(("absval", bins[0]))
                else:
                    return None
            else:
                return None
        return plan, measures

    def _dict_groupby_batch(self, batch: ColumnarBatch):
        """Sort-free grouped aggregation (reference: the role cuDF's hash
        groupby plays under `aggregate.scala:312` vs the sort-based
        fallback): when the integral key ranges (a single key, or the
        composite product of up to three keys) fit the dictionary
        budget at RUNTIME, the whole batch goes through ONE fused
        dispatch — key-window slots, Pallas one-hot grouped-sum
        (ops/pallas_kernels.grouped_sum_pallas), and the partial-batch
        finalize, all inside one jit.  A one-time first-batch probe
        sizes the padded dictionary; later batches compute their own
        window base (kmin) device-side and report overflow instead of
        paying a probe round-trip, so the steady state is one dispatch
        plus one tiny readback per batch.

        Planner-automatic: default-on (spark.rapids.tpu.dictGroupby
        .enabled) with float Sum/Average additionally gated on
        variableFloatAgg.enabled — the kernel accumulates f32, a
        variableFloatAgg-class tolerance (ADVICE r2).  Count-only plans
        are exact and need no float gate.  Returns the partial-layout
        batch or None (caller falls back to the sort kernel)."""
        from spark_rapids_tpu import config as C
        conf = C.get_active_conf()
        if not conf[C.DICT_GROUPBY_ENABLED] or self._dict_qual is None:
            return None
        if self._dict_float and not conf[C.VARIABLE_FLOAT_AGG]:
            return None
        if batch.capacity >= (1 << 24) or batch.capacity % 128:
            return None  # f32 counts exact below 2^24; kernel needs
            # lane-aligned capacities
        if self._dict_range_misses >= 3:
            # this exec's keys keep spanning past the budget: stop
            # trying (and stop paying discarded fast dispatches)
            return None

        nk = len(self._bound_groups)
        if self._dict_gpad is None:
            probe = self.kernels.get_or_build(
                ("dict-probe", nk, batch_signature(batch)),
                lambda: jax.jit(self._build_dict_probe(batch.capacity)),
                meta=self.kp_meta("agg-dict-probe"))
            if batch.sparse is not None:
                kmins, kmaxs = probe(batch.columns, batch.num_rows_i32,
                                     batch.sparse)
            else:
                kmins, kmaxs = probe(batch.columns, batch.num_rows_i32)
            import numpy as _np
            from spark_rapids_tpu.utils import checks as CK
            CK.note_host_sync("agg.dict_probe", nbytes=16 * nk)
            kmins = _np.asarray(kmins).reshape(-1)
            kmaxs = _np.asarray(kmaxs).reshape(-1)
            spans = [max(int(hi) - int(lo) + 1, 1) if hi >= lo else 1
                     for lo, hi in zip(kmins, kmaxs)]
            budget = int(conf[C.DICT_GROUPBY_MAX_GROUPS])
            if nk == 1:
                if spans[0] > budget:
                    self._dict_range_misses += 1
                    return None
                # bucket the padded width so compiles amortize
                self._dict_gpad = max(8, int(bucket_capacity(spans[0])))
            else:
                # per-key ~12.5% headroom (later batches drift), width
                # includes a null slot per key; composite product must
                # fit the budget
                pads = [max(4, -(-(s + s // 8) // 4) * 4)
                        for s in spans]
                total = 1
                for p in pads:
                    total *= p + 1
                if total > budget:
                    self._dict_range_misses += 1
                    return None
                self._dict_gpad = tuple(pads)
        g_pad = self._dict_gpad

        kp_members = (self._pre_stage.member_names()
                      if self._pre_stage is not None else None)
        if nk == 1:
            fused = self.kernels.get_or_build(
                ("dict-fused", g_pad, batch_signature(batch)),
                lambda: jax.jit(
                    self._build_dict_fused(batch.capacity, g_pad)),
                meta=self.kp_meta("agg-dict-fused",
                                  members=kp_members))
        else:
            fused = self.kernels.get_or_build(
                ("dict-fused-multi", g_pad, batch_signature(batch)),
                lambda: jax.jit(self._build_dict_fused_multi(
                    batch.capacity, list(g_pad))),
                meta=self.kp_meta("agg-dict-fused-multi",
                                  members=kp_members))
        if batch.sparse is not None:
            cols, n, excess = fused(batch.columns, batch.num_rows_i32,
                                    batch.sparse)
        else:
            cols, n, excess = fused(batch.columns, batch.num_rows_i32)
        from spark_rapids_tpu.utils import checks as CK
        check = CK.register(CK.BatchCheck(
            excess, f"dictGroupby[exec {self.exec_id}]",
            self._disable_dict_path))
        return ColumnarBatch(self._partial_schema(), list(cols), n,
                             batch.checks + (check,))

    def _disable_dict_path(self) -> None:
        self._dict_range_misses = 1 << 20

    #: static budget of per-batch overflow rows the fused kernel carries
    #: INLINE as singleton partial groups (exact — partial aggregation
    #: may emit duplicate keys; the final merge combines them).  Only
    #: when a batch overflows past this does the deferred excess check
    #: fire and deopt the query.
    DICT_OVERFLOW_BUDGET = 1024

    @staticmethod
    def _eval_dict_measures(ctx, measures, rows):
        """Shared by both fused dict kernels: evaluate measures into
        (f32 kernel inputs, raw (value, valid) pairs for overflow
        rows).  Raw values stay UN-masked and UN-cast: full-width f64
        selects/casts are slow emulated ops; mask+cast happen after the
        (tiny) overflow gather."""
        vals, raw = [], []
        for kind, e in measures:
            v = e.eval(ctx)
            good = v.validity & rows
            if kind in ("val", "absval"):
                v32 = (v.narrow if v.narrow is not None
                       else v.data.astype(jnp.float32))
                v32 = jnp.asarray(v32, jnp.float32)
                if kind == "absval":
                    # certificate input only — overflow singletons read
                    # the paired "val" measure's raw entry, so this
                    # raw slot is a placeholder keeping mi alignment
                    v32 = jnp.abs(v32)
                vals.append(jnp.where(good, v32, jnp.float32(0)))
                raw.append((None, good) if kind == "absval"
                           else (v.data, good))
            else:
                vals.append(good.astype(jnp.float32))
                raw.append((good, good))
        return vals, raw

    @staticmethod
    def _compact_dict_overflow(ovf_mask, ovf_cnt, cap, ovf_budget):
        """Shared overflow-row compaction (first ovf_budget overflow
        rows).  The compaction (a top_k over the full capacity, ~67ms
        at 2M) is gated behind lax.cond: the common case — zero
        overflow — pays only the (fused) mask/count it needed anyway."""
        def _compact():
            iota = jnp.arange(cap, dtype=jnp.int32)
            keyv = jnp.where(ovf_mask, iota, jnp.iinfo(jnp.int32).max)
            neg, _ = jax.lax.top_k(-keyv, ovf_budget)
            return jnp.clip(-neg, 0, cap - 1)

        return jax.lax.cond(
            ovf_cnt > 0, _compact,
            lambda: jnp.full(ovf_budget, cap - 1, jnp.int32))

    @staticmethod
    def _emit_dict_partials(plan, raw, sums_at, cnt_mixed, wi, oi,
                            from_win, valid_out):
        """Shared finalize: window groups + inline overflow singletons
        -> partial agg columns.  `sums_at(mi)` yields the compacted
        window column for kernel measure mi.  Invalid cells are masked
        AFTER the tiny overflow gather so they read as 0, not garbage
        (downstream merges may touch masked data)."""
        out = []
        inexact = jnp.bool_(False)
        for kind, mi in plan:
            if kind == "count_star":
                out.append(ColumnVector(T.INT64, cnt_mixed, valid_out))
                continue
            if kind == "count_expr":
                win_c = jnp.round(sums_at(mi)).astype(jnp.int64)
                _, good_o = raw[mi]
                ovf_c = jnp.take(good_o, oi).astype(jnp.int64)
                out.append(ColumnVector(
                    T.INT64, jnp.where(from_win, jnp.take(win_c, wi),
                                       ovf_c), valid_out))
                continue
            s_w = sums_at(mi)
            f_w = jnp.round(sums_at(mi + 1)).astype(jnp.int64)
            val_o, good_o = raw[mi]
            some = jnp.where(from_win, jnp.take(f_w > 0, wi),
                             jnp.take(good_o, oi)) & valid_out
            if kind in ("sum_int", "average_int"):
                # exactness certificate: every f32 add was exact iff
                # the group's sum(|v|) stayed under 2^24 (threshold
                # 2^23 leaves margin for the certificate's own
                # rounding); past it the deferred check deopts
                inexact = inexact | jnp.any(
                    sums_at(mi + 2) >= jnp.float32(1 << 23))
                win_s = jnp.round(s_w).astype(jnp.int64)
                ovf_s = jnp.take(val_o, oi).astype(jnp.int64)
                si = jnp.where(some,
                               jnp.where(from_win, jnp.take(win_s, wi),
                                         ovf_s), jnp.int64(0))
                if kind == "sum_int":
                    out.append(ColumnVector(T.INT64, si, some))
                else:  # average over ints: (f64 sum, i64 count)
                    out.append(ColumnVector(
                        T.FLOAT64, si.astype(jnp.float64), some))
                    cnt_col = jnp.where(
                        from_win, jnp.take(f_w, wi),
                        jnp.take(good_o, oi).astype(jnp.int64))
                    out.append(ColumnVector(T.INT64, cnt_col, valid_out))
                continue
            s = jnp.where(
                some,
                jnp.where(from_win, jnp.take(s_w, wi),
                          jnp.take(val_o, oi).astype(jnp.float64)),
                jnp.float64(0))
            out.append(ColumnVector(T.FLOAT64, s, some))
            if kind == "average":
                cnt_col = jnp.where(
                    from_win, jnp.take(f_w, wi),
                    jnp.take(good_o, oi).astype(jnp.int64))
                out.append(ColumnVector(T.INT64, cnt_col, valid_out))
        return out, inexact

    def _build_dict_fused(self, cap: int, g_pad: int):
        """Sync-free fused dict kernel: ONE dispatch computes the key
        window (anchored at this batch's own device-side kmin), the
        Pallas one-hot grouped sum, the compacted partial batch, AND
        folds out-of-window rows in as inline singleton partial groups.
        Slot layout: [0, g_pad) dense key window, g_pad = null group,
        g_pad+1 = masked (overflow + padding).  Returns
        (columns, num_rows, excess_flag) — all device; nothing syncs."""
        from spark_rapids_tpu.ops.pallas_kernels import (_on_tpu,
                                                         grouped_sum_pallas)
        key_expr = self._bound_groups[0]
        plan, measures = self._dict_qual
        kdt = self._group_fields[0].dtype
        ovf_budget = min(self.DICT_OVERFLOW_BUDGET, cap)
        w_cap = g_pad + 1
        out_cap = int(bucket_capacity(w_cap + ovf_budget))
        interp = not _on_tpu()

        def fused(columns, num_rows, mask=None):
            ctx = self._make_ctx(columns, cap, num_rows, mask)
            k = key_expr.eval(ctx)
            ok = k.validity & ctx.row_mask
            if k.narrow is not None:
                # 32-bit fast lane: 64-bit elementwise ops are ~50-100x
                # slower on TPU (emulated).  The unsigned-difference
                # trick keeps the window test EXACT even if kd-kmin
                # overflows int32: both fit i32, so the true offset
                # fits u32.
                k32 = k.narrow
                kmin32 = jnp.min(jnp.where(ok, k32,
                                           jnp.iinfo(jnp.int32).max))
                offu = (k32 - kmin32).astype(jnp.uint32)
                in_win = ok & (offu < jnp.uint32(g_pad))
                off = offu.astype(jnp.int32)
                kmin = kmin32.astype(jnp.int64)
            else:
                kd64 = k.data.astype(jnp.int64)
                i64 = jnp.iinfo(jnp.int64)
                kmin = jnp.min(jnp.where(ok, kd64, i64.max))
                off = kd64 - kmin
                in_win = ok & (off >= 0) & (off < g_pad)
            slots = jnp.where(
                in_win, off,
                jnp.where(ctx.row_mask & ~k.validity, g_pad,
                          g_pad + 1)).astype(jnp.int32)
            ovf_mask = ok & ~in_win
            ovf_cnt = ovf_mask.sum().astype(jnp.int32)
            vals, raw = HashAggregateExec._eval_dict_measures(
                ctx, measures, ctx.row_mask)
            # row masking rides the SLOT sentinel (padding/filtered rows
            # -> g_pad+1, never counted), so the kernel's prefix bound is
            # the full capacity — mandatory for SPARSE inputs, whose live
            # rows are scattered past the popcount
            sums, counts = grouped_sum_pallas(
                slots, tuple(vals), jnp.int32(cap), n_groups=g_pad + 1,
                capacity=cap, interpret=interp)

            # window-group compaction: null group FIRST, then dense keys
            order = jnp.concatenate([jnp.asarray([g_pad]),
                                     jnp.arange(g_pad)])
            cnt_o = jnp.take(counts, order)
            sums_o = jnp.take(sums, order, axis=0)
            occupied = cnt_o > 0
            n_win = occupied.sum().astype(jnp.int32)
            (nz,) = jnp.nonzero(occupied, size=w_cap, fill_value=0)
            slot_w = jnp.take(order, nz)
            cnt_w = jnp.take(cnt_o, nz)
            oidx = HashAggregateExec._compact_dict_overflow(
                ovf_mask, ovf_cnt, cap, ovf_budget)
            n_out = n_win + jnp.minimum(ovf_cnt, ovf_budget)
            excess = ovf_cnt > ovf_budget

            i = jnp.arange(out_cap)
            valid_out = i < n_out
            from_win = i < n_win
            wi = jnp.clip(i, 0, w_cap - 1)
            oi = jnp.take(oidx, jnp.clip(i - n_win, 0, ovf_budget - 1))

            key_data = jnp.where(
                from_win,
                jnp.take((kmin + slot_w).astype(kdt.storage_dtype), wi),
                jnp.take(k.data, oi).astype(kdt.storage_dtype))
            key_valid = jnp.where(from_win,
                                  jnp.take(slot_w != g_pad, wi),
                                  jnp.take(k.validity, oi)) & valid_out
            out = [ColumnVector(kdt, key_data, key_valid)]
            cnt_mixed = jnp.where(from_win,
                                  jnp.take(cnt_w.astype(jnp.int64), wi),
                                  jnp.int64(1))
            cols_m, inexact = HashAggregateExec._emit_dict_partials(
                plan, raw, lambda mi: jnp.take(sums_o[:, mi], nz),
                cnt_mixed, wi, oi, from_win, valid_out)
            out.extend(cols_m)
            return out, n_out, excess | inexact
        return fused

    def _build_dict_probe(self, cap: int):
        key_exprs = list(self._bound_groups)

        def probe(columns, num_rows, mask=None):
            ctx = self._make_ctx(columns, cap, num_rows, mask)
            i64 = jnp.iinfo(jnp.int64)
            mins, maxs = [], []
            for e in key_exprs:
                k = e.eval(ctx)
                ok = k.validity & ctx.row_mask
                kd = k.data.astype(jnp.int64)
                mins.append(jnp.min(jnp.where(ok, kd, i64.max)))
                maxs.append(jnp.max(jnp.where(ok, kd, i64.min)))
            return jnp.stack(mins), jnp.stack(maxs)
        return probe

    def _build_dict_fused_multi(self, cap: int, pads: list):
        """Composite-key variant of `_build_dict_fused`: each integral
        key gets a dense window of `pads[i]` value slots + 1 null slot,
        anchored at the batch's own device-side per-key minimum; the
        per-key slots fold into ONE composite id (row-major strides)
        that feeds the same Pallas one-hot grouped sum.  Rows outside
        ANY key's window become inline singleton partial groups exactly
        like the single-key path."""
        from spark_rapids_tpu.ops.pallas_kernels import (_on_tpu,
                                                         grouped_sum_pallas)
        key_exprs = list(self._bound_groups)
        kdts = [f.dtype for f in self._group_fields]
        plan, measures = self._dict_qual
        nk = len(key_exprs)
        widths = [p + 1 for p in pads]  # value slots + null slot
        strides = [1] * nk
        for i in range(nk - 2, -1, -1):
            strides[i] = strides[i + 1] * widths[i + 1]
        G = strides[0] * widths[0]
        ovf_budget = min(self.DICT_OVERFLOW_BUDGET, cap)
        w_cap = G
        out_cap = int(bucket_capacity(G + ovf_budget))
        interp = not _on_tpu()

        def fused(columns, num_rows, mask=None):
            ctx = self._make_ctx(columns, cap, num_rows, mask)
            rows = ctx.row_mask
            combined = jnp.zeros(cap, jnp.int32)
            in_win = rows
            kmins = []
            ks = []
            for e, span, stride in zip(key_exprs, pads, strides):
                k = e.eval(ctx)
                ks.append(k)
                okk = k.validity & rows
                if k.narrow is not None:
                    k32 = k.narrow
                    kmin32 = jnp.min(jnp.where(
                        okk, k32, jnp.iinfo(jnp.int32).max))
                    offu = (k32 - kmin32).astype(jnp.uint32)
                    within = offu < jnp.uint32(span)
                    off = offu.astype(jnp.int32)
                    kmin = kmin32.astype(jnp.int64)
                else:
                    kd64 = k.data.astype(jnp.int64)
                    kmin = jnp.min(jnp.where(
                        okk, kd64, jnp.iinfo(jnp.int64).max))
                    off64 = kd64 - kmin
                    within = (off64 >= 0) & (off64 < span)
                    off = jnp.clip(off64, 0, span - 1
                                   ).astype(jnp.int32)
                # per-key slot: dense value slot, or the null slot
                slot_i = jnp.where(k.validity,
                                   jnp.where(within, off, 0),
                                   jnp.int32(span))
                key_ok = jnp.where(k.validity, within, True)
                in_win = in_win & key_ok
                combined = combined + slot_i * jnp.int32(stride)
                kmins.append(kmin)
            ovf_mask = rows & ~in_win
            ovf_cnt = ovf_mask.sum().astype(jnp.int32)
            slots = jnp.where(in_win, combined, G).astype(jnp.int32)
            vals, raw = HashAggregateExec._eval_dict_measures(
                ctx, measures, rows)
            sums, counts = grouped_sum_pallas(
                slots, tuple(vals), jnp.int32(cap), n_groups=G + 1,
                capacity=cap, interpret=interp)
            occupied = counts[:G] > 0
            n_win = occupied.sum().astype(jnp.int32)
            (nz,) = jnp.nonzero(occupied, size=w_cap, fill_value=0)
            slot_w = nz.astype(jnp.int32)
            cnt_w = jnp.take(counts[:G], nz)
            oidx = HashAggregateExec._compact_dict_overflow(
                ovf_mask, ovf_cnt, cap, ovf_budget)
            n_out = n_win + jnp.minimum(ovf_cnt, ovf_budget)
            excess = ovf_cnt > ovf_budget

            i = jnp.arange(out_cap)
            valid_out = i < n_out
            from_win = i < n_win
            wi = jnp.clip(i, 0, w_cap - 1)
            oi = jnp.take(oidx, jnp.clip(i - n_win, 0, ovf_budget - 1))

            out = []
            for ki in range(nk):
                comp = (slot_w // jnp.int32(strides[ki])) \
                    % jnp.int32(widths[ki])
                k = ks[ki]
                is_null_w = comp == pads[ki]
                kd_w = (kmins[ki] + comp.astype(jnp.int64)
                        ).astype(kdts[ki].storage_dtype)
                key_data = jnp.where(
                    from_win, jnp.take(kd_w, wi),
                    jnp.take(k.data, oi).astype(
                        kdts[ki].storage_dtype))
                key_valid = jnp.where(
                    from_win, jnp.take(~is_null_w, wi),
                    jnp.take(k.validity, oi)) & valid_out
                out.append(ColumnVector(kdts[ki], key_data, key_valid))
            cnt_mixed = jnp.where(from_win,
                                  jnp.take(cnt_w.astype(jnp.int64), wi),
                                  jnp.int64(1))
            cols_m, inexact = HashAggregateExec._emit_dict_partials(
                plan, raw, lambda mi: jnp.take(sums[:G, mi], nz),
                cnt_mixed, wi, oi, from_win, valid_out)
            out.extend(cols_m)
            return out, n_out, excess | inexact
        return fused

    # -- execution ----------------------------------------------------------
    #: optimistic capacity for compacted group batches: a sort-lane
    #: partial otherwise stays at INPUT capacity (the group count is a
    #: device scalar — syncing it costs ~150ms through the tunnel), so
    #: every downstream op (exchange split, concat, merge re-sort) pays
    #: multi-M-capacity kernels for a few thousand groups.  Group rows
    #: are prefix-compacted by the kernel, so the compaction is a cheap
    #: head slice + a deferred overflow check.  On overflow the cap
    #: ESCALATES (x4 per deopt-and-retry round, learned per exec
    #: instance) rather than disabling — e.g. TPCx-BB q27's ~26K groups
    #: settle on the 64K tier, still far under review capacities.
    COMPACT_GROUPS_CAP = 1 << 14
    COMPACT_GROUPS_MAX = 1 << 20

    def _escalate_compact(self, failed_cap: int) -> None:
        # one escalation per retry round: several batches' checks may
        # fail together, and each invokes recover
        if getattr(self, "_compact_cap", self.COMPACT_GROUPS_CAP) \
                == failed_cap:
            self._compact_cap = failed_cap * 4

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        if not self.group_exprs:
            yield from self._reduction_path(batches)
            return

        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory import oocore as OC
        from spark_rapids_tpu.memory import retry as R
        from spark_rapids_tpu.utils import profile as P
        conf = C.get_active_conf()
        inter_fields = self._partial_schema()
        partials: list[ColumnarBatch] = []
        pending_bytes = 0
        runs: list = []
        external = False
        run_target = max(1, OC.window_bytes(conf) // OC.MERGE_FAN_IN)

        def flush_state():
            """Compact the pending partials to one batch of groups and
            spill it through the host→disk tiers (merging partial agg
            state is key-idempotent, so spilled blocks re-merge later
            in any grouping)."""
            nonlocal partials, pending_bytes
            if not partials:
                return
            merged = partials[0] if len(partials) == 1 else \
                self._merge_partials(partials, inter_fields)
            runs.append(OC.spill_run(merged.dense(), label=self.name(),
                                     metrics=self.metrics, conf=conf))
            partials = []
            pending_bytes = 0

        for batch in batches:
            if not batch.maybe_nonempty():
                continue
            with self.metrics.timed(M.TOTAL_TIME):
                # per-batch grouping is row-local, so halves from a
                # split-and-retry simply land as extra partials for the
                # merge below (this phase is a known OOM hotspot)
                pieces = list(self.oom_retry_batches(
                    batch, self._groupby_one,
                    label=f"{self.name()}.groupBatch"))
            partials.extend(pieces)
            pending_bytes += sum(R.estimate_batch_bytes(p)
                                 for p in pieces)
            if not external and OC.should_go_external(pending_bytes,
                                                      conf):
                external = True
                P.event(P.EV_OOCORE_DEGRADE, op=self.name(),
                        est_bytes=pending_bytes, algo="agg-spill")
            if external and pending_bytes > run_target:
                flush_state()

        if not partials and not runs:
            return
        if runs:
            flush_state()
            merged = self._merge_spilled_state(runs, inter_fields, conf)
        else:
            # concat + re-merge loop until one batch of groups remains
            merged = partials[0] if len(partials) == 1 else \
                self._merge_partials(partials, inter_fields)

        if self.mode == AggMode.PARTIAL:
            out = merged
        else:
            with self.metrics.timed(M.TOTAL_TIME):
                # the final projection reads one merged group batch —
                # no input to subdivide, so pressure spills + retries
                # in place (no-split lane)
                (out,) = tuple(self.oom_retry_batches(
                    merged, self._evaluate_one, split=False,
                    label=f"{self.name()}.evaluate"))
        if out.num_rows_known:
            out = out.with_capacity(bucket_capacity(out.num_rows))
        self.update_output_metrics(out)
        yield out

    def _groupby_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        """One batch (or split piece) through the grouping kernel ->
        partial-layout batch.  The OOM harness reserves ahead of this."""
        import time as _time
        phase = "merge" if self.mode == AggMode.FINAL else "update"
        t0 = _time.perf_counter() if (
            self._pre_stage is not None
            and not self._fused_event_done) else None
        fast = self._dict_groupby_batch(batch)
        if fast is not None:
            self._charge_pre_stage(t0)
            return fast
        wcap = self._kernel_compact_cap(batch)
        kern = self._groupby_kernel(batch, phase, wcap)
        if batch.sparse is not None:
            cols, n, coll, excess, cert = kern(
                batch.columns, batch.num_rows_i32, batch.sparse)
        else:
            cols, n, coll, excess, cert = kern(
                batch.columns, batch.num_rows_i32)
        self._charge_pre_stage(t0)
        checks = self._register_collision_check(coll, batch.checks)
        checks = self._register_excess_check(excess, wcap, checks)
        checks = self._register_banded_check(cert, checks)
        return ColumnarBatch(self._partial_schema(), list(cols), n,
                             checks)

    def _evaluate_one(self, merged: ColumnarBatch) -> ColumnarBatch:
        kern = self._evaluate_kernel(merged)
        cols = kern(merged.columns, merged.num_rows_i32)
        return ColumnarBatch(self._schema, list(cols), merged._rows,
                             merged.checks)

    def _get_merge_exec(self, inter_schema) -> "HashAggregateExec":
        """Cached internal FINAL-mode exec so merge kernels are compiled
        once per batch signature, not once per partition."""
        me = getattr(self, "_merge_exec", None)
        if me is None:
            me = HashAggregateExec(
                [GroupRef(i, f.dtype)
                 for i, f in enumerate(self._group_fields)],
                [AggAlias(f, a.name) for f, a in
                 zip(self._funcs, self.aggregates)],
                _SchemaOnly(inter_schema), mode=AggMode.FINAL)
            self._merge_exec = me
        return me

    def _merge_spilled_state(self, runs: list, inter_schema,
                             conf) -> ColumnarBatch:
        """Windowed re-merge of spilled partial-aggregation state: each
        pass reads back window-sized groups of runs, merges each to one
        compacted batch of groups, and re-spills until a single block
        remains.  Bounded by `oocore.maxRecursionDepth` passes — past
        it, a descriptive error, never a hang or partial data."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory import oocore as OC
        from spark_rapids_tpu.memory.retry import TpuOutOfCoreError
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        window = OC.window_bytes(conf)
        max_passes = max(1, int(conf[C.OOCORE_MAX_RECURSION]))
        passes = 0
        with W.heartbeat(f"{self.name()}.oocore-merge", kind="task",
                         conf=conf) as hb:
            while len(runs) > 1:
                if passes >= max_passes:
                    raise TpuOutOfCoreError(
                        f"{self.name()}: spilled aggregation state "
                        f"still spans {len(runs)} blocks after "
                        f"{passes} merge passes "
                        f"(spark.rapids.memory.oocore.maxRecursionDepth"
                        f"={max_passes}) — raise the HBM budget or "
                        f"oocore.windowFraction")
                passes += 1
                self.metrics.add(M.NUM_EXTERNAL_MERGE_PASSES, 1)
                P.event(P.EV_OOCORE_MERGE_PASS, op=self.name(),
                        num_runs=len(runs))
                groups: list[list] = [[]]
                group_bytes = 0
                for r in runs:
                    # 2x: payload + merge scratch; each group takes at
                    # least 2 runs so every pass at least halves the
                    # run count (the inner split-retry lattice absorbs
                    # any window overshoot)
                    if (len(groups[-1]) >= 2
                            and group_bytes + 2 * r.nbytes > window):
                        groups.append([])
                        group_bytes = 0
                    groups[-1].append(r)
                    group_bytes += 2 * r.nbytes
                next_runs = []
                for group in groups:
                    W.maybe_hang("oocore-merge", conf)
                    batches = [r.read(self.metrics) for r in group]
                    merged = batches[0] if len(batches) == 1 else \
                        self._merge_partials(batches, inter_schema)
                    for r in group:
                        r.free()
                    hb.beat()
                    if len(groups) == 1:
                        return merged  # final merge: no re-spill
                    next_runs.append(OC.spill_run(
                        merged.dense(), label=self.name(),
                        metrics=self.metrics, conf=conf))
                runs = next_runs
        final = runs[0]
        batch = final.read(self.metrics)
        final.free()
        return batch

    def _merge_partials(self, partials, inter_schema) -> ColumnarBatch:
        # sparse_ok: the merge kernel takes a deferred-selection mask,
        # so the concat can stay gather-free
        merged = concat_batches(partials, sparse_ok=True)
        merge_exec = self._get_merge_exec(inter_schema)
        # the merge phase is the aggregate's known OOM hotspot: under
        # reservation failure the concatenated partials split in half
        # and each half merges independently — a group key may then
        # appear in several results, so >1 outputs re-merge (each round
        # shrinks toward the final group count, and the row floor
        # bounds the recursion)
        outs = list(self.oom_retry_batches(
            merged,
            lambda b: self._merge_one(merge_exec, b, inter_schema),
            label=f"{self.name()}.mergePartials"))
        if len(outs) == 1:
            return outs[0]
        if sum(o.num_rows for o in outs) >= merged.num_rows:
            # split-retry made no progress: every split half still held
            # (nearly) every group key, so re-merging the halves would
            # ping-pong at the same row count forever under a sustained
            # reservation failure (tiny hbmBudgetBytes).  Fall back to
            # one unreserved best-effort merge of the whole state — the
            # same escape hatch the split floor uses.
            log.warning(
                "%s.mergePartials: split-retry not converging "
                "(%d rows -> %d across %d outputs); merging unreserved",
                self.name(), merged.num_rows,
                sum(o.num_rows for o in outs), len(outs))
            whole = concat_batches(outs, sparse_ok=True)
            return self._merge_one(merge_exec, whole, inter_schema)
        return self._merge_partials(outs, inter_schema)

    def _merge_one(self, merge_exec, merged, inter_schema
                   ) -> ColumnarBatch:
        wcap = self._kernel_compact_cap(merged)
        with self.metrics.timed(M.TOTAL_TIME):
            kern = merge_exec._groupby_kernel(merged, "merge", wcap)
            if merged.sparse is not None:
                cols, n, coll, excess, cert = kern(
                    merged.columns, merged.num_rows_i32, merged.sparse)
            else:
                cols, n, coll, excess, cert = kern(
                    merged.columns, merged.num_rows_i32)
        checks = merge_exec._register_collision_check(coll, merged.checks)
        # escalation is learned on the OUTER exec (the merge exec is a
        # cached internal helper; the compact policy lives with self)
        checks = self._register_excess_check(excess, wcap, checks)
        checks = self._register_banded_check(cert, checks)
        return ColumnarBatch(inter_schema, list(cols), n, checks)

    def _partial_schema(self) -> T.Schema:
        if self.mode == AggMode.FINAL:
            return self._child_schema  # child already emits partial layout
        fields = list(self._group_fields)
        for a, ts in zip(self.aggregates, self._inter_types):
            for j, it in enumerate(ts):
                fields.append(T.Field(f"{a.name}#{j}", it))
        return T.Schema(tuple(fields))

    # -- no-group-key reduction (reference aggregate.scala reduction path) --
    def _reduction_path(self, batches) -> Iterator[ColumnarBatch]:
        inter_schema = self._partial_schema()
        partials = []
        phase = "merge" if self.mode == AggMode.FINAL else "update"

        def reduce_one(b: ColumnarBatch) -> ColumnarBatch:
            import time as _time
            t0 = _time.perf_counter() if (
                self._pre_stage is not None
                and not self._fused_event_done) else None
            kern = self._reduce_kernel(b, phase)
            if b.sparse is not None:
                cols = kern(b.columns, b.num_rows_i32, b.sparse)
            else:
                cols = kern(b.columns, b.num_rows_i32)
            self._charge_pre_stage(t0)
            return ColumnarBatch(inter_schema, list(cols), 1, b.checks)

        for batch in batches:
            with self.metrics.timed(M.TOTAL_TIME):
                # whole-batch reductions are row-local too: split halves
                # just add 1-row partials to the merge below
                partials.extend(self.oom_retry_batches(
                    batch, reduce_one, label=f"{self.name()}.reduce"))
        if not partials:
            # SQL: aggregate of empty input yields one row (e.g. COUNT=0)
            partials = [self._empty_partial(inter_schema)]
        # always merge (even a single partial): normalizes e.g. an
        # all-invalid empty-input count intermediate into a valid 0
        merged = self._merge_reduction(partials, inter_schema)
        if self.mode == AggMode.PARTIAL:
            out = merged
        else:
            kern = self._evaluate_kernel(merged)
            cols = kern(merged.columns, merged.num_rows_i32)
            out = ColumnarBatch(self._schema, list(cols), 1, merged.checks)
        self.update_output_metrics(out)
        yield out

    def _reduce_kernel(self, batch: ColumnarBatch, phase: str):
        key = ("agg-reduce", phase, batch_signature(batch))

        def build():
            cap = batch.capacity
            funcs = self._funcs

            @jax.jit
            def kernel(columns, num_rows, mask=None):
                ctx = self._make_ctx(columns, cap, num_rows, mask)
                seg_ids = jnp.zeros(cap, jnp.int32)
                actx = AggContext(seg_ids, cap, ctx.row_mask,
                                  bounds=jnp.arange(cap) == 0,
                                  ends=jnp.full(cap, cap - 1, jnp.int32))
                if phase == "update":
                    inputs_per_f = [[e.eval(ctx) for e in bins]
                                    for bins in self._bound_inputs]
                else:
                    inputs_per_f = []
                    off = len(self._group_fields)
                    for f in funcs:
                        n = f.num_intermediates
                        inputs_per_f.append(columns[off: off + n])
                        off += n
                from spark_rapids_tpu.exprs.aggregates import \
                    run_agg_phase
                out_cols = []
                for outs in run_agg_phase(actx, funcs, inputs_per_f,
                                          phase):
                    out_cols.extend(outs)
                return out_cols

            return kernel

        return self.kernels.get_or_build(
            key, build,
            meta=self.kp_meta(
                f"agg-reduce-{phase}",
                members=(self._pre_stage.member_names()
                         if self._pre_stage is not None else None)))

    def _merge_reduction(self, partials, inter_schema) -> ColumnarBatch:
        merged = concat_batches(partials)
        agg = self._get_merge_exec(inter_schema)
        kern = agg._reduce_kernel(merged, "merge")
        cols = kern(merged.columns, merged.num_rows_i32)
        return ColumnarBatch(inter_schema, list(cols), 1, merged.checks)

    def _empty_partial(self, inter_schema) -> ColumnarBatch:
        from spark_rapids_tpu.columnar.batch import empty_batch
        e = empty_batch(inter_schema)
        # one row of "no inputs seen": validity false, counts zero
        return ColumnarBatch(inter_schema, e.columns, 1)


@dataclasses.dataclass(eq=False)
class GroupRef(Expression):
    """Positional reference used by the merge stage (keys are at fixed
    positions in partial batches)."""
    ordinal: int
    dtype: T.DataType

    def data_type(self, schema):
        return self.dtype

    def bind(self, schema):
        return self

    def eval(self, ctx):
        return ctx.columns[self.ordinal]



