"""Async pipelined execution: bounded prefetch between pipeline stages.

The engine is host-driven: Python pulls batches through operator
iterators while all per-batch compute runs in XLA executables.  Fully
synchronous pulling serializes the three resources a query actually
uses — host orchestration (decode, split bookkeeping, upload staging),
the host->device transfer, and device kernels — so the TPU idles while
Python works and vice versa.  `PrefetchIterator` breaks that lockstep at
pipeline breaks (scan->compute, both sides of a shuffle exchange,
coalesce boundaries, AQE stage materialization): a background producer
thread runs the upstream iterator up to `prefetchDepth` batches ahead of
the consumer through a bounded queue, the same overlap the reference
gets from `MultiFileThreadPoolFactory` + the CUDA stream (we only had it
inside io/scan.py's host buffering).

Discipline (the parts that make this safe rather than just concurrent):

* **Bounded depth** — the queue holds at most `prefetchDepth` batches,
  so a fast producer cannot flood HBM; backpressure is the queue block.
* **Semaphore** — a producer blocked on a full queue NEVER holds the TPU
  semaphore: it yields its task's hold for the duration of the block
  (`TpuSemaphore.yielded`, the PR 1 spill discipline) so concurrent
  tasks keep the accelerator busy while this one is parked.
* **Task identity** — the producer runs under the creating thread's
  `TaskContext` when one exists (one task, helper thread — the
  reference's multithreaded reader model), else under a fresh private
  context that is force-completed (semaphore released) on thread exit.
* **Conf propagation** — the session conf is thread-local; the producer
  re-installs the creator's conf so upstream conf reads see the same
  values the plan was built with.
* **Error / cancellation propagation** — a producer exception is
  re-raised at the consumer's pull point (so OOM split-and-retry and
  deopt recovery fire on the consuming side exactly as they would
  synchronously), and closing the consumer cancels the producer and
  closes the source iterator so upstream cleanup (shuffle reader
  release, file handles) still runs.
* **Lazy start** — the producer thread starts on the consumer's first
  pull, not at plan build: `execute_partitions()` constructs every
  partition's iterator eagerly, and starting all producers there would
  turn plan construction into unbounded whole-plan concurrency.
"""
from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from typing import Iterable, Iterator, Optional

from spark_rapids_tpu.utils import metrics as M

log = logging.getLogger("spark_rapids_tpu.pipeline")

#: end-of-stream sentinel (errors ride on `self._error`, set before this)
_DONE = object()

#: task ids for producers created outside any task context; offset far
#: above real task-attempt ids so the two never collide in the
#: semaphore's refcount table
_PRODUCER_TASK_IDS = itertools.count(1 << 40)

#: poll granularity for cancellable blocking queue ops; latency is only
#: paid on the (rare) full/empty-with-dead-producer edges
_POLL_S = 0.05

#: how long close()/_finish wait for a producer thread before declaring
#: it leaked (module-level so the watchdog suite can shrink it)
_JOIN_TIMEOUT_S = 10.0

# process-wide stats (bench.py records these alongside wall clock so the
# perf trajectory captures overlap, not just totals; leaked_producers
# counts threads that survived the close() join — surfaced in the
# watchdog dump, because a leaked producer is exactly the kind of
# wedged activity the watchdog exists to name)
_STATS_LOCK = threading.Lock()
_STATS = {"producers": 0, "hits": 0, "stalls": 0, "wait_ns": 0,
          "blocked_puts": 0, "leaked_producers": 0}

# LIVE occupancy (vs the cumulative counters above): how many consumers
# are blocked on an empty queue / producers parked on a full one RIGHT
# NOW — the telemetry sampler's pipeline_stall classification.  Bumped
# only on the (already slow) blocking edges, never the hit path.
_LIVE_STATS = {"stalled_consumers": 0, "blocked_producers": 0}


def pipeline_stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def pipeline_live() -> dict:
    with _STATS_LOCK:
        return dict(_LIVE_STATS)


def _bump_live(name: str, delta: int) -> None:
    with _STATS_LOCK:
        _LIVE_STATS[name] += delta


def reset_pipeline_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _bump(name: str, value: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[name] += value


class PrefetchIterator:
    """Depth-bounded background prefetch over a batch iterator.

    Iterator protocol on the consumer side; the source runs on a
    producer thread started at the first pull.  `close()` (also invoked
    by GC) cancels the producer, drains the queue, and closes the
    source."""

    def __init__(self, source: Iterable, depth: int,
                 label: str = "pipeline", metrics=None, conf=None):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory.semaphore import TaskContext
        assert depth > 0
        self._source = iter(source)
        self._q: "queue.Queue" = queue.Queue(maxsize=int(depth))
        self._label = label
        self._metrics = metrics
        self._conf = conf if conf is not None else C.get_active_conf()
        #: creator's task identity, shared with the producer thread when
        #: present (same task, helper thread)
        self._ctx = TaskContext.get()
        #: thread-local deopt-retry flag, propagated so fast paths the
        #: producer executes still bypass themselves on the final
        #: guaranteed-valid attempt (iterators are rebuilt per attempt,
        #: so construction-time capture is exact)
        from spark_rapids_tpu.utils import checks as CK
        self._retrying = CK.is_retrying()
        #: the creating query's context AND cancel token: the producer
        #: thread runs scoped to the creator's query, so its conf
        #: reads, deferred checks, profile events, semaphore fair-share
        #: group, and cancellation all belong to the RIGHT query —
        #: never a concurrent session's
        from spark_rapids_tpu.exec import scheduler as S
        from spark_rapids_tpu.utils import watchdog as W
        self._qc = S.current()
        self._token = W.current_token()
        #: creator's span context (None unless the query is profiled):
        #: the producer thread attaches here so its spans parent under
        #: the pipeline break that spawned it, not a detached root
        from spark_rapids_tpu.utils import profile as P
        self._span_ref = P.current_ref()
        self._hb = None
        self._closed = threading.Event()
        #: test-facing: set while the producer is parked on a full queue
        #: (the window in which it must not hold the TPU semaphore)
        self.blocked = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._done = False

    # -- consumer side ------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        self._ensure_started()
        try:
            item = self._q.get_nowait()
            _bump("hits")
            if self._metrics is not None:
                self._metrics.add(M.PREFETCH_HITS, 1)
        except queue.Empty:
            item = self._wait_for_item()
        if item is _DONE:
            self._done = True
            self._finish()
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            raise StopIteration
        return item

    def _wait_for_item(self):
        from spark_rapids_tpu.utils import profile as P
        t0 = time.perf_counter_ns()
        # a stalled pull is exactly the overlap loss the profile's
        # breakdown wants to name; already off the hot path (we only
        # get here when the queue was empty), and a no-op unprofiled
        sp = P.span(f"pipeline-wait:{self._label}", cat=P.CAT_WAIT) \
            if P.tracer() is not None else P._NULL_SPAN
        _bump_live("stalled_consumers", 1)
        try:
            with sp:
                while True:
                    try:
                        return self._q.get(timeout=_POLL_S)
                    except queue.Empty:
                        if self._token.cancelled:
                            # watchdog cancellation: release what the
                            # producer buffered before surfacing, so the
                            # failed query pins nothing
                            self.close()
                            self._token.check()
                        t = self._thread
                        if t is None or not t.is_alive():
                            # producer exited: drain the put/exit race,
                            # then report end-of-stream (error checked
                            # by caller)
                            try:
                                return self._q.get_nowait()
                            except queue.Empty:
                                return _DONE
        finally:
            _bump_live("stalled_consumers", -1)
            waited = time.perf_counter_ns() - t0
            _bump("stalls")
            _bump("wait_ns", waited)
            if self._metrics is not None:
                self._metrics.add(M.PREFETCH_STALLS, 1)
                self._metrics.add(M.PIPELINE_WAIT_TIME, waited)

    def close(self) -> None:
        """Cancel the producer and release everything it buffered."""
        self._done = True
        self._closed.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._join_or_leak()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _finish(self) -> None:
        self._join_or_leak()

    def _join_or_leak(self) -> None:
        """Join the producer; a thread that survives the bounded join
        is LEAKED, not silently forgotten: it is counted in the
        process-wide pipeline stats (surfaced in the watchdog dump)
        and its stack is logged so the wedged frame is attributable."""
        t = self._thread
        if (t is None or not t.is_alive()
                or t is threading.current_thread()):
            return
        t.join(timeout=_JOIN_TIMEOUT_S)
        if not t.is_alive():
            return
        self._thread = None  # joining again later cannot succeed
        _bump("leaked_producers")
        from spark_rapids_tpu.utils import watchdog as W
        stack = W.thread_stack(t.ident)
        log.warning(
            "prefetch producer %s survived the %.0fs close() join and "
            "was leaked (source iterator is wedged); stack:\n%s",
            t.name, _JOIN_TIMEOUT_S, stack or "<unavailable>")

    # -- producer side ------------------------------------------------------
    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._produce, daemon=True,
                name=f"tpu-prefetch-{self._label}")
            _bump("producers")
            self._thread.start()

    def _produce(self) -> None:
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory.semaphore import TaskContext
        from spark_rapids_tpu.utils import checks as CK
        from spark_rapids_tpu.utils import watchdog as W
        if self._retrying:
            CK.set_retrying(True)
        own_ctx = None
        if self._ctx is not None:
            TaskContext.set_current(self._ctx)
        else:
            own_ctx = TaskContext(next(_PRODUCER_TASK_IDS))
            TaskContext.set_current(own_ctx)
        # thread the query's cancel token + context through the
        # TaskContext so downstream checks on this thread (and any
        # helper threads it spawns) reach the right query
        cur = TaskContext.get()
        if cur is not None and getattr(cur, "cancel_token", None) is None:
            cur.cancel_token = self._token
        if cur is not None and getattr(cur, "query_ctx", None) is None:
            cur.query_ctx = self._qc
        from spark_rapids_tpu.exec import scheduler as S
        from spark_rapids_tpu.utils import profile as P
        try:
            with S.scoped(self._qc), C.session(self._conf), \
                    P.attach(self._span_ref), \
                    P.span(f"producer:{self._label}", cat=P.CAT_PIPELINE):
                hb = W.heartbeat(f"producer:{self._label}",
                                 kind="task",
                                 details=lambda: f"queue depth "
                                 f"{self._q.qsize()}/{self._q.maxsize}")
                self._hb = hb
                try:
                    with hb:
                        for item in self._source:
                            hb.beat()
                            W.maybe_hang("producer")
                            if not self._put(item):
                                return  # consumer closed
                except BaseException as e:  # noqa: BLE001 — re-raised
                    self._error = e         # at the consumer's pull
                self._put(_DONE)
        finally:
            self._hb = None
            try:
                close = getattr(self._source, "close", None)
                if close is not None:
                    close()
            except Exception:
                pass
            if own_ctx is not None:
                # private task identity: force-release any semaphore
                # hold the source's device work acquired
                own_ctx.complete()
            TaskContext.set_current(None)

    def _put(self, item) -> bool:
        """Enqueue with backpressure.  False = consumer cancelled.  A
        producer parked on a full queue must not hold the TPU semaphore
        — its task's hold is yielded for the duration of the block."""
        try:
            self._q.put_nowait(item)
            return True
        except queue.Full:
            pass
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        from contextlib import nullcontext
        _bump("blocked_puts")
        _bump_live("blocked_producers", 1)
        self.blocked.set()
        hb = self._hb
        try:
            # parked on a full queue: this is the CONSUMER's stall, not
            # ours — pause the producer heartbeat so backpressure is
            # never mistaken for a hang, and watch the cancel token so
            # a cancelled query's producer exits instead of parking
            # forever on a queue nobody will drain
            with TpuSemaphore.get().yielded(), \
                    (hb.pause() if hb is not None else nullcontext()):
                while not self._closed.is_set():
                    if self._token.cancelled:
                        return False
                    try:
                        self._q.put(item, timeout=_POLL_S)
                        return True
                    except queue.Full:
                        continue
                return False
        finally:
            _bump_live("blocked_producers", -1)
            self.blocked.clear()


def maybe_prefetch(source: Iterable, label: str = "pipeline",
                   metrics=None, conf=None,
                   depth: Optional[int] = None) -> Iterator:
    """Wrap `source` in a PrefetchIterator when the session conf enables
    pipelining (and `depth`/prefetchDepth > 0); otherwise return it
    unwrapped.  Call at iterator-construction time on the thread that
    carries the session conf (plan build / execute_partitions)."""
    from spark_rapids_tpu import config as C
    conf = conf if conf is not None else C.get_active_conf()
    if not conf[C.PIPELINE_ENABLED]:
        return iter(source)
    if depth is None:
        depth = int(conf[C.PIPELINE_PREFETCH_DEPTH])
    if depth <= 0:
        return iter(source)
    return PrefetchIterator(source, depth, label=label, metrics=metrics,
                            conf=conf)
