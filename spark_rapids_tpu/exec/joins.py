"""Join operators (reference shims `GpuHashJoin.scala:50,282`,
`GpuShuffledHashJoinExec.scala`, `GpuBroadcastHashJoinExec.scala`,
`GpuBroadcastNestedLoopJoinExec.scala`, `GpuCartesianProductExec.scala`).

TPU equi-join core — exact, static-shape, collision-free:

  1. concat build+probe rows; lexsort by join keys with a side flag as the
     final tie-break (build rows first within each key group);
  2. segment boundaries over the keys give key-groups; per group record the
     build-row range [group_start, group_start + build_count);
  3. each probe row's match count = its group's build count (0 if any key
     is null — SQL equi-join semantics); a CSR expansion enumerates the
     (probe, build) pairs.

The expansion size is data-dependent: kernel A returns counts and the
total syncs to host (one scalar), which picks the output capacity bucket
for kernel B — the bucketed-compile discipline from SURVEY.md §7(a).

Join types: inner, left/right outer, full outer, left semi, left anti,
cross.  Residual (non-equi) conditions post-filter inner/cross joins, as
the reference restricts.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.columnar.vector import ColumnVector, bucket_capacity
from spark_rapids_tpu.exec.base import (
    KernelCache, RequireSingleBatch, TpuExec, batch_signature,
    make_eval_context)
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.ops.sort_encode import (
    encode_key_bits, packed_lexsort, segment_boundaries)
from spark_rapids_tpu.utils import checks as CK
from spark_rapids_tpu.utils import metrics as M


from spark_rapids_tpu.columnar.vector import (gather_narrowest,
                                              pack_validity_bits,
                                              validity_bit_assignment)


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    CROSS = "cross"


_PROBE_ONLY = (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI)


class HashJoinExec(TpuExec):
    """Shuffled hash join: build side concatenated to one batch, probe side
    streamed (reference GpuShuffledHashJoinExec)."""

    def __init__(self, join_type: JoinType,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 left: TpuExec, right: TpuExec,
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        self.join_type = join_type
        if condition is not None and join_type not in (
                JoinType.INNER, JoinType.CROSS):
            raise ValueError(
                "residual join conditions only supported for inner joins "
                "(same restriction as the reference GpuHashJoin)")
        self.condition = condition
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        lschema, rschema = left.output_schema(), right.output_schema()
        self._lschema, self._rschema = lschema, rschema
        # probe = left, build = right, except RIGHT_OUTER which probes right
        self._flip = join_type == JoinType.RIGHT_OUTER
        if self._flip:
            self._probe, self._build = right, left
            self._probe_keys = [e.bind(rschema) for e in self.right_keys]
            self._build_keys = [e.bind(lschema) for e in self.left_keys]
        else:
            self._probe, self._build = left, right
            self._probe_keys = [e.bind(lschema) for e in self.left_keys]
            self._build_keys = [e.bind(rschema) for e in self.right_keys]

        if join_type in _PROBE_ONLY:
            self._schema = lschema
        else:
            self._schema = T.Schema(tuple(lschema.fields) +
                                    tuple(rschema.fields))
        from spark_rapids_tpu.exprs.base import fingerprint
        self._join_cache = KernelCache((
            "HashJoinExec", join_type.name, self._flip,
            fingerprint(self._probe_keys), fingerprint(self._build_keys),
            fingerprint(condition), fingerprint(lschema),
            fingerprint(rschema)))
        # dense direct-address fast path: single integral equi-key,
        # no residual condition, join types whose output is derivable
        # from a per-probe-row lookup (FULL_OUTER needs unmatched-build
        # emission -> sort path)
        self._dense_qual = (
            condition is None and
            len(self._probe_keys) == 1 and
            self._probe_keys[0].data_type(
                self._probe.output_schema()).is_integral and
            self._build_keys[0].data_type(
                self._build.output_schema()).is_integral and
            join_type in (JoinType.INNER, JoinType.LEFT_OUTER,
                          JoinType.RIGHT_OUTER, JoinType.LEFT_SEMI,
                          JoinType.LEFT_ANTI))
        self._dense_tables: dict = {}

    def output_schema(self) -> T.Schema:
        return self._schema

    def describe(self):
        return (f"HashJoinExec({self.join_type.value}, "
                f"keys={len(self.left_keys)})")

    # -- kernel A: match counts ------------------------------------------
    def _match_kernel(self, build: ColumnarBatch, probe: ColumnarBatch):
        key = ("join-match", batch_signature(build),
               batch_signature(probe))

        def build_fn():
            bcap, pcap = build.capacity, probe.capacity
            cap = bcap + pcap
            build_keys, probe_keys = self._build_keys, self._probe_keys

            @jax.jit
            def kernel(bcols, bnum, pcols, pnum):
                bctx = make_eval_context(bcols, bcap, bnum)
                pctx = make_eval_context(pcols, pcap, pnum)
                bk = [e.eval(bctx) for e in build_keys]
                pk = [e.eval(pctx) for e in probe_keys]
                # combined key columns (build rows at [0, bcap))
                comb = []
                for b, p in zip(bk, pk):
                    if b.dtype.is_string:
                        from spark_rapids_tpu.columnar.vector import \
                            _pad_chars
                        cc = max(b.char_cap, p.char_cap)
                        b, p = _pad_chars(b, cc), _pad_chars(p, cc)
                        comb.append(ColumnVector(
                            b.dtype,
                            jnp.concatenate([b.data, p.data]),
                            jnp.concatenate([b.validity, p.validity]),
                            jnp.concatenate([b.lengths, p.lengths])))
                    else:
                        dt = b.dtype if b.dtype == p.dtype else \
                            T.common_type(b.dtype, p.dtype)
                        from spark_rapids_tpu.exprs.base import promote
                        b, p = promote(b, dt), promote(p, dt)
                        comb.append(ColumnVector(
                            dt, jnp.concatenate([b.data, p.data]),
                            jnp.concatenate([b.validity, p.validity])))
                side = jnp.concatenate([jnp.zeros(bcap, jnp.uint8),
                                        jnp.ones(pcap, jnp.uint8)])
                row_mask = jnp.concatenate([bctx.row_mask, pctx.row_mask])
                keys_msf = [((~row_mask).astype(jnp.uint8), 1)]
                for c in comb:
                    keys_msf.extend(encode_key_bits(c, True, True))
                keys_msf.append((side, 1))
                perm = packed_lexsort(keys_msf)
                bounds = segment_boundaries(comb, perm, row_mask)
                gid = jnp.cumsum(bounds.astype(jnp.int32)) - 1
                sorted_side = jnp.take(side, perm)
                sorted_mask = jnp.take(row_mask, perm)
                keys_ok = jnp.ones(cap, bool)
                for c in comb:
                    keys_ok = keys_ok & c.validity
                sorted_ok = jnp.take(keys_ok, perm) & sorted_mask
                gid_safe = jnp.where(sorted_mask, gid, cap)
                is_build = (sorted_side == 0) & sorted_ok
                is_probe = (sorted_side == 1) & sorted_ok
                bcount = jax.ops.segment_sum(
                    is_build.astype(jnp.int32),
                    jnp.where(is_build, gid_safe, cap), num_segments=cap)
                pcount = jax.ops.segment_sum(
                    is_probe.astype(jnp.int32),
                    jnp.where(is_probe, gid_safe, cap), num_segments=cap)
                (gstart,) = jnp.nonzero(bounds, size=cap,
                                        fill_value=cap - 1)
                # per probe ORIGINAL row: count + start of its build range
                sorted_pos = jnp.arange(cap)
                probe_orig = jnp.where(sorted_side == 1,
                                       jnp.take(perm, sorted_pos) - bcap, 0)
                counts_p = jnp.zeros(pcap, jnp.int32)
                start_p = jnp.zeros(pcap, jnp.int32)
                cnt_for_row = jnp.where(is_probe,
                                        jnp.take(bcount, gid_safe,
                                                 mode="clip"), 0)
                st_for_row = jnp.where(is_probe,
                                       jnp.take(gstart, gid_safe,
                                                mode="clip"), 0)
                sel = sorted_side == 1
                counts_p = counts_p.at[
                    jnp.where(sel, probe_orig, pcap)].add(
                    cnt_for_row.astype(jnp.int32), mode="drop")
                start_p = start_p.at[
                    jnp.where(sel, probe_orig, pcap)].add(
                    st_for_row.astype(jnp.int32), mode="drop")
                # build matched flags (original build rows)
                bmatch_sorted = is_build & (jnp.take(pcount, gid_safe,
                                                     mode="clip") > 0)
                bmatched = jnp.zeros(bcap, bool)
                borig = jnp.where(sorted_side == 0,
                                  jnp.take(perm, sorted_pos), bcap)
                bmatched = bmatched.at[borig].max(bmatch_sorted,
                                                  mode="drop")
                total_inner = counts_p.sum()
                return counts_p, start_p, perm, bmatched, total_inner

            return kernel

        return self._join_cache.get_or_build(
            key, build_fn, meta=self.kp_meta("join-match"))

    # -- kernel B: pair expansion ----------------------------------------
    def _expand_kernel(self, build: ColumnarBatch, probe: ColumnarBatch,
                       out_cap: int, outer_probe: bool):
        key = ("join-expand", outer_probe, out_cap,
               batch_signature(build), batch_signature(probe))

        def build_fn():
            bcap, pcap = build.capacity, probe.capacity
            cap = bcap + pcap

            @jax.jit
            def kernel(bcols, pcols, counts_p, start_p, perm, pnum):
                eff = counts_p
                if outer_probe:
                    probe_valid = jnp.arange(pcap) < pnum
                    eff = jnp.where(probe_valid & (counts_p == 0), 1,
                                    counts_p)
                cum = jnp.cumsum(eff)
                total = cum[-1]
                k = jnp.arange(out_cap)
                i = jnp.searchsorted(cum, k, side="right")
                i = jnp.clip(i, 0, pcap - 1)
                prev = jnp.where(i > 0, jnp.take(cum, i - 1, mode="clip"),
                                 0)
                off = k - prev
                in_range = k < total
                has_match = jnp.take(counts_p, i, mode="clip") > 0
                sorted_bpos = jnp.take(start_p, i, mode="clip") + off
                combined_row = jnp.take(perm, jnp.clip(sorted_bpos, 0,
                                                       cap - 1))
                build_row = jnp.clip(combined_row, 0, bcap - 1)
                probe_sel = jnp.where(in_range, i, 0)
                build_sel = jnp.where(in_range & has_match, build_row, 0)
                pvalid = in_range
                bvalid = in_range & has_match
                pout = [c.gather(probe_sel, pvalid) for c in pcols]
                bout = [c.gather(build_sel, bvalid) for c in bcols]
                return pout, bout, total

            return kernel

        return self._join_cache.get_or_build(
            key, build_fn, meta=self.kp_meta("join-expand"))

    def _semi_kernel(self, probe: ColumnarBatch, anti: bool):
        key = ("join-semi", anti, batch_signature(probe))

        def build_fn():
            pcap = probe.capacity

            @jax.jit
            def kernel(pcols, counts_p, pnum):
                probe_valid = jnp.arange(pcap) < pnum
                keep = probe_valid & ((counts_p == 0) if anti
                                      else (counts_p > 0))
                n = keep.sum().astype(jnp.int32)
                (idx,) = jnp.nonzero(keep, size=pcap, fill_value=pcap - 1)
                valid = jnp.arange(pcap) < n
                return [c.gather(idx, valid) for c in pcols], n

            return kernel

        return self._join_cache.get_or_build(
            key, build_fn, meta=self.kp_meta("join-semi"))

    # -- dense direct-address fast path -----------------------------------
    # Reference capability parallel: the role cuDF's hash-join build
    # table plays (`GpuHashJoin.scala:282` doJoinLeftRight).  On TPU a
    # pointer-chasing hash table is hostile (serialized gathers), but a
    # DENSE table — one slot per key in [kmin, kmin+span) — turns the
    # whole probe into two fused gathers.  Applicability is checked at
    # build time (span fits budget, keys unique); the sort-merge kernel
    # remains the general fallback.  PK-FK joins on TPC-style dense
    # surrogate keys all take this lane.

    def _try_dense_table(self, build: ColumnarBatch):
        """Build (or fetch cached) the direct-address table; None when
        the build side does not qualify (span too wide / dup keys)."""
        import numpy as np
        from spark_rapids_tpu import config as C
        conf = C.get_active_conf()
        if not conf[C.DENSE_JOIN_ENABLED]:
            return None
        if build.capacity >= (1 << 24) or build.capacity % 128:
            return None  # f32 row-index exactness + pallas lane alignment
        ck = (id(build), build.capacity)
        cached = self._dense_tables.get(ck)
        if cached is not None:
            return cached[0]
        probe = self._join_cache.get_or_build(
            ("dense-probe", batch_signature(build)),
            lambda: jax.jit(self._build_dense_probe(build.capacity)),
            meta=self.kp_meta("join-dense-probe"))
        kmin, kmax = probe(build.columns, build.num_rows_i32)
        kmin, kmax = int(kmin), int(kmax)
        span = kmax - kmin + 1 if kmax >= kmin else 0
        entry = None
        if span <= int(conf[C.DENSE_JOIN_MAX_SPAN]):
            g = int(bucket_capacity(max(span, 1)))
            tab_kern = self._join_cache.get_or_build(
                ("dense-table2", g, batch_signature(build)),
                lambda: jax.jit(self._build_dense_table_kernel(
                    build.capacity, g)),
                meta=self.kp_meta("join-dense-table"))
            bidx1_tab, vmask_tab, max_cnt = tab_kern(
                build.columns, build.num_rows_i32, jnp.int64(kmin))
            if int(max_cnt) <= 1:  # unique build keys required
                entry = (kmin, g, bidx1_tab, vmask_tab)
        # single-entry cache (repeated collects rebuild the build batch
        # each execute — keeping every old one would pin device memory);
        # the strong ref to the build batch keeps id() valid
        self._dense_tables = {ck: (entry, build)}
        return entry

    def _build_dense_probe(self, cap: int):
        key_expr = self._build_keys[0]

        def probe(columns, num_rows):
            ctx = make_eval_context(columns, cap, num_rows)
            k = key_expr.eval(ctx)
            ok = k.validity & ctx.row_mask
            if k.narrow is not None:
                i32 = jnp.iinfo(jnp.int32)
                kmin = jnp.min(jnp.where(ok, k.narrow, i32.max))
                kmax = jnp.max(jnp.where(ok, k.narrow, i32.min))
                return kmin.astype(jnp.int64), kmax.astype(jnp.int64)
            kd = k.data.astype(jnp.int64)
            i64 = jnp.iinfo(jnp.int64)
            return (jnp.min(jnp.where(ok, kd, i64.max)),
                    jnp.max(jnp.where(ok, kd, i64.min)))
        return probe

    def _build_dense_table_kernel(self, cap: int, g: int):
        """slots <- key - kmin; table[slot] = build row index; counts
        detect duplicates.  Built with an XLA scatter-add — slow on TPU
        but paid ONCE per join build (and cached), unlike the per-probe
        work, and it scales to multi-million-slot tables that the
        one-hot kernel's VMEM cannot hold."""
        key_expr = self._build_keys[0]

        def kernel(columns, num_rows, kmin):
            ctx = make_eval_context(columns, cap, num_rows)
            k = key_expr.eval(ctx)
            ok = k.validity & ctx.row_mask
            if k.narrow is not None:
                offu = (k.narrow - kmin.astype(jnp.int32)
                        ).astype(jnp.uint32)
                in_t = ok & (offu < jnp.uint32(g))
                off = offu.astype(jnp.int32)
            else:
                off64 = k.data.astype(jnp.int64) - kmin
                in_t = ok & (off64 >= 0) & (off64 < g)
                off = off64
            # sentinel slot g: masked rows scatter 0 there; it must read
            # as count 0 for out-of-table probes, so only in_t rows add
            slots = jnp.where(in_t, off, g).astype(jnp.int32)
            cnt_tab = jnp.zeros(g + 1, jnp.int32).at[slots].add(
                in_t.astype(jnp.int32))
            # unique keys are required downstream, so one i32 table
            # carries both the row index AND the occupancy test:
            # bidx1[slot] = build row + 1, 0 = empty slot
            bidx1_tab = jnp.zeros(g + 1, jnp.int32).at[slots].add(
                jnp.where(in_t, jnp.arange(cap, dtype=jnp.int32) + 1, 0))
            # pack every non-string build column's validity into one
            # i32 bitmask per slot: the probe side then resolves ALL
            # column validities with a single gather instead of one
            # bool gather per column (random-access passes dominate
            # probe cost on this chip, ~70ns/row each)
            _, packed = pack_validity_bits(columns)
            if packed is None:
                packed = jnp.zeros(cap, jnp.int32)
            vmask_tab = jnp.zeros(g + 1, jnp.int32).at[slots].add(
                jnp.where(in_t, packed, 0))
            return bidx1_tab, vmask_tab, cnt_tab[:g].max()
        return kernel

    def _dense_key_remat_ordinal(self) -> Optional[int]:
        """Ordinal of the build column the (single) build key reads
        directly, or None.  For an equi-join, that column's matched-row
        values EQUAL the probe key values, so the probe side can
        rematerialize it from the probe key instead of paying a gather
        stream (storage dtypes must agree for bit-exact remat)."""
        from spark_rapids_tpu.exprs.base import BoundReference
        bk = self._build_keys[0]
        if isinstance(bk, BoundReference):
            return bk.ordinal
        return None

    def _dense_probe_kernel(self, build: ColumnarBatch,
                            probe: ColumnarBatch, g: int,
                            narrow_ok: bool):
        key = ("dense-join2", g, narrow_ok, batch_signature(build),
               batch_signature(probe))
        jt = self.join_type

        def build_fn():
            pcap = probe.capacity
            probe_key = self._probe_keys[0]
            remat_ord = self._dense_key_remat_ordinal()

            @jax.jit
            def kernel(pcols, pnum, bcols, bidx1_tab, vmask_tab, kmin,
                       pmask=None):
                ctx = make_eval_context(pcols, pcap, pnum, pmask)
                pk = probe_key.eval(ctx)
                ok = pk.validity & ctx.row_mask
                if pk.narrow is not None and narrow_ok:
                    # narrow_ok: the CALLER verified [kmin, kmin+g)
                    # fits int32, so the unsigned-difference window
                    # test is exact (a kmin outside int32 would wrap
                    # and fabricate matches)
                    offu = (pk.narrow - kmin.astype(jnp.int32)
                            ).astype(jnp.uint32)
                    in_t = ok & (offu < jnp.uint32(g))
                    off = offu.astype(jnp.int32)
                else:
                    off64 = pk.data.astype(jnp.int64) - kmin
                    in_t = ok & (off64 >= 0) & (off64 < g)
                    off = off64.astype(jnp.int32)
                slot = jnp.where(in_t, off, g)
                bsel1 = jnp.take(bidx1_tab, slot, mode="clip")
                matched = in_t & (bsel1 > 0)
                if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
                    keep = (ctx.row_mask & ~matched
                            if jt == JoinType.LEFT_ANTI
                            else matched)
                    return keep
                bsel = jnp.where(matched, bsel1 - 1, 0)
                # random-access passes dominate here (~70ns/row each):
                # one bidx1 lookup + one packed-validity lookup + the
                # narrowest possible per-column payload gather, with
                # the build KEY column rematerialized from the probe
                # key (equi-join: matched-row values are equal)
                vm = jnp.take(vmask_tab, slot, mode="clip")
                vbits = validity_bit_assignment(bcols)
                bout = []
                for ci, c in enumerate(bcols):
                    if ci in vbits:
                        valid = matched & (((vm >> vbits[ci]) & 1) != 0)
                    else:
                        valid = matched & jnp.take(c.validity, bsel,
                                                   mode="clip")
                    if (remat_ord == ci
                            and pk.data.dtype == c.data.dtype
                            and not c.dtype.is_string):
                        # matched implies the build key is non-null
                        bout.append(ColumnVector(
                            c.dtype, pk.data, matched, None, pk.narrow))
                    elif c.dtype.is_string:
                        bout.append(c.gather(bsel, matched))
                    else:
                        bout.append(gather_narrowest(c, bsel, valid))
                return bout, matched
            return kernel

        return self._join_cache.get_or_build(
            key, build_fn, meta=self.kp_meta("join-dense"))

    def _execute_dense(self, build, tab) -> Iterator[ColumnarBatch]:
        kmin, g, bidx1_tab, vmask_tab = tab
        jt = self.join_type
        kmin_op = jnp.int64(kmin)
        i32 = np.iinfo(np.int32)
        narrow_ok = i32.min <= kmin and kmin + g <= i32.max

        def probe_one(pb: ColumnarBatch) -> ColumnarBatch:
            with self.metrics.timed(M.TOTAL_TIME):
                kern = self._dense_probe_kernel(build, pb, g, narrow_ok)
                args = (pb.columns, pb.num_rows_i32, build.columns,
                        bidx1_tab, vmask_tab, kmin_op)
                if pb.sparse is not None:
                    args = args + (pb.sparse,)
                if jt in _PROBE_ONLY:
                    keep = kern(*args)
                    return ColumnarBatch(self._schema, pb.columns,
                                         None, pb.checks, sparse=keep)
                elif jt == JoinType.INNER:
                    bout, matched = kern(*args)
                    return self._assemble_sparse(pb.columns, bout,
                                                 matched, pb.checks)
                else:  # LEFT/RIGHT OUTER (probe side preserved)
                    bout, _ = kern(*args)
                    return self._assemble_sparse(pb.columns, bout,
                                                 pb.sparse, pb.checks,
                                                 rows=pb._rows)

        for it in self._probe.execute_partitions():
            for pb in it:
                if not pb.maybe_nonempty():
                    continue
                # probe rows are independent given a fixed build table,
                # so the probe side is fully split-and-retry-able
                for out in self.oom_retry_batches(
                        pb, probe_one,
                        label=f"{self.name()}.denseProbe"):
                    if out.maybe_nonempty():
                        self.update_output_metrics(out)
                        yield out

    def _assemble_sparse(self, pcols, bcols, sparse, checks, rows=None):
        if self._flip:
            cols = list(bcols) + list(pcols)
        else:
            cols = list(pcols) + list(bcols)
        return ColumnarBatch(self._schema, cols,
                             rows if sparse is None or rows is not None
                             else None,
                             checks, sparse=sparse)

    # -- execution --------------------------------------------------------
    def children_coalesce_goal(self):
        # build side needs a single batch
        return [None, RequireSingleBatch()] if not self._flip else \
            [RequireSingleBatch(), None]

    def _collect_build_batches(self) -> list[ColumnarBatch]:
        return [b.dense() for it in self._build.execute_partitions()
                for b in it if b.maybe_nonempty()]

    def _concat_build(self, batches: list[ColumnarBatch]) -> ColumnarBatch:
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch
            return empty_batch(self._build.output_schema())
        if len(batches) == 1:
            return batches[0]
        # the build-side concat is the join's known OOM hotspot, and a
        # hash join needs the build side WHOLE (single-batch contract),
        # so pressure here spills + retries in place — no split
        from spark_rapids_tpu.memory import retry as R
        nbytes = 2 * sum(b.device_size_bytes() for b in batches)
        return R.with_retry(lambda: concat_batches(batches),
                            out_bytes=nbytes, metrics=self.metrics,
                            label=f"{self.name()}.buildSide")

    def _build_batch(self) -> ColumnarBatch:
        return self._concat_build(self._collect_build_batches())

    def _grace_candidate_batches(self) -> Optional[list[ColumnarBatch]]:
        """Raw build batches when the grace-hash lane may apply, None
        when the build side must be taken whole (broadcast)."""
        if not self._build_keys or not self._probe_keys:
            return None
        return self._collect_build_batches()

    def _assemble(self, pout, bout, n) -> ColumnarBatch:
        """Order output columns as (left, right) regardless of probe side."""
        if self._flip:
            cols = list(bout) + list(pout)
        else:
            cols = list(pout) + list(bout)
        return ColumnarBatch(self._schema, cols, n)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory import oocore as OC
        batches = self._grace_candidate_batches()
        if batches is not None:
            conf = C.get_active_conf()
            est = 2 * sum(b.device_size_bytes() for b in batches)
            if OC.should_go_external(est, conf):
                from spark_rapids_tpu.utils import profile as P
                P.event(P.EV_OOCORE_DEGRADE, op=self.name(),
                        est_bytes=est, algo="grace-hash")
                probe_src = (pb for it in self._probe.execute_partitions()
                             for pb in it if pb.maybe_nonempty())
                yield from self._grace_join(iter(batches), probe_src,
                                            0, conf)
                return
            build = self._concat_build(batches)
        else:
            build = self._build_batch()
        if self._dense_qual:
            tab = self._try_dense_table(build)
            if tab is not None:
                yield from self._execute_dense(build, tab)
                return
        probe_src = (pb for it in self._probe.execute_partitions()
                     for pb in it)
        yield from self._join_stream(build, probe_src)

    def _join_stream(self, build: ColumnarBatch,
                     probe_batches) -> Iterator[ColumnarBatch]:
        """Sort-path join of one WHOLE build batch against a stream of
        probe batches (the former execute_columnar body, factored out
        so the grace-hash lane can run it once per key partition —
        per-partition FULL_OUTER unmatched-build emission is sound
        because key-hash partitions are key-disjoint)."""
        jt = self.join_type
        outer_probe = jt in (JoinType.LEFT_OUTER, JoinType.RIGHT_OUTER,
                             JoinType.FULL_OUTER)
        bmatched_total = np.zeros(build.capacity, bool)

        def probe_one(pb: ColumnarBatch) -> ColumnarBatch:
            pb = pb.dense()
            with self.metrics.timed(M.TOTAL_TIME):
                mk = self._match_kernel(build, pb)
                counts_p, start_p, perm, bmatched, total_inner = mk(
                    build.columns, jnp.int32(build.num_rows),
                    pb.columns, jnp.int32(pb.num_rows))
                if jt == JoinType.FULL_OUTER:
                    # in-place OR: the flags accumulate across probe
                    # batches AND split pieces (build rows matched by
                    # any piece stay matched)
                    np.logical_or(bmatched_total,
                                  np.asarray(bmatched)[:build.capacity],
                                  out=bmatched_total)
                if jt in _PROBE_ONLY:
                    sk = self._semi_kernel(pb, jt == JoinType.LEFT_ANTI)
                    cols, n = sk(pb.columns, counts_p,
                                 jnp.int32(pb.num_rows))
                    CK.note_host_sync("join.expand", nbytes=4)
                    return ColumnarBatch(self._schema, list(cols), int(n))
                # per-probe-batch host sync: the expand kernel's output
                # capacity must be a HOST int (it keys the compile)
                CK.note_host_sync("join.expand", nbytes=4)
                total = int(total_inner)
                if outer_probe:
                    total = total + pb.num_rows  # upper bound
                out_cap = bucket_capacity(max(total, 1))
                ek = self._expand_kernel(build, pb, out_cap, outer_probe)
                pout, bout, tot = ek(build.columns, pb.columns,
                                     counts_p, start_p, perm,
                                     jnp.int32(pb.num_rows))
                out = self._assemble(pout, bout, int(tot))
                if self.condition is not None:
                    out = self._apply_condition(out)
                return out

        for pb in probe_batches:
            if not pb.maybe_nonempty():
                continue
            # probe rows are independent given the fixed build side
            # (FULL_OUTER's unmatched-build flags OR across pieces),
            # so probe batches split-and-retry freely while the pair
            # expansion's out_cap shrinks with each piece
            for out in self.oom_retry_batches(
                    pb, probe_one, label=f"{self.name()}.probe"):
                if out.num_rows > 0:
                    self.update_output_metrics(out)
                    yield out
        if jt == JoinType.FULL_OUTER:
            un = self._unmatched_build(build, bmatched_total)
            if un is not None and un.num_rows > 0:
                self.update_output_metrics(un)
                yield un

    # -- grace-hash out-of-core lane ---------------------------------------
    #: base seed for grace partition hashing — deliberately NOT Spark's
    #: seed 42: an upstream HashPartitioning shuffle on the same keys
    #: already bucketed rows by murmur3@42 pmod N, and re-hashing with
    #: the same seed would correlate perfectly and collapse every grace
    #: partition into one
    _GRACE_SALT_BASE = 104729

    def _grace_partition_side(self, batches, bound_keys, nparts: int,
                              depth: int, side: str, conf) -> list[list]:
        """Hash-partition one side of the join by its key columns and
        spill every non-empty slice as an out-of-core run.  The salt is
        a traced kernel argument (one compile serves every recursion
        depth) that varies per depth, so keys that collided at depth d
        scatter at depth d+1."""
        from jax import lax
        from spark_rapids_tpu.memory import oocore as OC
        from spark_rapids_tpu.ops.murmur3 import murmur3_row_hash
        from spark_rapids_tpu.shuffle.partitioning import (
            _slice_partitions, _split_kernel_for)

        def pid_fn(ctx, salt, extra):
            keys = [e.eval(ctx) for e in bound_keys]
            h = murmur3_row_hash(keys, seed=salt)
            m = lax.rem(h, jnp.int32(nparts))
            return jnp.where(m < 0, m + nparts, m)

        salt = jnp.uint32(self._GRACE_SALT_BASE + depth)
        parts: list[list] = [[] for _ in range(nparts)]
        for batch in batches:
            kern = _split_kernel_for(self._join_cache, batch, pid_fn,
                                     nparts, ("grace", side))
            cols, counts = kern(batch.columns, batch.num_rows_i32,
                                salt, (), batch.sparse)
            slices = _slice_partitions(cols, counts, batch.schema,
                                       batch.capacity, batch.checks)
            for p, s in enumerate(slices):
                if s is None or not s.maybe_nonempty():
                    continue
                parts[p].append(OC.spill_run(
                    s.dense(), label=self.name(),
                    metrics=self.metrics, conf=conf))
        return parts

    def _read_runs(self, runs) -> Iterator[ColumnarBatch]:
        for r in runs:
            b = r.read(self.metrics)
            r.free()
            yield b

    def _grace_join(self, build_src, probe_src, depth: int,
                    conf) -> Iterator[ColumnarBatch]:
        """Grace-hash join: partition BOTH sides by key hash into
        spilled runs, join each partition pair that fits the HBM window
        with the normal sort-path core, and recurse (new salt) on pairs
        whose build side still does not fit.  Bounded by
        `oocore.maxRecursionDepth` — irreducible key skew past it is a
        descriptive error, never a hang and never partial data."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory import oocore as OC
        from spark_rapids_tpu.memory.retry import TpuOutOfCoreError
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        jt = self.join_type
        nparts = max(2, int(conf[C.OOCORE_GRACE_PARTITIONS]))
        max_depth = max(1, int(conf[C.OOCORE_MAX_RECURSION]))
        window = OC.window_bytes(conf)
        self.metrics.add(M.NUM_GRACE_PARTITIONS, nparts)
        P.event(P.EV_OOCORE_GRACE_PARTITION, op=self.name(),
                num_partitions=nparts, depth=depth)
        build_parts = self._grace_partition_side(
            build_src, self._build_keys, nparts, depth, "build", conf)
        probe_parts = self._grace_partition_side(
            probe_src, self._probe_keys, nparts, depth, "probe", conf)
        for p in range(nparts):
            W.check_cancelled()
            bruns, pruns = build_parts[p], probe_parts[p]
            if not bruns and not pruns:
                continue
            if not pruns and jt != JoinType.FULL_OUTER:
                # build rows with no probe rows only matter to
                # FULL_OUTER's unmatched-build emission
                for r in bruns:
                    r.free()
                continue
            if not bruns and jt in (JoinType.INNER, JoinType.LEFT_SEMI):
                for r in pruns:
                    r.free()
                continue
            best = 2 * sum(r.meta.size_bytes for r in bruns)
            if bruns and best > window:
                if depth + 1 >= max_depth:
                    raise TpuOutOfCoreError(
                        f"{self.name()}: grace-hash build partition {p} "
                        f"is still ~{best} bytes (window {window}) at "
                        f"recursion depth {depth + 1} with "
                        f"spark.rapids.memory.oocore.maxRecursionDepth="
                        f"{max_depth} — the join key is too skewed to "
                        f"partition further (one hot key larger than "
                        f"the window); raise the HBM budget, "
                        f"oocore.windowFraction, or maxRecursionDepth")
                P.event(P.EV_OOCORE_RECURSE, op=self.name(),
                        depth=depth + 1, partition=p)
                yield from self._grace_join(
                    self._read_runs(bruns), self._read_runs(pruns),
                    depth + 1, conf)
                continue
            build_batches = [b.dense() for b in self._read_runs(bruns)]
            build = self._concat_build(
                [b for b in build_batches if b.maybe_nonempty()])
            yield from self._join_stream(build, self._read_runs(pruns))

    def _apply_condition(self, batch: ColumnarBatch) -> ColumnarBatch:
        from spark_rapids_tpu.exec.basic import FilterExec, LocalBatchSource
        f = getattr(self, "_cond_filter", None)
        if f is None:
            src = LocalBatchSource([[]], schema=self._schema)
            f = FilterExec(self.condition, src)
            self._cond_filter = f
        out = list(f.process_partition(iter([batch])))
        return out[0]

    def _unmatched_build(self, build: ColumnarBatch,
                         matched: np.ndarray) -> Optional[ColumnarBatch]:
        """FULL OUTER: build rows never matched, with null probe side."""
        if build.num_rows == 0:
            return None
        unmatched = ~matched[: build.num_rows]
        idx = np.nonzero(unmatched)[0]
        if len(idx) == 0:
            return None
        cap = bucket_capacity(len(idx))
        sel = jnp.asarray(np.pad(idx, (0, cap - len(idx))))
        valid = jnp.arange(cap) < len(idx)
        bout = [c.gather(sel, valid) for c in build.columns]
        # null probe columns
        from spark_rapids_tpu.columnar.batch import empty_batch
        pschema = self._probe.output_schema()
        nulls = []
        for f in pschema.fields:
            from spark_rapids_tpu.exprs.base import Literal
            lv = Literal(None, f.dtype)
            ctx = make_eval_context([], cap, jnp.int32(len(idx)))
            nulls.append(lv.eval(ctx))
        return self._assemble(nulls, bout, len(idx))

    def output_partition_count(self) -> int:
        return 1

    def execute_partitions(self):
        return [self.execute_columnar()]


class BroadcastHashJoinExec(HashJoinExec):
    """Same join core; the build side comes from a BroadcastExchangeExec
    so every probe partition reuses one broadcast batch (reference
    GpuBroadcastHashJoinExec)."""

    def _build_batch(self) -> ColumnarBatch:
        from spark_rapids_tpu.shuffle.exchange import BroadcastExchangeExec
        if isinstance(self._build, BroadcastExchangeExec):
            return self._build.broadcast_batch()
        return super()._build_batch()

    def _grace_candidate_batches(self) -> Optional[list[ColumnarBatch]]:
        # a broadcast build side is already materialized whole (and
        # shared across consumers) — grace repartitioning it here would
        # not bound anything the broadcast did not already pay
        from spark_rapids_tpu.shuffle.exchange import BroadcastExchangeExec
        if isinstance(self._build, BroadcastExchangeExec):
            return None
        return super()._grace_candidate_batches()


class NestedLoopJoinExec(TpuExec):
    """Brute-force cross/conditioned join (reference
    GpuBroadcastNestedLoopJoinExec / GpuCartesianProductExec — both
    disabled by default there for OOM risk; here the pair expansion is
    bucketed so memory stays bounded per batch pair)."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 condition: Optional[Expression] = None,
                 join_type: JoinType = JoinType.CROSS):
        super().__init__(left, right)
        if join_type not in (JoinType.CROSS, JoinType.INNER):
            raise ValueError("nested loop join supports cross/inner only")
        self.condition = condition
        self._schema = T.Schema(tuple(left.output_schema().fields) +
                                tuple(right.output_schema().fields))
        from spark_rapids_tpu.exprs.base import fingerprint
        self._cache = KernelCache((
            "NestedLoopJoinExec", join_type.name, fingerprint(condition),
            fingerprint(self._schema)))

    def output_schema(self):
        return self._schema

    def _pair_kernel(self, lb: ColumnarBatch, rb: ColumnarBatch):
        key = ("nlj", batch_signature(lb), batch_signature(rb))

        def build_fn():
            lcap, rcap = lb.capacity, rb.capacity
            out_cap = lcap * rcap

            @jax.jit
            def kernel(lcols, lnum, rcols, rnum):
                k = jnp.arange(out_cap)
                li = k // rcap
                ri = k % rcap
                valid = (li < lnum) & (ri < rnum)
                lout = [c.gather(jnp.where(valid, li, 0), valid)
                        for c in lcols]
                rout = [c.gather(jnp.where(valid, ri, 0), valid)
                        for c in rcols]
                # compact valid pairs to the front
                n = valid.sum().astype(jnp.int32)
                (idx,) = jnp.nonzero(valid, size=out_cap,
                                     fill_value=out_cap - 1)
                ok = jnp.arange(out_cap) < n
                lout = [c.gather(idx, ok) for c in lout]
                rout = [c.gather(idx, ok) for c in rout]
                return lout, rout, n

            return kernel

        return self._cache.get_or_build(
            key, build_fn, meta=self.kp_meta("join-nlj"))

    def execute_columnar(self):
        right_batches = [b.dense() for it in
                         self.children[1].execute_partitions()
                         for b in it if b.maybe_nonempty()]
        right_batches = [b for b in right_batches if b.num_rows > 0]
        # pair-expansion budget: the kernel materializes lcap*rcap
        # output rows, so the LEFT side is sharded until one pair
        # block's bytes fit target_size_bytes (the knob the planner
        # threads from spark.rapids.sql.batchSizeBytes — reference
        # GpuBroadcastNestedLoopJoinExec's targetSizeBytes)
        tsb = int(getattr(self, "target_size_bytes", 0)) or (1 << 30)
        row_bytes = max(8 * len(self._schema.fields), 1)
        for it in self.children[0].execute_partitions():
            for lb in it:
                if not lb.maybe_nonempty():
                    continue
                lb = lb.dense()
                if lb.num_rows == 0:
                    continue
                for rb in right_batches:
                    max_left = max(1, tsb // (row_bytes * rb.capacity))
                    pieces = ([lb] if lb.capacity <= max_left else
                              [lb.slice(lo, min(max_left,
                                                lb.num_rows - lo))
                               for lo in range(0, lb.num_rows, max_left)])
                    for piece in pieces:
                        with self.metrics.timed(M.TOTAL_TIME):
                            kern = self._pair_kernel(piece, rb)
                            lout, rout, n = kern(
                                piece.columns, jnp.int32(piece.num_rows),
                                rb.columns, jnp.int32(rb.num_rows))
                            out = ColumnarBatch(
                                self._schema, list(lout) + list(rout),
                                int(n))
                            if self.condition is not None:
                                out = self._apply_condition(out)
                        if out.num_rows:
                            self.update_output_metrics(out)
                            yield out

    def _apply_condition(self, batch):
        from spark_rapids_tpu.exec.basic import FilterExec, LocalBatchSource
        f = getattr(self, "_cond_filter", None)
        if f is None:
            src = LocalBatchSource([[]], schema=self._schema)
            f = FilterExec(self.condition, src)
            self._cond_filter = f
        return list(f.process_partition(iter([batch])))[0]

    def output_partition_count(self) -> int:
        return 1

    def execute_partitions(self):
        return [self.execute_columnar()]


def CartesianProductExec(left: TpuExec, right: TpuExec,
                         condition=None) -> NestedLoopJoinExec:
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.shims import current_shims
    return current_shims(C.get_active_conf()).make_nested_loop_join(
        JoinType.CROSS, left, right, condition)
