"""Window operator (reference `GpuWindowExec.scala:99,177` +
`GpuWindowExpression.scala`: rows-frames, range-frames-on-timestamp,
row_number, min/max/sum/count/avg window functions).

TPU design: one jitted kernel per batch sorts rows by (partition keys,
order keys), computes partition segments, evaluates every window function
over the sorted layout, then scatters results back to the original row
order (Spark preserves input order semantics only per-partition; we
restore the exact input order).

Frame math is all O(n) or O(n log n) vectorized:
  - running (UNBOUNDED PRECEDING..CURRENT): segment-local cumulative ops
    via global cumsum minus segment-start offset;
  - whole-partition (UNBOUNDED..UNBOUNDED): segment reduce + gather;
  - sliding rows-frames: prefix-sum differences with bounds clamped to
    the segment;
  - range frames: vectorized binary search (log2(cap) steps) over the
    (segment, order-value) lexicographic order.

The exec requires its child coalesced to a single batch per partition
group (RequireSingleBatch), the same contract as the reference.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exec.base import (
    CoalesceGoal, RequireSingleBatch, TpuExec, UnaryExecBase,
    batch_signature, make_eval_context)
from spark_rapids_tpu.exec.sort import SortOrder
from spark_rapids_tpu.exprs.base import Expression, output_name
from spark_rapids_tpu.ops.sort_encode import (
    hash_prefix_sort_bounds, sort_with_bounds, wide_key_set)
from spark_rapids_tpu.utils import checks as CK
from spark_rapids_tpu.utils import metrics as M

UNBOUNDED = None
CURRENT_ROW = 0


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """rows/range frame; bounds: None = unbounded, int offsets otherwise
    (negative = preceding, positive = following, 0 = current row)."""
    is_rows: bool = True
    lower: Optional[int] = UNBOUNDED   # default UNBOUNDED PRECEDING
    upper: Optional[int] = CURRENT_ROW  # default CURRENT ROW


@dataclasses.dataclass
class WindowSpec:
    partition_by: Sequence[Expression]
    order_by: Sequence[SortOrder] = ()
    frame: WindowFrame = WindowFrame()


@dataclasses.dataclass
class WindowFunction:
    kind: str                      # row_number, rank, dense_rank, lead,
    # lag, sum, min, max, count, avg, first, last
    child: Optional[Expression] = None
    offset: int = 1                # for lead/lag
    default: Optional[object] = None

    def alias(self, name):
        return (self, name)


def RowNumber():
    return WindowFunction("row_number")


def Rank():
    return WindowFunction("rank")


def DenseRank():
    return WindowFunction("dense_rank")


def Lead(e, offset=1, default=None):
    return WindowFunction("lead", e, offset, default)


def Lag(e, offset=1, default=None):
    return WindowFunction("lag", e, offset, default)


def WinSum(e):
    return WindowFunction("sum", e)


def WinMin(e):
    return WindowFunction("min", e)


def WinMax(e):
    return WindowFunction("max", e)


def WinCount(e):
    return WindowFunction("count", e)


def WinAvg(e):
    return WindowFunction("avg", e)


def _result_type(fn: WindowFunction, schema) -> T.DataType:
    if fn.kind in ("row_number", "rank", "dense_rank"):
        return T.INT32
    if fn.kind == "count":
        return T.INT64
    if fn.kind == "avg":
        return T.FLOAT64
    dt = fn.child.data_type(schema)
    if fn.kind == "sum":
        return T.FLOAT64 if dt.is_floating else T.INT64
    return dt


def _lex_searchsorted(seg, vals, q_seg, q_vals, side: str, cap: int):
    """Vectorized binary search over rows sorted by (seg, vals):
    first index where (seg, vals) >/>= (q_seg, q_vals)."""
    lo = jnp.zeros(q_seg.shape, jnp.int32)
    hi = jnp.full(q_seg.shape, cap, jnp.int32)
    steps = max(1, math.ceil(math.log2(max(cap, 2))) + 1)
    for _ in range(steps):
        mid = (lo + hi) // 2
        ms = jnp.take(seg, mid, mode="clip")
        mv = jnp.take(vals, mid, mode="clip")
        if side == "left":
            go_right = (ms < q_seg) | ((ms == q_seg) & (mv < q_vals))
        else:
            go_right = (ms < q_seg) | ((ms == q_seg) & (mv <= q_vals))
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


class WindowExec(UnaryExecBase):
    def __init__(self, window_exprs: Sequence, spec: WindowSpec,
                 child: TpuExec):
        """window_exprs: list of (WindowFunction, name) or WindowFunction."""
        super().__init__(child)
        self.spec = spec
        self.fns = []
        child_schema = child.output_schema()
        self._child_schema = child_schema
        names = []
        for i, w in enumerate(window_exprs):
            fn, name = w if isinstance(w, tuple) else (w, f"w{i}")
            self.fns.append(fn)
            names.append(name)
        self._bound_parts = [e.bind(child_schema)
                             for e in spec.partition_by]
        self._bound_order = [
            SortOrder(o.expr.bind(child_schema), o.ascending,
                      o.nulls_first) for o in spec.order_by]
        self._bound_inputs = [
            fn.child.bind(child_schema) if fn.child is not None else None
            for fn in self.fns]
        fields = list(child_schema.fields) + [
            T.Field(n, _result_type(fn, child_schema))
            for fn, n in zip(self.fns, names)]
        self._schema = T.Schema(tuple(fields))

    def output_schema(self):
        return self._schema

    def children_coalesce_goal(self) -> list[Optional[CoalesceGoal]]:
        return [RequireSingleBatch()]

    def describe(self):
        return (f"WindowExec([{', '.join(f.kind for f in self.fns)}], "
                f"partitionBy={len(self.spec.partition_by)})")

    def cache_scope(self):
        from spark_rapids_tpu.exprs.base import fingerprint
        return (fingerprint(self.spec), fingerprint(self._bound_parts),
                fingerprint(self._bound_order),
                fingerprint(self._bound_inputs), fingerprint(self.fns))

    # ------------------------------------------------------------------
    def _use_hash_partitions(self, batch: ColumnarBatch) -> bool:
        """Wide PARTITION BY key sets (string partitions explode into
        one 9-bit sort key per char position) sort by two murmur3
        words instead — partition order is unobservable in window
        results, only the grouping and the ORDER BY within it matter.
        Same retry/deopt contract as the aggregate's hash lane."""
        if not self._bound_parts or CK.is_retrying() or \
                getattr(self, "_hash_parts_disabled", False):
            return False
        from spark_rapids_tpu import config as C
        if not C.get_active_conf()[C.HASH_GROUPING_ENABLED]:
            return False
        return wide_key_set(self._bound_parts, batch, self._child_schema)

    def _disable_hash_partitions(self) -> None:
        self._hash_parts_disabled = True

    def _kernel(self, batch: ColumnarBatch):
        use_hash = self._use_hash_partitions(batch)
        key = ("window", use_hash, batch_signature(batch))

        def build():
            cap = batch.capacity
            frame = self.spec.frame

            @jax.jit
            def kernel(columns, num_rows):
                ctx = make_eval_context(columns, cap, num_rows)
                parts = [e.eval(ctx) for e in self._bound_parts]
                orders = [o.expr.eval(ctx) for o in self._bound_order]
                okeys = [(o, so.ascending, so.resolved_nulls_first)
                         for o, so in zip(orders, self._bound_order)]
                if use_hash:
                    perm, sorted_mask, pbounds, obounds_all, collision = \
                        hash_prefix_sort_bounds(parts, okeys,
                                                ctx.row_mask)
                else:
                    keyspec = [(p, True, True) for p in parts] + okeys
                    perm, sorted_mask, pbounds, obounds_all = \
                        sort_with_bounds(keyspec, ctx.row_mask,
                                         prefix=len(parts))
                    collision = None
                # partition segments (partition keys only)
                if parts:
                    bounds = pbounds
                else:
                    bounds = (jnp.arange(cap) == 0) & sorted_mask
                seg = jnp.cumsum(bounds.astype(jnp.int32)) - 1
                seg = jnp.where(sorted_mask, seg, cap)
                pos = jnp.arange(cap, dtype=jnp.int32)
                (seg_start_idx,) = jnp.nonzero(bounds, size=cap,
                                               fill_value=cap - 1)
                seg_start = jnp.take(seg_start_idx,
                                     jnp.clip(seg, 0, cap - 1))
                # per-segment exclusive end WITHOUT a scatter (XLA:TPU
                # serializes segment_sum): rows are partition-sorted
                # with invalid rows last, so segment s ends where s+1
                # starts, and the LAST segment ends at num_rows
                num_segs = bounds.sum().astype(jnp.int32)
                nxt = jnp.concatenate(
                    [seg_start_idx[1:],
                     jnp.full((1,), cap, seg_start_idx.dtype)])
                seg_end_by_id = jnp.where(
                    jnp.arange(cap) >= num_segs - 1,
                    jnp.asarray(num_rows, jnp.int32), nxt.astype(jnp.int32))
                seg_end = jnp.take(seg_end_by_id,
                                   jnp.clip(seg, 0, cap - 1))  # exclusive

                # order-key change flags (for rank/dense_rank)
                obounds = obounds_all if orders else bounds

                # frame bounds [lo, hi) per row, shared by all functions
                if frame.is_rows:
                    lo = seg_start if frame.lower is None else \
                        jnp.maximum(pos + frame.lower, seg_start)
                    hi = seg_end if frame.upper is None else \
                        jnp.minimum(pos + frame.upper + 1, seg_end)
                    hi = jnp.maximum(hi, lo)
                else:
                    # RANGE frame: single integer/date/timestamp order key
                    assert len(orders) == 1, \
                        "range frames need exactly one order key"
                    oc = orders[0].gather(perm, sorted_mask)
                    ovals = oc.data.astype(jnp.int64)
                    seg_q = jnp.where(sorted_mask, seg, cap)
                    if frame.lower is None:
                        lo = seg_start
                    else:
                        lo = _lex_searchsorted(
                            seg_q, ovals, seg_q, ovals + frame.lower,
                            "left", cap).astype(jnp.int32)
                        lo = jnp.maximum(lo, seg_start)
                    if frame.upper is None:
                        hi = seg_end
                    else:
                        hi = _lex_searchsorted(
                            seg_q, ovals, seg_q, ovals + frame.upper,
                            "right", cap).astype(jnp.int32)
                        hi = jnp.minimum(hi, seg_end)
                    hi = jnp.maximum(hi, lo)

                results = []
                for fn, bin_ in zip(self.fns, self._bound_inputs):
                    if bin_ is not None:
                        v = bin_.eval(ctx)
                        sv = v.gather(perm, sorted_mask)
                    else:
                        sv = None
                    results.append(self._eval_fn(
                        fn, sv, pos, seg, seg_start, seg_end, obounds,
                        sorted_mask, cap, lo, hi))

                # scatter back to original row order
                inv = jnp.zeros(cap, jnp.int32).at[perm].set(
                    pos, mode="drop")
                out = []
                for r in results:
                    out.append(r.gather(inv, ctx.row_mask))
                return list(columns) + out, collision

            return kernel

        return self.kernels.get_or_build(
            key, build, meta=self.kp_meta("window"))

    def _eval_fn(self, fn, sv, pos, seg, seg_start, seg_end, obounds,
                 sorted_mask, cap, lo, hi) -> ColumnVector:
        k = fn.kind
        if k == "row_number":
            data = (pos - seg_start + 1).astype(jnp.int32)
            return ColumnVector(T.INT32, data, sorted_mask)
        if k in ("rank", "dense_rank"):
            # dense: count of order-changes within segment up to row
            ochange = obounds.astype(jnp.int32)
            cum_o = jnp.cumsum(ochange)
            start_o = jnp.take(cum_o, seg_start)
            dense = cum_o - start_o + 1
            if k == "dense_rank":
                return ColumnVector(T.INT32, dense.astype(jnp.int32),
                                    sorted_mask)
            # rank: position of first row of the tie group
            (grp_first,) = jnp.nonzero(obounds, size=cap,
                                       fill_value=cap - 1)
            tie_start = jnp.take(grp_first,
                                 jnp.clip(cum_o - 1, 0, cap - 1))
            data = (tie_start - seg_start + 1).astype(jnp.int32)
            return ColumnVector(T.INT32, data, sorted_mask)
        if k in ("lead", "lag"):
            off = fn.offset if k == "lead" else -fn.offset
            src = pos + off
            in_seg = (src >= seg_start) & (src < seg_end)
            got = sv.gather(jnp.clip(src, 0, cap - 1), in_seg & sorted_mask)
            if fn.default is not None:
                from spark_rapids_tpu.exprs.base import Literal
                # fill out-of-frame with the default literal
                dv = Literal.of(fn.default)
                dctx = make_eval_context([], cap, jnp.int32(cap))
                dcol = dv.eval(dctx)
                from spark_rapids_tpu.exprs.conditional import _select
                got = _select(in_seg, got, dcol)
                got = ColumnVector(got.dtype, got.data,
                                   jnp.where(in_seg, got.validity,
                                             sorted_mask), got.lengths)
            return got

        # frame-aggregates ------------------------------------------------
        ok = sv.validity & sorted_mask
        if k == "count":
            c = ok.astype(jnp.int64)
            ps = jnp.cumsum(c)
            total = _range_sum(ps, lo, hi)
            return ColumnVector(T.INT64, total, sorted_mask)
        if k in ("sum", "avg"):
            acc_t = jnp.float64 if (sv.dtype.is_floating or k == "avg") \
                else jnp.int64
            vals = jnp.where(ok, sv.data.astype(acc_t), 0)
            ps = jnp.cumsum(vals)
            s = _range_sum(ps, lo, hi)
            cnt = _range_sum(jnp.cumsum(ok.astype(jnp.int64)), lo, hi)
            if k == "sum":
                dt = T.FLOAT64 if sv.dtype.is_floating else T.INT64
                return ColumnVector(dt, s.astype(dt.storage_dtype),
                                    sorted_mask & (cnt > 0))
            avg = s.astype(jnp.float64) / jnp.where(cnt > 0, cnt, 1)
            return ColumnVector(T.FLOAT64, avg, sorted_mask & (cnt > 0))
        if k in ("min", "max"):
            return self._minmax_frame(sv, ok, lo, hi, cap, k == "min",
                                      sorted_mask)
        if k in ("first", "last"):
            idx = lo if k == "first" else hi - 1
            got = sv.gather(jnp.clip(idx, 0, cap - 1),
                            sorted_mask & (hi > lo))
            return got
        raise ValueError(f"unsupported window function {k}")

    def _minmax_frame(self, sv, ok, lo, hi, cap, is_min, sorted_mask):
        """Sliding min/max via sparse segment-tree style prefix tables:
        O(n log n) doubling table (sparse table RMQ)."""
        if sv.dtype.is_string:
            raise NotImplementedError("string window min/max")
        if sv.dtype.is_floating:
            fill = jnp.inf if is_min else -jnp.inf
            vals = jnp.where(ok, sv.data.astype(jnp.float64), fill)
        else:
            info = jnp.iinfo(jnp.int64)
            fill = info.max if is_min else info.min
            vals = jnp.where(ok, sv.data.astype(jnp.int64), fill)
        levels = [vals]
        span = 1
        while span < cap:
            prev = levels[-1]
            shifted = jnp.roll(prev, -span)
            pad_fill = jnp.asarray(fill, prev.dtype)
            shifted = jnp.where(jnp.arange(cap) + span < cap, shifted,
                                pad_fill)
            levels.append(jnp.minimum(prev, shifted) if is_min
                          else jnp.maximum(prev, shifted))
            span *= 2
        # RMQ query [lo, hi): k = floor(log2(hi-lo))
        length = jnp.maximum(hi - lo, 1)
        k = (jnp.log2(length.astype(jnp.float64))).astype(jnp.int32)
        k = jnp.clip(k, 0, len(levels) - 1)
        stacked = jnp.stack(levels)  # [L, cap]
        a = stacked[k, jnp.clip(lo, 0, cap - 1)]
        b_idx = jnp.clip(hi - (1 << k.astype(jnp.int64)), 0, cap - 1)
        b = stacked[k, b_idx]
        red = jnp.minimum(a, b) if is_min else jnp.maximum(a, b)
        has = hi > lo
        # count valid in range to set validity
        cnt = _range_sum(jnp.cumsum(ok.astype(jnp.int64)), lo, hi)
        return ColumnVector(sv.dtype, red.astype(sv.dtype.storage_dtype),
                            sorted_mask & has & (cnt > 0))

    def _window_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        with self.metrics.timed(M.TOTAL_TIME):
            kern = self._kernel(batch)
            cols, coll = kern(batch.columns, batch.num_rows_i32)
            checks = CK.register_deopt(
                coll, f"hashWindowParts[exec {self.exec_id}]",
                self._disable_hash_partitions, batch.checks)
            return ColumnarBatch(self._schema, list(cols),
                                 batch._rows, checks)

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.exec.coalesce import coalesce_iterator
        batches = coalesce_iterator(batches, RequireSingleBatch(),
                                    self._child_schema, self.metrics)
        for batch in batches:
            batch = batch.dense()
            # window frames read the WHOLE partition group
            # (RequireSingleBatch contract) — a row split would cut
            # partitions mid-frame, so pressure here takes the no-split
            # lane: spill + retry in place, floor fallback past that
            (out,) = tuple(self.oom_retry_batches(
                batch, self._window_one, split=False,
                label=self.name()))
            self.update_output_metrics(out)
            yield out


def _range_sum(prefix, lo, hi):
    """sum over [lo, hi) given inclusive prefix sums."""
    cap = prefix.shape[0]
    hi_v = jnp.where(hi > 0, jnp.take(prefix, jnp.clip(hi - 1, 0, cap - 1)),
                     0)
    lo_v = jnp.where(lo > 0, jnp.take(prefix, jnp.clip(lo - 1, 0, cap - 1)),
                     0)
    return hi_v - lo_v


# ---------------------------------------------------------------------------
# Planner-facing window node + independent CPU evaluation (the golden
# engine for window parity tests; Spark's WindowExec analog on the
# fallback side).  The override rule in plan/overrides.py converts it to
# the TPU WindowExec above.
from spark_rapids_tpu.plan.nodes import CpuNode as _CpuNode


class CpuWindow(_CpuNode):
    """CPU plan node: child columns + one column per window function."""

    def __init__(self, window_exprs: Sequence, spec: WindowSpec, child):
        super().__init__(child)
        self.spec = spec
        self.window_exprs = [
            w if isinstance(w, tuple) else (w, f"w{i}")
            for i, w in enumerate(window_exprs)]
        cs = child.output_schema()
        fields = list(cs.fields) + [
            T.Field(n, _result_type(fn, cs))
            for fn, n in self.window_exprs]
        self._schema = T.Schema(tuple(fields))

    def output_schema(self):
        return self._schema

    def output_partition_count(self) -> int:
        return 1

    def name(self) -> str:
        return "CpuWindow"

    def describe(self) -> str:
        return (f"CpuWindow([{', '.join(f.kind for f, _ in self.window_exprs)}]"
                f", partitionBy={len(self.spec.partition_by)})")

    def execute(self):
        import pandas as pd
        from spark_rapids_tpu.plan.nodes import empty_df, normalize_df
        parts = [df for it in self.child.execute() for df in it]
        cs = self.child.output_schema()
        df = (pd.concat(parts, ignore_index=True) if parts
              else empty_df(cs))
        out = _cpu_window_eval(df, cs, self.spec, self.window_exprs)
        return [iter([normalize_df(out, self._schema)])]


def _cpu_window_eval(df, child_schema, spec: WindowSpec, window_exprs):
    """Row-at-a-time reference implementation of the window semantics
    the TPU kernel vectorizes: per-partition sorted evaluation with
    rows/range frames (range CURRENT ROW includes peers, like Spark)."""
    import numpy as np
    import pandas as pd
    from spark_rapids_tpu.plan.cpu_eval import cpu_eval, nullable_dtype

    n = len(df)
    out = df.copy()
    results = {name: [None] * n for _, name in window_exprs}
    if n == 0:
        for fn, name in window_exprs:
            out[name] = pd.Series(
                [], dtype=nullable_dtype(_result_type(fn, child_schema)))
        return out

    pcols = [cpu_eval(e, df, child_schema) for e in spec.partition_by]
    ocols = [cpu_eval(o.expr, df, child_schema) for o in spec.order_by]

    def okey(i):
        key = []
        for s, o in zip(ocols, spec.order_by):
            v = s.iloc[i]
            null = pd.isna(v)
            # null ordering then direction, mirroring SortOrder's
            # resolved default (asc -> nulls first, desc -> nulls last)
            key.append((null != o.resolved_nulls_first,
                        _dirval(v, o.ascending, null)))
        return tuple(key)

    def pkey(i):
        return tuple(None if pd.isna(s.iloc[i]) else s.iloc[i]
                     for s in pcols)

    groups: dict = {}
    for i in range(n):
        groups.setdefault(pkey(i), []).append(i)

    frame = spec.frame
    fn_inputs = {name: (cpu_eval(fn.child, df, child_schema)
                        if fn.child is not None else None)
                 for fn, name in window_exprs}
    for rows in groups.values():
        rows.sort(key=okey)
        m = len(rows)
        order_vals = [okey(i) for i in rows]
        for fn, name in window_exprs:
            vals = fn_inputs[name]
            res = results[name]
            if fn.kind == "row_number":
                for pos, i in enumerate(rows):
                    res[i] = pos + 1
            elif fn.kind in ("rank", "dense_rank"):
                rank = dense = 0
                prev = object()
                for pos, i in enumerate(rows):
                    if order_vals[pos] != prev:
                        rank = pos + 1
                        dense += 1
                        prev = order_vals[pos]
                    res[i] = rank if fn.kind == "rank" else dense
            elif fn.kind in ("lead", "lag"):
                step = fn.offset if fn.kind == "lead" else -fn.offset
                for pos, i in enumerate(rows):
                    j = pos + step
                    if 0 <= j < m:
                        v = vals.iloc[rows[j]]
                        res[i] = None if pd.isna(v) else v
                    else:
                        res[i] = fn.default
            else:  # framed aggregates
                for pos, i in enumerate(rows):
                    lo, hi = _frame_bounds(frame, pos, m, order_vals)
                    window = [vals.iloc[rows[j]]
                              for j in range(lo, hi + 1)]
                    res[i] = _frame_agg(fn.kind, window)

    for fn, name in window_exprs:
        out[name] = pd.Series(results[name]).astype(
            nullable_dtype(_result_type(fn, child_schema)))
    return out


def _dirval(v, ascending: bool, null: bool):
    if null:
        return 0
    if ascending:
        return v
    if isinstance(v, str):
        # descending strings: inverted bytes + a terminator sentinel
        # larger than any inverted byte, so a prefix sorts AFTER its
        # extensions ("ab" before "a" descending)
        return tuple(255 - b for b in v.encode("utf-8")) + (256,)
    return -v


def _frame_bounds(frame: WindowFrame, pos: int, m: int, order_vals):
    if frame.is_rows:
        lo = 0 if frame.lower is None else max(0, pos + frame.lower)
        hi = m - 1 if frame.upper is None else min(m - 1,
                                                   pos + frame.upper)
        return lo, min(hi, m - 1)
    # range frame with UNBOUNDED / CURRENT ROW bounds: peers included
    if frame.lower is None:
        lo = 0
    elif frame.lower == 0:
        lo = pos
        while lo > 0 and order_vals[lo - 1] == order_vals[pos]:
            lo -= 1
    else:
        raise NotImplementedError(
            "CPU range frames support UNBOUNDED/CURRENT bounds")
    if frame.upper is None:
        hi = m - 1
    elif frame.upper == 0:
        hi = pos
        while hi < m - 1 and order_vals[hi + 1] == order_vals[pos]:
            hi += 1
    else:
        raise NotImplementedError(
            "CPU range frames support UNBOUNDED/CURRENT bounds")
    return lo, hi


def _frame_agg(kind: str, window: list):
    """`window` holds raw frame values INCLUDING nulls: first/last keep
    Spark's ignoreNulls=false semantics (a null boundary row yields
    null), the others skip nulls like their aggregate counterparts."""
    import pandas as pd
    if kind == "first":
        v = window[0] if window else None
        return None if v is None or pd.isna(v) else v
    if kind == "last":
        v = window[-1] if window else None
        return None if v is None or pd.isna(v) else v
    vals = [v for v in window if not pd.isna(v)]
    if kind == "count":
        return len(vals)
    if not vals:
        return None
    if kind == "sum":
        return sum(vals)
    if kind == "min":
        return min(vals)
    if kind == "max":
        return max(vals)
    if kind == "avg":
        return sum(vals) / len(vals)
    raise NotImplementedError(f"window agg {kind}")
