"""Sort operator (reference `GpuSortExec.scala:50-124`).

Local (per-partition) sort runs per batch; global sort requires its child
coalesced to a single batch (RequireSingleBatch goal), same contract as the
reference.  The whole sort — key encode, lexsort, gather of every column —
is one jitted kernel per batch bucket.
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import (
    CoalesceGoal, RequireSingleBatch, TpuExec, UnaryExecBase,
    batch_signature, make_eval_context)
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.ops.sort_encode import multi_key_argsort
from spark_rapids_tpu.utils import metrics as M


@dataclasses.dataclass(frozen=True)
class SortOrder:
    """Spark SortOrder: expression + direction + null ordering.  Defaults
    follow Spark: ascending -> nulls first, descending -> nulls last."""
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None

    @property
    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def asc(e: Expression) -> SortOrder:
    return SortOrder(e, True)


def desc(e: Expression) -> SortOrder:
    return SortOrder(e, False)


class SortExec(UnaryExecBase):
    def __init__(self, order: Sequence[SortOrder], child: TpuExec,
                 global_sort: bool = True):
        super().__init__(child)
        self.order = list(order)
        self.global_sort = global_sort
        self._schema = child.output_schema()
        self._bound = [o.expr.bind(self._schema) for o in self.order]

    def output_schema(self) -> T.Schema:
        return self._schema

    def children_coalesce_goal(self) -> list[Optional[CoalesceGoal]]:
        return [RequireSingleBatch() if self.global_sort else None]

    def describe(self):
        dirs = ",".join(
            f"{o.expr!r} {'ASC' if o.ascending else 'DESC'}"
            for o in self.order)
        return f"SortExec({dirs}, global={self.global_sort})"

    def cache_scope(self):
        from spark_rapids_tpu.exprs.base import fingerprint
        return (fingerprint(self._bound),
                tuple((o.ascending, o.resolved_nulls_first)
                      for o in self.order))

    def _kernel(self, batch: ColumnarBatch, head: Optional[int] = None):
        key = ("sort", head, batch_signature(batch))

        def build():
            bound = self._bound
            specs = [(o.ascending, o.resolved_nulls_first)
                     for o in self.order]
            cap = batch.capacity
            out_cap = cap
            if head is not None and head < cap:
                from spark_rapids_tpu.columnar.vector import bucket_capacity
                out_cap = bucket_capacity(head)

            @jax.jit
            def kernel(columns, num_rows, mask=None):
                ctx = make_eval_context(columns, cap, num_rows, mask)
                keys = [e.eval(ctx) for e in bound]
                perm = multi_key_argsort(
                    [(k, a, nf) for k, (a, nf) in zip(keys, specs)],
                    ctx.row_mask)
                # selected rows sort FIRST (row_mask is the most
                # significant key), so a sparse input compacts for free
                count = num_rows
                if out_cap < cap:
                    # fused limit: gather only the head — skipping the
                    # full-capacity payload gathers is the whole win
                    # (each costs ~30ms at 4M rows on this chip)
                    perm = perm[:out_cap]
                    count = jnp.minimum(num_rows,
                                        jnp.int32(min(head, out_cap)))
                valid = jnp.arange(out_cap) < count
                return [c.gather(perm, valid) for c in columns]

            return kernel

        return self.kernels.get_or_build(
            key, build,
            meta=self.kp_meta("sort" if head is None
                              else f"sort-head{head}"))

    def output_partition_count(self) -> int:
        if not self.global_sort:
            return self.child.output_partition_count()
        return 1

    def execute_partitions(self):
        if not self.global_sort:
            return [self.process_partition(it)
                    for it in self.child.execute_partitions()]

        # a global sort is a single output partition over ALL child
        # partitions (the distributed planner replaces this with a range
        # exchange; standalone we collapse here)
        def chain():
            for it in self.child.execute_partitions():
                yield from it
        return [self.process_partition(chain())]

    def process_partition(self, batches,
                          head: Optional[int] = None
                          ) -> Iterator[ColumnarBatch]:
        if self.global_sort:
            yield from self._global_sort(batches, head)
            return
        for batch in batches:
            out = self._sort_with_retry(batch, head)
            self.update_output_metrics(out)
            yield out

    def _global_sort(self, batches,
                     head: Optional[int]) -> Iterator[ColumnarBatch]:
        """Global-sort lane with out-of-core degradation: stream the
        child, and while the buffered working set fits the HBM window
        keep the existing coalesce-to-one-batch path; once the
        accounted estimate says it cannot fit (memory/oocore.py
        `should_go_external`), switch to an external merge sort —
        sorted runs spill through the host→disk tiers and k-way merge
        back in window-sized groups, instead of split-retrying the
        single giant batch down to the row floor and erroring."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.memory import oocore as OC
        from spark_rapids_tpu.memory import retry as R
        from spark_rapids_tpu.utils import profile as P
        conf = C.get_active_conf()
        pending: list[ColumnarBatch] = []
        pending_bytes = 0
        runs: list = []
        external = False
        # runs flush at window/fan-in so a merge group of MERGE_FAN_IN
        # runs fits back inside the window
        run_target = max(1, OC.window_bytes(conf) // OC.MERGE_FAN_IN)

        def flush_run():
            nonlocal pending, pending_bytes
            if not pending:
                return
            from spark_rapids_tpu.columnar.batch import concat_batches
            merged = (concat_batches([p.dense() for p in pending])
                      if len(pending) > 1 else pending[0])
            # head pruning per run is sound for top-N: each run's head
            # is a superset of its contribution to the global head
            sorted_b = self._sort_with_retry(merged, head)
            runs.append(OC.spill_run(sorted_b, label=self.name(),
                                     metrics=self.metrics, conf=conf))
            pending = []
            pending_bytes = 0

        for batch in batches:
            pending.append(batch)
            pending_bytes += R.estimate_batch_bytes(batch)
            if not external and OC.should_go_external(pending_bytes, conf):
                external = True
                P.event(P.EV_OOCORE_DEGRADE, op=self.name(),
                        est_bytes=pending_bytes, algo="external-sort")
            if external and pending_bytes > run_target:
                flush_run()

        if not external:
            # working set fit: the original coalesce + one-shot sort
            from spark_rapids_tpu.exec.coalesce import coalesce_iterator
            for batch in coalesce_iterator(
                    iter(pending), RequireSingleBatch(), self._schema,
                    self.metrics):
                out = self._sort_with_retry(batch, head)
                self.update_output_metrics(out)
                yield out
            return

        flush_run()
        out = self._merge_spilled_runs(runs, head, conf)
        self.update_output_metrics(out)
        yield out

    def _merge_spilled_runs(self, runs: list, head: Optional[int],
                            conf) -> ColumnarBatch:
        """Hierarchical merge of spilled sorted runs: each pass reads
        back window-sized groups, merges each with one in-window sort
        (the OOM split-retry lattice stays active inside), and
        re-spills until one run remains.  Bounded by
        `oocore.maxRecursionDepth` passes — past it, a descriptive
        error, never a hang or partial data."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.columnar.batch import concat_batches
        from spark_rapids_tpu.memory import oocore as OC
        from spark_rapids_tpu.memory.retry import TpuOutOfCoreError
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        window = OC.window_bytes(conf)
        max_passes = max(1, int(conf[C.OOCORE_MAX_RECURSION]))
        passes = 0
        with W.heartbeat(f"{self.name()}.oocore-merge", kind="task",
                         conf=conf) as hb:
            while len(runs) > 1:
                if passes >= max_passes:
                    raise TpuOutOfCoreError(
                        f"{self.name()}: external sort still has "
                        f"{len(runs)} runs after {passes} merge passes "
                        f"(spark.rapids.memory.oocore.maxRecursionDepth"
                        f"={max_passes}) — the merge window "
                        f"({window} bytes) is too small for the run "
                        f"count; raise the HBM budget or "
                        f"oocore.windowFraction")
                passes += 1
                self.metrics.add(M.NUM_EXTERNAL_MERGE_PASSES, 1)
                P.event(P.EV_OOCORE_MERGE_PASS, op=self.name(),
                        num_runs=len(runs))
                next_runs = []
                pending_groups: list[list] = [[]]
                group_bytes = 0
                for r in runs:
                    # 2x: serialized payload + sort scratch must both
                    # fit the window.  A group always takes at least 2
                    # runs (progress guarantee — every pass at least
                    # halves the run count; the inner split-retry
                    # lattice absorbs any window overshoot)
                    if (len(pending_groups[-1]) >= 2
                            and group_bytes + 2 * r.nbytes > window):
                        pending_groups.append([])
                        group_bytes = 0
                    pending_groups[-1].append(r)
                    group_bytes += 2 * r.nbytes
                for group in pending_groups:
                    W.maybe_hang("oocore-merge", conf)
                    merged = concat_batches(
                        [r.read(self.metrics).dense() for r in group])
                    sorted_b = self._sort_with_retry(merged, head)
                    for r in group:
                        r.free()
                    hb.beat()
                    if len(pending_groups) == 1:
                        return sorted_b  # final merge: no re-spill
                    next_runs.append(OC.spill_run(
                        sorted_b, label=self.name(),
                        metrics=self.metrics, conf=conf))
                runs = next_runs
        final = runs[0]
        batch = final.read(self.metrics)
        final.free()
        return batch

    def _sort_one_batch(self, batch: ColumnarBatch,
                        head: Optional[int]) -> ColumnarBatch:
        with self.metrics.timed(M.TOTAL_TIME):
            kernel = self._kernel(batch, head)
            if batch.sparse is not None:
                cols = kernel(batch.columns, batch.num_rows_i32,
                              batch.sparse)
            else:
                cols = kernel(batch.columns, batch.num_rows_i32)
            rows = batch._rows
            if head is not None:
                rows = (min(rows, head) if batch.num_rows_known
                        else jnp.minimum(batch.num_rows_i32,
                                         jnp.int32(head)))
            return ColumnarBatch(self._schema, list(cols), rows,
                                 batch.checks)

    def _sort_with_retry(self, batch: ColumnarBatch,
                         head: Optional[int]) -> ColumnarBatch:
        """Materialization point routed through the OOM harness: under
        reservation failure the input halves, each half sorts at half
        capacity (a fused `head` keeps only each half's head — sound
        for top-N), and the sorted runs merge through ONE final
        no-split sort pass over their concatenation.  Key VALUES are
        bit-exact vs the unsplit sort; only the order within equal
        keys can differ (Spark does not promise sort stability)."""
        pieces = list(self.oom_retry_batches(
            batch, lambda b: self._sort_one_batch(b, head),
            label=f"{self.name()}.sortBatch"))
        if len(pieces) == 1:
            return pieces[0]
        from spark_rapids_tpu.columnar.batch import concat_batches
        merged = concat_batches([p.dense() for p in pieces])
        (out,) = tuple(self.oom_retry_batches(
            merged, lambda b: self._sort_one_batch(b, head),
            split=False, label=f"{self.name()}.mergeRuns"))
        return out

    def execute_head(self, n: int) -> Iterator[ColumnarBatch]:
        """Global sort fused with a LIMIT n: the sort kernel gathers only
        the head rows at bucket(n) capacity (a GlobalLimitExec parent
        dispatches here; Spark's planner does the same fusion by
        rewriting to TakeOrderedAndProject)."""
        def chain():
            for it in self.child.execute_partitions():
                yield from it
        return self.process_partition(chain(), head=n)


class SortedTopNExec(UnaryExecBase):
    """TakeOrderedAndProject analog: per-batch top-N keep + final merge.
    (Reference uses CPU fallback for TakeOrderedAndProject at this
    snapshot; we accelerate it since sort is cheap on device.)"""

    def __init__(self, n: int, order: Sequence[SortOrder], child: TpuExec):
        super().__init__(child)
        self.n = n
        self.order = list(order)
        self._schema = child.output_schema()
        # one shared sorter so per-batch sort kernels hit ONE compile cache
        from spark_rapids_tpu.exec.base import SchemaOnlyExec
        self._sorter = SortExec(self.order, SchemaOnlyExec(self._schema),
                                global_sort=False)

    def output_schema(self):
        return self._schema

    def _sort_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        kern = self._sorter._kernel(batch)
        if batch.sparse is not None:
            cols = kern(batch.columns, batch.num_rows_i32, batch.sparse)
        else:
            cols = kern(batch.columns, batch.num_rows_i32)
        return ColumnarBatch(self._schema, list(cols), batch._rows,
                             batch.checks)

    def _topk_applicable(self) -> bool:
        if len(self.order) != 1 or self.n > 128:
            return False
        dt = self._sorter._bound[0].data_type(self._schema)
        return not dt.is_string

    def _prune_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Per-batch candidate pruning.  Single numeric key: lax.top_k
        over an exact sentinel-encoded score (~10x cheaper than the full
        bitonic sort at multi-M rows); NaN/inf/past-2^53 magnitudes
        route to the sort branch via lax.cond so ordering stays exact.
        The cross-batch merge re-sorts candidates exactly, fixing any
        top_k tie order."""
        if not self._topk_applicable():
            return self._sort_one(batch).take_head(self.n)
        kern = self.kernels.get_or_build(
            ("topn-k", self.n, batch_signature(batch)),
            lambda: jax.jit(self._build_topk(batch.capacity)),
            meta=self.kp_meta("topn-k"))
        if batch.sparse is not None:
            cols, count = kern(batch.columns, batch.num_rows_i32,
                               batch.sparse)
        else:
            cols, count = kern(batch.columns, batch.num_rows_i32)
        return ColumnarBatch(self._schema, list(cols), count,
                             batch.checks)

    def _build_topk(self, cap: int):
        from spark_rapids_tpu.columnar.vector import bucket_capacity
        o = self.order[0]
        bound = self._sorter._bound[0]
        dt = bound.data_type(self._schema)
        kk = min(self.n, cap)
        out_cap = bucket_capacity(kk)
        BIG, NBIG = 4e300, 2e300

        def kernel(columns, num_rows, mask=None):
            ctx = make_eval_context(columns, cap, num_rows, mask)
            k = bound.eval(ctx)
            d = k.data.astype(jnp.float64)
            valid = k.validity & ctx.row_mask
            if dt.is_floating:
                special = jnp.any(valid & (jnp.isnan(d) |
                                           (jnp.abs(d) >= 1e290)))
            else:
                special = jnp.any(valid &
                                  (jnp.abs(d) >= jnp.float64(2**53)))

            sv = d if not o.ascending else -d
            if dt.is_floating:
                nan_score = NBIG if not o.ascending else -NBIG
                sv = jnp.where(jnp.isnan(d), nan_score, sv)
            null_score = BIG if o.resolved_nulls_first else -BIG
            score = jnp.where(k.validity, sv, null_score)
            score = jnp.where(ctx.row_mask, score, -jnp.inf)

            # 64-bit top_k is ~8x slower than 32-bit on this chip: prune
            # candidates with a MONOTONE f32 downcast of the score, then
            # re-rank just the candidates exactly in f64.  Sound unless
            # the f32 tie bucket at the candidate boundary could hide a
            # true top-k row — detected on device and routed (with the
            # NaN/magnitude specials) to the exact 64-bit sort branch.
            kkp = min(cap, max(4 * kk, kk + 118))
            # clip BEFORE the downcast so the +/-BIG null sentinels stay
            # FINITE in f32 (a raw downcast overflows them to +/-inf,
            # conflating nulls-last rows with row-mask-excluded rows);
            # masked rows are re-pinned to -inf afterwards.  clip is
            # monotone non-strict, so collapsed extremes are exactly the
            # tie case the boundary guard already routes to the exact
            # branch.
            score32 = jnp.where(
                ctx.row_mask,
                jnp.clip(score, -3.0e38, 3.0e38).astype(jnp.float32),
                -jnp.inf)
            vals32, cand = jax.lax.top_k(score32, kkp)
            cand_exact = jnp.take(score, cand)
            order = jnp.argsort(-cand_exact)
            topk_idx = jnp.take(cand, order[:kk]).astype(jnp.int32)
            # boundary tie: the K'-th kept f32 key equals the k-th —
            # rows beyond K' with the same f32 key may beat kept ones
            # in f64.  A -inf boundary means fewer than k real rows, so
            # every real row is already a candidate; kkp == cap means
            # EVERY row is a candidate (statically sound).
            if kkp >= cap:
                unsound = jnp.bool_(False)
            else:
                unsound = ((vals32[kkp - 1] == vals32[kk - 1])
                           & (vals32[kk - 1] != -jnp.inf))

            def sort_branch():
                perm = multi_key_argsort(
                    [(k, o.ascending, o.resolved_nulls_first)],
                    ctx.row_mask)
                return perm[:kk].astype(jnp.int32)

            idx = jax.lax.cond(special | unsound, sort_branch,
                               lambda: topk_idx)
            count = jnp.minimum(jnp.asarray(num_rows, jnp.int32), kk)
            pad_idx = jnp.zeros(out_cap, jnp.int32).at[:kk].set(idx)
            valid_out = jnp.arange(out_cap) < count
            cols = [c.gather(pad_idx, valid_out) for c in columns]
            return cols, count
        return kernel

    def execute_columnar(self):
        from spark_rapids_tpu.columnar.batch import concat_batches
        pruned = []
        for part in self.child.execute_partitions():
            for batch in part:
                top = self._prune_one(batch)
                if top.maybe_nonempty():
                    pruned.append(top)
        if not pruned:
            return
        merged = concat_batches(pruned)
        final = self._sort_one(merged).take_head(self.n)
        self.update_output_metrics(final)
        yield final

    def execute_partitions(self):
        return [self.execute_columnar()]

