"""SPMD whole-stage execution: pjit the fused stage over the device
mesh, not the partition.

PR 7's `FusedStageExec` made a stage ONE XLA program — but Python
still dispatched it once per partition batch, and on a pod that is the
multichip scaling wall: O(partitions) host round-trips per stage while
the mesh sits idle between them (the 1-3% HBM story of BENCH_r05/r06).
Theseus (PAPERS.md) argues the runtime must own data movement
end-to-end; the pjit/GDA pattern (SNIPPETS.md [1][2], PartitionSpec
layouts [3]) is the JAX-native form of that for stage compute:

  1. drain the stage's child partitions and STACK every batch along a
     leading slot axis (padded to a common capacity/char-cap, with a
     per-slot row mask so ragged partitions stay bit-exact);
  2. lay the stack out with `NamedSharding(mesh, P("data"))`
     (parallel/mesh.py) — slot i lives on device i % n_dev;
  3. run the whole composed project->filter chain as ONE
     jit-with-shardings program (`jax.vmap` over the slot axis, XLA
     partitions it over the mesh and inserts the cross-shard
     collectives itself: the ANSI-flag any(), the output row-count
     sum, and the output gather back to the engine's default device —
     downstream execution is host-orchestrated single-device work
     today; shard-resident consumption is the pod-scale follow-up);
  4. slice the gathered outputs back into per-partition
     ColumnarBatches in the original order (plain single-device ops).

One Python dispatch per stage, regardless of partition count.

Interop contracts preserved from the per-partition lane:

* bit-exactness: each slot evaluates the same composed expressions on
  the same rows under the same mask the per-partition kernel would
  use — padding rows are masked out, never computed on trust;
* deferred selection: filter stages emit per-slot sparse masks exactly
  like `FilterExec`; pure-project stages pass the input's row
  count/mask through;
* per-member metrics (`FusedStageExec._charge_members` per slot, rows
  as lazy device scalars), OOM reserve/spill/retry at gang granularity
  (`memory/retry.with_retry` over the stacked footprint), watchdog
  collective-class heartbeats (`watched_collective` wraps the gang
  dispatch — a whole-mesh program blocks every participant, so it gets
  the tighter collective deadline and the collective hang-injection
  site), and the movement ledger's `collective` edge (site
  ``spmd-stage``: the payload of the program's implicit cross-shard
  reductions, same bytes-entering-the-collective convention as the
  hand-rolled mesh exchange);
* admission: gang dispatches serialize on the process-wide
  `scheduler.whole_mesh_dispatch` gate (two concurrent whole-mesh
  programs would oversubscribe every chip at once) and take one
  `TpuSemaphore` task hold for the whole mesh.

Deopt (never an error): no active mesh, `spark.rapids.sql.spmd.enabled`
off, uneven batch layouts the stacker cannot unify (mixed narrow-shadow
presence), a gang trace failure, or a prior deopt on this exec — each
falls back to the per-partition fused lane over the already-drained
batches (`numSpmdDeopts`, `spmd_deopt` event).  Compiled gang programs
land in the shared KernelCache under `mesh_cache_scope` keys (mesh
shape + device ids + shardings), so SPMD and per-partition entries can
never collide.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import ColumnVector, _pad_chars
from spark_rapids_tpu.exec.base import make_eval_context, mesh_cache_scope
from spark_rapids_tpu.utils import metrics as M

log = logging.getLogger("spark_rapids_tpu.exec.spmd")

#: site label on the movement ledger's collective edge
SITE_SPMD_STAGE = "spmd-stage"


class SpmdUnsupported(Exception):
    """This gang cannot run SPMD (deopt to the per-partition lane)."""


# ---------------------------------------------------------------------------
# lane counters (bench/CI summary + tests): process-wide so the bench
# can prove "one Python dispatch per stage" without instrumenting jit
_STATS_LOCK = threading.Lock()
_STATS = {"gang_dispatches": 0, "gang_batches": 0, "gang_slots": 0,
          "deopts": 0}


def spmd_stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_spmd_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _bump(**kv) -> None:
    with _STATS_LOCK:
        for k, v in kv.items():
            _STATS[k] += v


# ---------------------------------------------------------------------------
def maybe_execute_spmd(exec_) -> Optional[list]:
    """The SPMD lane for one `FusedStageExec`: partition iterators when
    the lane engages, None when the per-partition lane should run
    (conf off, no active mesh, or this exec already deopted).  Conf and
    mesh resolve at EXECUTION time — never captured at plan build."""
    from spark_rapids_tpu.parallel import mesh as PM
    conf = C.get_active_conf()
    if not conf[C.SPMD_ENABLED]:
        return None
    active = PM.get_active_mesh()
    if active is None:
        return None
    if exec_._fusion_deopt or exec_._spmd_deopt:
        return None
    mesh, axis = active

    from spark_rapids_tpu.utils import profile as P
    parts = exec_.child.execute_partitions()
    n_parts = len(parts)
    # the gang barrier: SPMD needs every partition's batches together
    # (that is what one whole-mesh program per stage MEANS)
    entries = [(pi, b) for pi, it in enumerate(parts) for b in it]
    if not entries:
        return [iter(()) for _ in range(n_parts)]

    from spark_rapids_tpu.utils.watchdog import TpuQueryTimeout
    outs = None
    try:
        with exec_.metrics.timed(M.TOTAL_TIME):
            outs = _run_gang(exec_, mesh, axis,
                             [b for _, b in entries])
    except (MemoryError, TpuQueryTimeout):
        raise  # the OOM lattice / watchdog own these
    except Exception as e:  # noqa: BLE001 — unsupported gang shapes
        _note_deopt(exec_, e)  # and trace failures deopt THIS stage

    groups: list[list] = [[] for _ in range(n_parts)]
    if outs is None:
        # per-partition fallback over the already-drained batches: the
        # fused per-batch lane (which may itself deopt further, to the
        # per-operator members)
        for pi, b in entries:
            groups[pi].append(b)
        return [P.wrap_operator(exec_, pi,
                                exec_.process_partition(iter(g)))
                for pi, g in enumerate(groups)]
    for (pi, _), ob in zip(entries, outs):
        groups[pi].append(ob)
    return [P.wrap_operator(exec_, pi, iter(g))
            for pi, g in enumerate(groups)]


def _note_deopt(exec_, err: BaseException) -> None:
    from spark_rapids_tpu.utils import profile as P
    exec_._spmd_deopt = True
    exec_.metrics.add(M.NUM_SPMD_DEOPTS, 1)
    _bump(deopts=1)
    P.event(P.EV_SPMD_DEOPT, members=exec_.stage.member_names(),
            error=f"{type(err).__name__}: {err}"[:300])
    log.warning(
        "SPMD gang for stage [%s] deopted to the per-partition lane: "
        "%s", exec_.stage.describe_ops(), err)


# ---------------------------------------------------------------------------
# stacking
def _gang_layout(schema: T.Schema, batches: list) -> tuple:
    """Unified layout for one gang: (capacity, per-column char_cap,
    per-column narrow-presence).  Raises SpmdUnsupported on layouts the
    stacker cannot unify bit-exactly (mixed narrow shadows: dropping a
    lossy f32 shadow from some slots but not others would route slots
    through DIFFERENT downstream fast paths than the per-partition
    lane)."""
    cap = max(b.capacity for b in batches)
    char_caps: list = []
    narrows: list = []
    for ci, f in enumerate(schema.fields):
        vecs = [b.columns[ci] for b in batches]
        char_caps.append(max(v.char_cap for v in vecs)
                         if f.dtype.is_string else 0)
        with_n = sum(1 for v in vecs if v.narrow is not None)
        if with_n not in (0, len(vecs)):
            raise SpmdUnsupported(
                f"column '{f.name}' carries a narrow shadow on "
                f"{with_n}/{len(vecs)} gang batches — uneven layouts "
                "deopt to the per-partition lane")
        narrows.append(with_n > 0)
    return cap, tuple(char_caps), tuple(narrows)


def _stack_gang(schema: T.Schema, batches: list, cap: int,
                char_caps: tuple, n_slots: int) -> tuple:
    """Stack per-batch columns into [n_slots, cap, ...] pytrees plus
    the per-slot row counts and masks.  Slots past len(batches) are
    zero padding with all-False masks — they flow through the program
    fully masked, so they can never contribute a row."""
    pad_slots = n_slots - len(batches)

    def pad_tail(arr):
        if not pad_slots:
            return arr
        return jnp.concatenate(
            [arr, jnp.zeros((pad_slots,) + arr.shape[1:], arr.dtype)])

    cols: list = []
    for ci, f in enumerate(schema.fields):
        vecs = [b.columns[ci] for b in batches]
        if f.dtype.is_string:
            vecs = [_pad_chars(v, char_caps[ci]) for v in vecs]
        vecs = [v.with_capacity(cap) for v in vecs]
        data = pad_tail(jnp.stack([v.data for v in vecs]))
        validity = pad_tail(jnp.stack([v.validity for v in vecs]))
        lengths = (pad_tail(jnp.stack([v.lengths for v in vecs]))
                   if vecs[0].lengths is not None else None)
        narrow = (pad_tail(jnp.stack([v.narrow for v in vecs]))
                  if vecs[0].narrow is not None else None)
        cols.append(ColumnVector(f.dtype, data, validity, lengths,
                                 narrow))
    num_rows = pad_tail(jnp.stack([b.num_rows_i32 for b in batches]))
    masks = pad_tail(jnp.stack([
        jnp.pad(b.sparse, (0, cap - b.capacity))
        if b.sparse is not None
        else jnp.arange(cap) < b.num_rows_i32 for b in batches]))
    return cols, num_rows, masks


def _stacked_nbytes(cols, masks) -> int:
    total = masks.nbytes + 4 * masks.shape[0]
    for c in cols:
        total += c.data.nbytes + c.validity.nbytes
        if c.lengths is not None:
            total += c.lengths.nbytes
        if c.narrow is not None:
            total += c.narrow.nbytes
    return total


def _tree_nbytes(tree) -> int:
    return sum(a.nbytes for a in jax.tree_util.tree_leaves(tree))


# ---------------------------------------------------------------------------
def _gang_kernel(exec_, mesh, axis: str, cap: int, n_slots: int,
                 col_sig: tuple):
    """One jit-with-shardings program for the whole gang, cached in the
    exec's (stage-fingerprint-scoped) KernelCache under a key that
    includes the mesh shape + shardings — SPMD entries never collide
    with per-partition ones, or with another mesh's."""
    from spark_rapids_tpu.parallel import mesh as PM
    from spark_rapids_tpu.plan.fusion import _eval_stage
    data_shard = PM.data_sharding(mesh, axis)
    repl = PM.replicated(mesh)
    key = ("spmd-stage",
           mesh_cache_scope(mesh, axis, (data_shard.spec,)),
           n_slots, cap, col_sig)

    def build():
        stage = exec_.stage
        has_filter = bool(stage.preds)
        labels: list = []

        def per_slot(cols, nrows, mask):
            ctx = make_eval_context(cols, cap, nrows, mask)
            out_cols, keep, counts = _eval_stage(stage, ctx)
            labels.clear()
            labels.extend(l for l, _ in ctx.pending_checks)
            return (out_cols, keep, tuple(counts),
                    tuple(f for _, f in ctx.pending_checks))

        def gang(cols, nrows, mask):
            out_cols, keep, counts, pend = \
                jax.vmap(per_slot)(cols, nrows, mask)
            # the program's only CROSS-SHARD traffic — XLA inserts the
            # collectives for these replicated reductions itself:
            # one any() per deferred-check flag, one sum() for the
            # stage's total output rows (charged lazily to the fused
            # node's metrics, no host sync)
            pend = tuple(jnp.any(f) for f in pend)
            rows = counts[-1] if counts else nrows
            total = rows.sum().astype(jnp.int32)
            return out_cols, keep, counts, pend, total

        kernel = jax.jit(
            gang,
            in_shardings=(data_shard, data_shard, data_shard),
            out_shardings=(data_shard, data_shard, data_shard, repl,
                           repl))
        kernel._ansi_labels = labels
        return kernel

    # gang kernels carry member attribution like the per-partition
    # fused lane: one catalog entry per (mesh, stage, layout) whose
    # members name the operators the sharded program evaluates
    return exec_.kernels.get_or_build(
        key, build,
        meta=exec_.kp_meta("spmd-gang",
                           members=exec_.stage.member_names())), \
        data_shard


def _run_gang(exec_, mesh, axis: str, batches: list) -> list:
    """Dispatch one gang: stack, shard, run, unstack.  Returns one
    output ColumnarBatch per input batch, in order."""
    from spark_rapids_tpu.exec import scheduler as S
    from spark_rapids_tpu.exec.basic import _register_ansi
    from spark_rapids_tpu.memory import retry as R
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.parallel.collective_exchange import (
        watched_collective)
    from spark_rapids_tpu.utils import movement as MV
    from spark_rapids_tpu.utils import profile as P

    stage = exec_.stage
    schema = stage.in_schema
    n_dev = mesh.shape[axis]
    B = len(batches)
    n_slots = -(-B // n_dev) * n_dev
    cap, char_caps, narrows = _gang_layout(schema, batches)
    col_sig = tuple(
        (f.dtype.id.value, char_caps[ci], narrows[ci])
        for ci, f in enumerate(schema.fields))

    # trace/compile OUTSIDE the dispatch gate (KernelCache single-
    # flight already serializes same-key builders)
    kernel, data_shard = _gang_kernel(exec_, mesh, axis, cap, n_slots,
                                      col_sig)
    cols, num_rows, masks = _stack_gang(schema, batches, cap,
                                        char_caps, n_slots)
    est_bytes = _stacked_nbytes(cols, masks)

    first = not getattr(kernel, "_spmd_reported", False)
    t0 = time.perf_counter() if first else 0.0
    # one task hold covers the whole mesh: the gang IS the stage's
    # device occupancy, not one hold per partition
    TpuSemaphore.get().acquire_if_necessary()
    has_filter = bool(stage.preds)
    out_schema = exec_.output_schema()
    outs: list = []
    # the gang's outputs converge to the engine's DEFAULT device: the
    # whole downstream engine is host-orchestrated single-device work
    # today, and slicing a still-sharded array per slot enqueues one
    # whole-mesh program per slice (measured ~100x the kernel's own
    # cost on the 8-device CPU mesh, and a rendezvous-deadlock vector
    # outside the gate).  Shard-resident consumption is the pod-scale
    # follow-up (ROADMAP items 1/6).
    from jax.sharding import SingleDeviceSharding
    home = SingleDeviceSharding(jax.devices()[0])

    def dispatch():
        out = kernel(*inputs)
        # the output gather IS the program's main implicit collective:
        # every non-home shard's bytes cross the mesh here, inside the
        # watched/timed region
        return jax.device_put(out, home)

    # the gate covers every whole-mesh enqueue (input scatter, gang
    # program, output gather): concurrent whole-mesh enqueues from two
    # threads can invert per-device queue order and deadlock the
    # collective rendezvous (exec/scheduler.py).  The stacked gang
    # inputs are device-pinned for the dispatch — the residency ledger
    # carries them so a gang's footprint shows in the owning query's
    # high-water composition
    from spark_rapids_tpu.utils import residency as RES
    with RES.tracked(est_bytes, site="spmd-gang",
                     kind=RES.KIND_GANG), \
            S.whole_mesh_dispatch(label=stage.describe_ops()):
        inputs = jax.device_put((cols, num_rows, masks), data_shard)
        t_disp = time.perf_counter_ns()
        out_cols, keep, counts, pend, total = R.with_retry(
            lambda: watched_collective(
                dispatch, label=f"spmd:{stage.describe_ops()}"),
            out_bytes=est_bytes, metrics=exec_.metrics,
            label=f"SpmdStage[{stage.describe_ops()}]")
        disp_ns = time.perf_counter_ns() - t_disp
    # post-gather, slicing is plain single-device work: no whole-mesh
    # enqueues escape the gate
    wave_checks = _register_ansi(pend, kernel._ansi_labels)
    for slot, b in enumerate(batches):
        slot_cols = [
            ColumnVector(
                f.dtype, cv.data[slot], cv.validity[slot],
                None if cv.lengths is None else cv.lengths[slot],
                None if cv.narrow is None else cv.narrow[slot])
            for f, cv in zip(out_schema.fields, out_cols)]
        checks = b.checks + wave_checks
        slot_counts = tuple(c[slot] for c in counts)
        if has_filter:
            out_b = ColumnarBatch(out_schema, slot_cols,
                                  slot_counts[-1], checks,
                                  sparse=keep[slot])
        elif b.sparse is not None:
            out_b = ColumnarBatch(out_schema, slot_cols, b._rows,
                                  checks, sparse=keep[slot])
        else:
            out_b = ColumnarBatch(out_schema, slot_cols, b._rows,
                                  checks)
        exec_._charge_members(b, slot_counts)
        outs.append(out_b)
    # one event per gang dispatch (one per stage execution — cheap);
    # a jit's first call traces + compiles synchronously, so the
    # first-dispatch delta IS the gang's compile cost
    kernel._spmd_reported = True
    P.event(P.EV_STAGE_SPMD, members=stage.member_names(),
            batches=B, slots=n_slots, mesh_devices=int(n_dev),
            **({"compile_ms": round((time.perf_counter() - t0) * 1e3,
                                    2)} if first else {}))
    _bump(gang_dispatches=1, gang_batches=B, gang_slots=n_slots)
    exec_.metrics.add(M.NUM_SPMD_DISPATCHES, 1)
    if MV.ledger() is not None and n_dev > 1:
        # the implicit collectives' payload: the stage outputs
        # entering the output gather, plus the cross-shard flag /
        # row-count reductions — the same bytes-entering-the-
        # collective convention as the hand-rolled lane's
        # stacked_payload_bytes, so the two lanes' collective-edge
        # numbers reconcile
        implicit = _tree_nbytes((out_cols, keep, counts, pend, total))
        MV.record(MV.EDGE_COLLECTIVE, implicit, site=SITE_SPMD_STAGE,
                  dur_ns=disp_ns)
        exec_.metrics.add(M.COLLECTIVE_BYTES, implicit)
    # stage totals ride the replicated device scalar (one add, lazy)
    exec_.metrics.add(M.NUM_OUTPUT_ROWS, total)
    exec_.metrics.add(M.NUM_OUTPUT_BATCHES, B)
    return outs
