"""Speculative partition execution: duplicate attempts for stragglers.

The engine survives hard faults — OOM (memory/retry.py), dead peers
(shuffle/recovery.py), hangs (utils/watchdog.py) — but none of those
fire on *slow*: one degraded executor stalls a whole `collect()` and,
under the query scheduler, holds admission budget hostage for every
queued query.  This module is the tail-latency answer, modeled on
Spark's task speculation (spark.speculation.*) and the "Accelerating
Presto with GPUs" framing of interactive analytics as a p95/p99
problem:

* Each manager-lane map task registers a watchdog heartbeat with a
  **slow_check** — the scanner's new *slow* classification, distinct
  from *hung*: a beating task whose elapsed runtime exceeds
  `speculation.multiplier` x the stage's completed-task median (once
  `minCompletedTasks` finished, never before `minTaskRuntimeMs`).
* A slow task gets a **duplicate attempt** launched from the
  exchange's retained lineage onto another in-process executor; both
  attempts run to a **first-wins, epoch-guarded commit**
  (`MapOutputRegistry.register(first_wins=True)` — the loser's commit
  raises `StaleMapStatusError` and its buffers are freed, so a losing
  attempt can never publish).  Results stay bit-exact: both attempts
  compute identical map output from the same pure lineage.
* The **loser is cancelled** via its per-attempt `AttemptToken`
  (watchdog machinery): every cancellation point under the attempt —
  batch boundaries, injected slow sleeps, backoff waits — wakes
  immediately, the attempt aborts its writer, and the stage moves on.

Disabled (`spark.rapids.sql.speculation.enabled`, default off) the
exchange never constructs a SpeculationManager and behavior is
byte-identical to the pre-speculation engine.
"""
from __future__ import annotations

import logging
import statistics
import threading
import time
from typing import Callable, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.utils import metrics as M

log = logging.getLogger("spark_rapids_tpu.speculation")

# process-lifetime counters for CI summary lines / leak assertions
_STATS_LOCK = threading.Lock()
_STATS = {"launched": 0, "wins": 0, "losers_cancelled": 0}


def speculation_stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_speculation_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _note(key: str) -> None:
    with _STATS_LOCK:
        _STATS[key] += 1


class _Task:
    """Race state for one map task: the inline original attempt plus
    at most one speculative duplicate."""

    def __init__(self, map_id: int, t0: float, epoch0: int, mgr):
        self.map_id = map_id
        self.t0 = t0
        self.epoch0 = epoch0
        self.mgr = mgr
        self.lock = threading.Lock()
        self.speculated = False
        self.settled = False
        self.orig_token = None       # AttemptToken of the inline run
        self.spec_token = None       # AttemptToken of the duplicate
        self.spec_thread: Optional[threading.Thread] = None
        self.spec_done = threading.Event()
        self.spec_won = False
        self.spec_error: Optional[BaseException] = None
        self.commit_time: Optional[float] = None

    def try_mark_speculated(self) -> bool:
        with self.lock:
            if self.speculated or self.settled:
                return False
            self.speculated = True
            return True


class SpeculationManager:
    """Per-stage (one shuffle exchange's map side) speculation driver.

    The exchange supplies three closures:
      * ``write_fn(map_id, batch_iter, mgr, epoch, first_wins)`` —
        split + write + COMMIT one map task onto `mgr` (the exchange's
        write_map_task, replication included).
      * ``lineage_fn(map_id)`` — a FRESH batch iterator for the map
        task's input, re-derived from the exchange's retained child
        lineage (the same closure recovery recomputes from).
      * ``backup_fn(exclude_mgr)`` — a healthy in-process executor to
        host the duplicate, or None when there is nowhere to run it.
    """

    def __init__(self, shuffle_id: int, conf: C.RapidsConf, metrics,
                 write_fn: Callable, lineage_fn: Callable,
                 backup_fn: Callable):
        from spark_rapids_tpu.exec import scheduler as S
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        self.shuffle_id = shuffle_id
        self.conf = conf
        self.metrics = metrics
        self.write_fn = write_fn
        self.lineage_fn = lineage_fn
        self.backup_fn = backup_fn
        self.multiplier = max(1.0, float(conf[C.SPECULATION_MULTIPLIER]))
        self.min_runtime_s = \
            float(conf[C.SPECULATION_MIN_RUNTIME_MS]) / 1e3
        self.min_completed = max(1, int(conf[C.SPECULATION_MIN_COMPLETED]))
        # duplicate attempts run with pipelining off: a cancelled
        # loser must not leave producer threads parked on queues
        self.spec_conf = conf.set(C.PIPELINE_ENABLED.key, False)
        self._lock = threading.Lock()
        self._durations: list[float] = []
        # captured on the driver thread so speculative threads carry
        # the query's context (cancellation, conf, profile parenting)
        self._qc = S.current()
        self._span_ref = P.current_ref()
        self._query_token = W.current_token()
        self._threads: list[threading.Thread] = []

    # -- stage-median bookkeeping -------------------------------------------
    def _note_completion(self, seconds: float) -> None:
        with self._lock:
            self._durations.append(seconds)

    def _median(self) -> Optional[float]:
        with self._lock:
            if len(self._durations) < self.min_completed:
                return None
            return statistics.median(self._durations)

    # -- slow classification (runs on the watchdog scanner thread) ----------
    def _slow_check(self, state: _Task) -> Callable:
        def check(hb, now: float) -> None:
            if state.settled or state.speculated:
                return
            med = self._median()
            if med is None:
                return
            elapsed = now - state.t0
            threshold = max(self.min_runtime_s, self.multiplier * med)
            if elapsed < threshold:
                return
            if not state.try_mark_speculated():
                return
            backup = self.backup_fn(state.mgr)
            if backup is None:
                return
            t = threading.Thread(
                target=self._run_speculative,
                args=(state, backup, elapsed, med), daemon=True,
                name=f"tpu-speculate-s{self.shuffle_id}m{state.map_id}")
            state.spec_thread = t
            self._threads.append(t)
            t.start()
        return check

    # -- the duplicate attempt ----------------------------------------------
    def _run_speculative(self, state: _Task, backup, elapsed: float,
                         median: float) -> None:
        from spark_rapids_tpu.exec import scheduler as S
        from spark_rapids_tpu.shuffle.manager import StaleMapStatusError
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        stok = W.AttemptToken(parent=self._query_token)
        state.spec_token = stok
        self.metrics.add(M.NUM_SPECULATIVE_TASKS, 1)
        _note("launched")
        P.event(P.EV_SPECULATION_LAUNCHED, shuffle_id=self.shuffle_id,
                map_id=state.map_id, backup=backup.executor_id,
                elapsed_ms=round(elapsed * 1e3, 1),
                stage_median_ms=round(median * 1e3, 1))
        try:
            with S.scoped(self._qc), C.session(self.spec_conf), \
                    P.attach(self._span_ref), W.attempt_scope(stok):
                it = self.lineage_fn(state.map_id)
                try:
                    self.write_fn(state.map_id, it, backup,
                                  state.epoch0, True)
                finally:
                    close = getattr(it, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:  # noqa: BLE001
                            pass
            state.spec_won = True
            state.commit_time = time.monotonic()
            self.metrics.add(M.NUM_SPECULATIVE_WINS, 1)
            _note("wins")
            P.event(P.EV_SPECULATION_WIN, shuffle_id=self.shuffle_id,
                    map_id=state.map_id, backup=backup.executor_id)
            if state.orig_token is not None:
                state.orig_token.cancel_race_lost(
                    f"speculation: duplicate attempt on "
                    f"{backup.executor_id} committed first")
        except StaleMapStatusError:
            # the original committed first: this attempt lost at the
            # registry and its writer already freed its buffers
            pass
        except W.TpuQueryTimeout:
            if not stok.race_lost:
                # whole-query cancellation: the original attempt (or
                # collect) raises it; nothing to add here
                log.debug("speculative attempt for map %d cancelled "
                          "with the query", state.map_id)
        except BaseException as e:  # noqa: BLE001 — the original is
            state.spec_error = e    # the safety net; never fail the
            log.warning("speculative attempt for shuffle %d map %d "
                        "failed (original continues): %s",
                        self.shuffle_id, state.map_id, e)
        finally:
            state.spec_done.set()

    # -- the inline original attempt ----------------------------------------
    def run_task(self, map_id: int, batch_iter, mgr) -> None:
        """Run one map task with speculation armed: the inline attempt
        executes on the calling thread; the watchdog may race a
        duplicate against it.  Returns once the map output is
        committed (by either attempt) and both attempts are settled."""
        from spark_rapids_tpu.shuffle.manager import (
            MapOutputRegistry, StaleMapStatusError)
        from spark_rapids_tpu.utils import watchdog as W
        t0 = time.monotonic()
        epoch0 = MapOutputRegistry.epoch(self.shuffle_id)
        state = _Task(map_id, t0, epoch0, mgr)
        otok = W.AttemptToken(parent=self._query_token)
        state.orig_token = otok
        hb = W.heartbeat(f"map-task:s{self.shuffle_id}m{map_id}",
                         kind="task", conf=self.conf,
                         slow_check=self._slow_check(state))
        orig_error: Optional[BaseException] = None
        won = False
        try:
            try:
                with W.attempt_scope(otok):
                    self.write_fn(map_id, batch_iter, mgr, epoch0, True)
                won = True
                state.commit_time = state.commit_time or time.monotonic()
            except StaleMapStatusError:
                pass  # the duplicate committed first — clean loss
            except W.TpuQueryTimeout:
                if otok.race_lost:
                    # cancelled loser: drop the half-consumed input so
                    # its pipeline producer (if any) unparks and exits
                    _note("losers_cancelled")
                    close = getattr(batch_iter, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:  # noqa: BLE001
                            pass
                else:
                    raise
            except BaseException as e:  # noqa: BLE001
                orig_error = e
        finally:
            with state.lock:
                state.settled = True
            hb.close()
        # settle the race
        if won and state.spec_token is not None:
            state.spec_token.cancel_race_lost(
                "speculation: original attempt committed first")
        if state.spec_thread is not None:
            # prompt: every wait under the attempt is cancellable
            state.spec_done.wait(timeout=60.0)
            state.spec_thread.join(timeout=10.0)
        if not won and not state.spec_won:
            # nobody published: surface the original's failure (or the
            # speculative one as a last resort)
            err = orig_error or state.spec_error
            if err is not None:
                raise err
            raise RuntimeError(
                f"map task {self.shuffle_id}/{map_id}: no attempt "
                f"committed and no error was recorded")
        if orig_error is not None and state.spec_won:
            log.warning("original attempt for shuffle %d map %d failed "
                        "but its speculative duplicate won: %s",
                        self.shuffle_id, map_id, orig_error)
        end = state.commit_time or time.monotonic()
        self._note_completion(end - t0)

    def finish(self) -> None:
        """Join any stray speculative threads (all are settled by
        run_task; this is belt-and-braces for error paths)."""
        for t in self._threads:
            t.join(timeout=10.0)


def maybe_create(shuffle_id: int, conf: C.RapidsConf, metrics,
                 write_fn: Callable, lineage_fn: Callable,
                 backup_fn: Callable,
                 num_executors: int) -> Optional[SpeculationManager]:
    """A SpeculationManager when speculation is on and there is more
    than one in-process executor to speculate onto; else None (the
    exchange keeps its plain sequential loop — byte-identical
    behavior)."""
    from spark_rapids_tpu.utils import watchdog as W
    if not conf[C.SPECULATION_ENABLED] or num_executors < 2:
        return None
    if not W.enabled(conf):
        return None  # slow classification rides the watchdog scanner
    return SpeculationManager(shuffle_id, conf, metrics, write_fn,
                              lineage_fn, backup_fn)
