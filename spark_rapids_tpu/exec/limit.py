"""Limit operators (reference `limit.scala`: GpuLocalLimitExec,
GpuGlobalLimitExec, GpuCollectLimitExec)."""
from __future__ import annotations

from typing import Iterator

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, UnaryExecBase


def _limited(batches, n: int, on_output) -> Iterator[ColumnarBatch]:
    """Emit at most n rows.  Lazy-count batches avoid the ~150ms count
    sync via take_head; the running `remaining` only syncs when ANOTHER
    batch follows (the single-batch case — a limit over one sorted
    batch — never syncs)."""
    remaining = n
    it = iter(batches)
    prev = next(it, None)
    while prev is not None and remaining > 0:
        nxt = next(it, None)
        if prev.num_rows_known and prev.num_rows <= remaining:
            out = prev
        else:
            out = prev.take_head(remaining)
        if nxt is not None:
            remaining -= out.num_rows  # may sync; another batch follows
        else:
            remaining = 0
        on_output(out)
        yield out
        prev = nxt


class LocalLimitExec(UnaryExecBase):
    """Per-partition limit: slice batches until n rows emitted."""

    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        self.n = n

    def output_schema(self):
        return self.child.output_schema()

    def describe(self):
        return f"LocalLimitExec({self.n})"

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        yield from _limited(batches, self.n, self.update_output_metrics)


class GlobalLimitExec(UnaryExecBase):
    """Whole-query limit; requires a single upstream partition (planner
    inserts a single-partition exchange below, like Spark)."""

    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        self.n = n

    def output_schema(self):
        return self.child.output_schema()

    def describe(self):
        return f"GlobalLimitExec({self.n})"

    def execute_columnar(self):
        from spark_rapids_tpu.exec.sort import SortExec
        if (isinstance(self.child, SortExec) and self.child.global_sort):
            # fuse the limit into the sort's gather (the sort kernel
            # then never materializes full-capacity payload columns)
            yield from _limited(self.child.execute_head(self.n), self.n,
                                self.update_output_metrics)
            return
        def chain():
            for part in self.child.execute_partitions():
                yield from part
        yield from _limited(chain(), self.n, self.update_output_metrics)

    def output_partition_count(self) -> int:
        return 1

    def execute_partitions(self):
        return [self.execute_columnar()]


def CollectLimitExec(n: int, child: TpuExec) -> GlobalLimitExec:
    """Reference GpuCollectLimitExec: limit + single-partition collect."""
    return GlobalLimitExec(n, child)
