"""Limit operators (reference `limit.scala`: GpuLocalLimitExec,
GpuGlobalLimitExec, GpuCollectLimitExec)."""
from __future__ import annotations

from typing import Iterator

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, UnaryExecBase


class LocalLimitExec(UnaryExecBase):
    """Per-partition limit: slice batches until n rows emitted."""

    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        self.n = n

    def output_schema(self):
        return self.child.output_schema()

    def describe(self):
        return f"LocalLimitExec({self.n})"

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        remaining = self.n
        for b in batches:
            if remaining <= 0:
                break
            if b.num_rows <= remaining:
                remaining -= b.num_rows
                self.update_output_metrics(b)
                yield b
            else:
                out = b.slice(0, remaining)
                remaining = 0
                self.update_output_metrics(out)
                yield out


class GlobalLimitExec(UnaryExecBase):
    """Whole-query limit; requires a single upstream partition (planner
    inserts a single-partition exchange below, like Spark)."""

    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        self.n = n

    def output_schema(self):
        return self.child.output_schema()

    def describe(self):
        return f"GlobalLimitExec({self.n})"

    def execute_columnar(self):
        remaining = self.n
        for part in self.child.execute_partitions():
            for b in part:
                if remaining <= 0:
                    return
                out = b if b.num_rows <= remaining else b.slice(0, remaining)
                remaining -= out.num_rows
                self.update_output_metrics(out)
                yield out

    def output_partition_count(self) -> int:
        return 1

    def execute_partitions(self):
        return [self.execute_columnar()]


def CollectLimitExec(n: int, child: TpuExec) -> GlobalLimitExec:
    """Reference GpuCollectLimitExec: limit + single-partition collect."""
    return GlobalLimitExec(n, child)
