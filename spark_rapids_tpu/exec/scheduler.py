"""Concurrent multi-query serving: query contexts, admission control,
and the plan-fingerprint result cache.

PRs 1-5 made a single query survive OOM, peer death, and hangs — but the
engine still executed one `collect()` at a time while HBM sat 1-3%
utilized.  This module is the serving layer in front of
`TpuExec.collect` that lets tens of concurrent sessions share one
accelerator the way the Presto-on-GPU work shares a GPU between
interactive tenants, with the resource-accounting discipline Theseus
argues decides whether an accelerator engine stays healthy under load:

* **QueryContext** — one per top-level query: the query id, the conf
  SNAPSHOT (no globals resolved mid-query), the `CancelToken`, the
  per-query watchdog stats, the per-query deferred-check registry, the
  per-query profile tracer, and the execution epoch for
  `CommonSubplanExec` caches.  Carried thread-locally on the driver
  thread and threaded through `TaskContext.query_ctx` to every helper
  thread (pipeline producers, AQE fills, shuffle fetch threads), so a
  fault injected into query A — OOM, peer kill, hang — cancels,
  retries, or fails A alone and never bleeds into query B.
* **QueryScheduler** — admission control against the `DeviceManager`
  HBM admission ledger: a query declares an HBM budget estimate
  (`spark.rapids.sql.scheduler.queryBudgetBytes`, defaulting to an
  equal share of the accounted arena) and is admitted only while the
  sum of admitted budgets fits the device budget AND fewer than
  `maxConcurrentQueries` queries are in flight.  Otherwise it queues
  FIFO (bounded by `queueDepth`, watched by a task-class heartbeat so
  a wedged queue is watchdog-visible) and sheds load with a
  descriptive `TpuQueryRejected` when the queue is full or the
  `queueTimeout` passes — queueing at the front door instead of
  thrashing the spill/retry lattice once saturated.
* **ResultCache** — a byte-bounded LRU keyed by (plan structural
  fingerprint, source-data identity, session-conf fingerprint) for
  repeated dashboard-style queries: a hit returns the cached pandas
  result (copied, bit-exact) without touching the device; any conf
  change changes the key, so stale-conf hits are impossible.  Plans
  with leaves the fingerprinter does not recognize are simply not
  cached — never a wrong answer.

The collect-side handshake is `CollectScope` (used by
`TpuExec.collect`): the outermost collect on a thread with no live
QueryContext creates one, begins its profile, admits it, and serializes
on the PLAN INSTANCE lock (two sessions sharing one plan object would
race its CommonSubplanExec caches and metrics; distinct plan instances
— the normal case — run fully concurrently).
"""
from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from spark_rapids_tpu import config as C

log = logging.getLogger("spark_rapids_tpu.scheduler")


class TpuQueryRejected(RuntimeError):
    """Admission control shed this query: the device is saturated and
    the wait queue is full (or the queue deadline passed).  Carries a
    snapshot of the admission state so the caller can size budgets."""


# ---------------------------------------------------------------------------
# execution epochs: minted process-globally so no two query attempts can
# ever collide on a CommonSubplanExec cache tag, scoped per-query so
# concurrent queries' epochs don't invalidate each other's caches
_EPOCH_COUNTER = itertools.count(1)
_EPOCH_LOCK = threading.Lock()
_LAST_EPOCH = 0

_QUERY_IDS = itertools.count(1)


def new_epoch() -> int:
    global _LAST_EPOCH
    with _EPOCH_LOCK:
        _LAST_EPOCH = next(_EPOCH_COUNTER)
        return _LAST_EPOCH


def current_epoch() -> int:
    """The epoch `CommonSubplanExec` caches are scoped to: the current
    query's attempt epoch, or (no query in flight — direct
    execute_partitions in tests) the last minted value."""
    qc = current()
    if qc is not None and qc.epoch:
        return qc.epoch
    return _LAST_EPOCH


# ---------------------------------------------------------------------------
class QueryContext:
    """Everything one in-flight query owns.  Created by the outermost
    collect (via CollectScope), installed thread-locally on the driver
    thread, and propagated to helper threads through
    `TaskContext.query_ctx` / `scoped()`."""

    __slots__ = ("query_id", "conf", "token", "stats", "pending_checks",
                 "tracer", "epoch", "budget_bytes", "admitted",
                 "owner_thread", "created", "report_plan", "_depth",
                 "_lock")

    def __init__(self, conf: Optional[C.RapidsConf] = None):
        from spark_rapids_tpu.utils import watchdog as W
        self.query_id = f"q{next(_QUERY_IDS):06d}-{os.getpid() & 0xffff}"
        self.conf = conf if conf is not None else C.get_active_conf()
        self.token = W.CancelToken()
        #: per-query watchdog counters (timeouts/cancels/dumps/slowest
        #: heartbeat) — query A's trip must never charge query B's plan
        self.stats = {"timeouts": 0, "cancels": 0, "dumps": 0,
                      "slowest_heartbeat_ms": 0}
        #: per-query deferred-check registry (utils/checks.py): checks
        #: from concurrent queries must not interleave in one list
        self.pending_checks: list = []
        self.tracer = None           # utils/profile.QueryTracer or None
        self.epoch = 0               # minted per top-level attempt
        self.budget_bytes = 0        # declared HBM admission budget
        self.admitted = False        # holds an admission-ledger slot
        self.owner_thread = threading.get_ident()
        self.created = time.monotonic()
        self.report_plan = None      # outermost plan, for the profile
        self._depth = 0              # collect() nesting within this query
        self._lock = threading.Lock()

    def enter_collect(self) -> bool:
        with self._lock:
            self._depth += 1
            return self._depth == 1

    def exit_collect(self) -> bool:
        with self._lock:
            self._depth -= 1
            return self._depth == 0

    @property
    def collect_depth(self) -> int:
        with self._lock:
            return self._depth


_TLS = threading.local()


def current() -> Optional[QueryContext]:
    """The calling thread's QueryContext: the thread-locally installed
    one (driver thread / `scoped` helper threads), else the one riding
    the thread's TaskContext (pipeline producers)."""
    qc = getattr(_TLS, "qc", None)
    if qc is not None:
        return qc
    from spark_rapids_tpu.memory.semaphore import TaskContext
    ctx = TaskContext.get()
    if ctx is not None:
        return getattr(ctx, "query_ctx", None)
    return None


@contextmanager
def scoped(qc: Optional[QueryContext]):
    """Install `qc` as this thread's QueryContext for the duration —
    helper threads (AQE fills, shuffle fetch threads, pipeline
    producers) capture their creator's context via `current()` and
    enter this, so cancellation, conf reads, deferred checks, and
    profile events all resolve to the right query.  None is a no-op."""
    if qc is None:
        yield None
        return
    prev = getattr(_TLS, "qc", None)
    _TLS.qc = qc
    try:
        yield qc
    finally:
        _TLS.qc = prev


# ---------------------------------------------------------------------------
class _QueueEntry:
    __slots__ = ("qc", "budget", "max_queries", "event", "enqueued",
                 "admitted", "rejected")

    def __init__(self, qc: QueryContext, budget: int, max_queries: int):
        self.qc = qc
        self.budget = budget
        self.max_queries = max_queries
        self.event = threading.Event()
        self.enqueued = time.monotonic()
        self.admitted = False
        self.rejected: Optional[str] = None


class QueryScheduler:
    """Process singleton gatekeeper in front of query execution."""

    _instance: Optional["QueryScheduler"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self._cv = threading.Condition()
        self._queue: list[_QueueEntry] = []   # FIFO
        self._stats = {"admitted": 0, "queued": 0, "rejected": 0,
                       "queue_timeouts": 0, "max_queue_depth": 0,
                       "longest_queue_wait_ms": 0}

    @classmethod
    def get(cls) -> "QueryScheduler":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._ilock:
            cls._instance = None

    # -----------------------------------------------------------------------
    @staticmethod
    def _budget_for(conf: C.RapidsConf, dm) -> int:
        declared = int(conf[C.SCHED_QUERY_BUDGET])
        if declared > 0:
            return declared
        maxq = max(1, int(conf[C.SCHED_MAX_CONCURRENT]))
        return max(1, dm.budget // maxq)

    def admit(self, qc: QueryContext, conf: C.RapidsConf) -> bool:
        """Admit `qc` (True) or queue until admissible; raises
        `TpuQueryRejected` when the queue is full or the queue deadline
        passes, and `TpuQueryTimeout` if the query is cancelled while
        queued.  False = scheduler disabled (unmanaged query)."""
        if not conf[C.SCHED_ENABLED]:
            return False
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        from spark_rapids_tpu.utils import profile as P
        dm = DeviceManager.get()
        budget = self._budget_for(conf, dm)
        maxq = max(1, int(conf[C.SCHED_MAX_CONCURRENT]))
        qc.budget_bytes = budget
        with self._cv:
            if self._try_admit_locked(qc, budget, maxq, dm):
                P.event(P.EV_QUERY_ADMITTED, query=qc.query_id,
                        budget_bytes=budget, queued_ms=0)
                return True
            depth = int(conf[C.SCHED_QUEUE_DEPTH])
            if len(self._queue) >= max(0, depth):
                self._stats["rejected"] += 1
                snap = self._snapshot_locked(dm)
                P.event(P.EV_QUERY_REJECTED, query=qc.query_id,
                        budget_bytes=budget, **snap)
                raise TpuQueryRejected(
                    f"query {qc.query_id} rejected: admission queue is "
                    f"full ({len(self._queue)}/{depth} waiting, "
                    f"{snap['admitted_queries']} queries admitted "
                    f"holding {snap['admitted_bytes']}/{dm.budget} "
                    f"budget bytes).  Retry later, raise "
                    f"{C.SCHED_QUEUE_DEPTH.key}, or lower "
                    f"{C.SCHED_QUERY_BUDGET.key} "
                    f"(requested {budget} bytes).")
            entry = _QueueEntry(qc, budget, maxq)
            self._queue.append(entry)
            self._stats["queued"] += 1
            self._stats["max_queue_depth"] = max(
                self._stats["max_queue_depth"], len(self._queue))
            position = len(self._queue)
            P.event(P.EV_QUERY_QUEUED, query=qc.query_id,
                    budget_bytes=budget, position=position)
        return self._wait_admitted(entry, conf, dm)

    def _wait_admitted(self, entry: _QueueEntry, conf: C.RapidsConf,
                       dm) -> bool:
        """Park in the admission queue: bounded polls so cancellation is
        honored, a task-class heartbeat that beats as the queue drains
        (a queue making NO progress past the watchdog deadline trips a
        dump naming every admitted query), and the explicit
        `queueTimeout` bound."""
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        qc = entry.qc
        timeout = float(conf[C.SCHED_QUEUE_TIMEOUT])
        deadline = entry.enqueued + timeout
        last_pos = None
        hb = W.heartbeat(
            f"query-queue:{qc.query_id}", kind="task", conf=conf,
            details=lambda: self.describe())
        try:
            with P.span(f"admission-queue:{qc.query_id}",
                        cat=P.CAT_QUEUE):
                with self._cv:
                    while True:
                        if entry.admitted:
                            waited = (time.monotonic()
                                      - entry.enqueued) * 1e3
                            self._stats["longest_queue_wait_ms"] = max(
                                self._stats["longest_queue_wait_ms"],
                                int(waited))
                            P.event(P.EV_QUERY_ADMITTED,
                                    query=qc.query_id,
                                    budget_bytes=entry.budget,
                                    queued_ms=int(waited))
                            return True
                        try:
                            pos = self._queue.index(entry) + 1
                        except ValueError:
                            pos = 0
                        if pos != last_pos:
                            hb.beat()      # queue progress, not a hang
                            last_pos = pos
                        now = time.monotonic()
                        if qc.token.cancelled or now >= deadline:
                            self._remove_locked(entry)
                            if qc.token.cancelled:
                                qc.token.check()  # raises TpuQueryTimeout
                            self._stats["queue_timeouts"] += 1
                            self._stats["rejected"] += 1
                            snap = self._snapshot_locked(dm)
                            P.event(P.EV_QUERY_REJECTED, query=qc.query_id,
                                    budget_bytes=entry.budget,
                                    timeout_s=timeout, **snap)
                            raise TpuQueryRejected(
                                f"query {qc.query_id} rejected: spent "
                                f"{timeout:.1f}s "
                                f"({C.SCHED_QUEUE_TIMEOUT.key}) in the "
                                f"admission queue at position {pos} "
                                f"({snap['admitted_queries']} queries "
                                f"admitted holding "
                                f"{snap['admitted_bytes']}/{dm.budget} "
                                "budget bytes).")
                        self._cv.wait(min(0.05, max(0.0,
                                                    deadline - now)))
        finally:
            hb.close()
            with self._cv:
                self._remove_locked(entry)

    def _try_admit_locked(self, qc: QueryContext, budget: int,
                          maxq: int, dm) -> bool:
        if len(dm.admissions()) >= maxq:
            return False
        if not dm.try_admit(qc.query_id, budget):
            return False
        qc.admitted = True
        self._stats["admitted"] += 1
        return True

    def _remove_locked(self, entry: _QueueEntry) -> None:
        try:
            self._queue.remove(entry)
        except ValueError:
            pass

    def release(self, qc: QueryContext) -> None:
        """Return `qc`'s admission slot and drain the queue head(s)."""
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        if not qc.admitted:
            return
        dm = DeviceManager.get()
        with self._cv:
            dm.release_admission(qc.query_id)
            qc.admitted = False
            # FIFO drain: admit from the head while it fits.  Stopping
            # at the first non-admissible entry keeps arrival order —
            # a large query at the head is not starved by small ones
            # slipping past it forever.
            for entry in list(self._queue):
                if entry.admitted:
                    continue
                if not self._try_admit_locked(entry.qc, entry.budget,
                                              entry.max_queries, dm):
                    break
                entry.admitted = True
            self._cv.notify_all()

    # -----------------------------------------------------------------------
    def _snapshot_locked(self, dm) -> dict:
        adm = dm.admissions()
        return {"admitted_queries": len(adm),
                "admitted_bytes": sum(adm.values()),
                "queue_depth": len(self._queue)}

    def stats(self) -> dict:
        with self._cv:
            return dict(self._stats)

    def queue_depth(self) -> int:
        """Queries parked in the admission queue RIGHT NOW (telemetry
        gauge + the sampler's queue_wait classification)."""
        with self._cv:
            return len(self._queue)

    def describe(self) -> str:
        """One-line admission state for watchdog dumps / heartbeats."""
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        try:
            dm = DeviceManager.get()
            with self._cv:
                adm = dm.admissions()
                queued = [(e.qc.query_id, e.budget)
                          for e in self._queue]
            return (f"admitted={adm} admitted_bytes="
                    f"{sum(adm.values())}/{dm.budget} queued={queued}")
        except Exception as e:  # noqa: BLE001 — diagnostics only
            return f"<unavailable: {e}>"


# ---------------------------------------------------------------------------
class QueryScope:
    """Query ownership for a driver-side entry point: if the calling
    thread has no live QueryContext, creates one, begins its profile
    (BEFORE admission, so queue wait is a first-class span/category in
    the query's own breakdown), and admits it; otherwise a no-op that
    defers to the enclosing scope.  `plan/overrides.collect` holds one
    around the whole drive (deopt retries, the AQE stage loop, partial
    CPU plans) and `TpuExec.collect` holds one per direct collect."""

    __slots__ = ("qc", "owns", "prof_owner", "_prev_tls")

    def __init__(self, conf: Optional[C.RapidsConf] = None):
        from spark_rapids_tpu.utils import profile as P
        self.qc = current()
        self.owns = self.qc is None
        self.prof_owner = None
        self._prev_tls = None
        if not self.owns:
            return
        conf = conf if conf is not None else C.get_active_conf()
        self.qc = QueryContext(conf)
        self._prev_tls = getattr(_TLS, "qc", None)
        _TLS.qc = self.qc
        # engine-wide telemetry (utils/telemetry.py): lazy-started on
        # the first collect whose conf enables it; the in-flight query
        # count feeds the utilization sampler's idle/host attribution
        from spark_rapids_tpu.utils import telemetry as T
        T.maybe_start(conf)
        T.note_query_begin()
        # kernel attribution (utils/kernelprof.py): same lazy-start
        # discipline — sticky process-wide enable on the first query
        # whose conf asks for it, one global read + one lookup when off
        from spark_rapids_tpu.utils import kernelprof as KP
        KP.maybe_enable(conf)
        try:
            self.prof_owner = P.begin_query(conf)
            QueryScheduler.get().admit(self.qc, conf)
        except BaseException as e:
            self.close(error=e)
            raise

    def close(self, error: Optional[BaseException] = None,
              end_profile: bool = True) -> None:
        """Release admission + the thread-local installation (owner
        only).  `end_profile=False` when the caller already assembled
        the QueryProfile itself (TpuExec.collect orders it around its
        metrics charge)."""
        if not self.owns:
            return
        self.owns = False
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import telemetry as T
        try:
            if end_profile:
                P.end_query(self.prof_owner, self.qc.report_plan,
                            error=error)
        finally:
            QueryScheduler.get().release(self.qc)
            T.note_query_end()
            _TLS.qc = self._prev_tls


class CollectScope:
    """The per-collect handshake `TpuExec.collect` drives.  Not a
    context manager: collect needs the outermost flag and the profile
    owner between its own finally steps."""

    __slots__ = ("plan", "qc", "owns_qc", "prof_owner", "outermost",
                 "_qscope", "_plan_locked")

    def __init__(self, plan):
        self.plan = plan
        self._qscope = QueryScope()
        self.qc = self._qscope.qc
        self.owns_qc = self._qscope.owns
        self.prof_owner = self._qscope.prof_owner
        self._plan_locked = False
        entered = False
        try:
            self.outermost = self.qc.enter_collect()
            entered = True
            if self.outermost:
                # serialize collects over the SAME plan instance: its
                # CommonSubplanExec caches, metrics, and release hooks
                # are instance state.  Distinct plan instances (the
                # normal concurrent-session case) run in parallel.
                self._lock_plan()
                if self.qc.report_plan is None:
                    self.qc.report_plan = plan
        except BaseException:
            if entered:
                # a cancelled plan-lock wait must not leave the depth
                # bumped — a NESTED collect's enclosing query would
                # never see its own outermost exit again
                self.qc.exit_collect()
            self._qscope.close(end_profile=True)
            raise

    def _lock_plan(self) -> None:
        lock = getattr(self.plan, "_plan_lock", None)
        if lock is None:
            return
        while not lock.acquire(timeout=0.1):
            self.qc.token.check()
        self._plan_locked = True

    def finish_collect(self) -> bool:
        """Decrement the query's collect depth; True = this was the
        outermost collect (caller releases plan state + assembles the
        profile before `close`)."""
        return self.qc.exit_collect()

    def close(self) -> None:
        """Release the plan lock and, for the qc owner, the admission
        slot and the thread-local installation (the profile was ended
        by collect itself, ordered after the metrics charge)."""
        if self._plan_locked:
            self.plan._plan_lock.release()
            self._plan_locked = False
        self._qscope.close(end_profile=False)


# ---------------------------------------------------------------------------
# whole-mesh dispatch gate (exec/spmd.py SPMD gang dispatches)
#
# Task-level device sharing is the TpuSemaphore's job, and per-query
# HBM admission is the ledger's — but a whole-mesh program (an SPMD
# gang dispatch, a mesh-exchange all-to-all, or the slicing of their
# sharded outputs) occupies EVERY device of the active mesh at once.
# Two threads enqueueing whole-mesh programs concurrently can invert
# the per-device queue order (program A before B on device 0, B before
# A on device 4) and DEADLOCK the collective rendezvous — observed on
# the 8-device virtual CPU mesh with one query in the hand-rolled
# exchange lane and another in an SPMD gang.  The gate serializes
# every whole-mesh enqueue region process-wide, with the same
# cancellable bounded-poll discipline every other engine wait uses: a
# query cancelled while parked here unwinds instead of queueing a
# dispatch nobody will consume.  Reentrant, so a lane that composes
# whole-mesh steps (count + data phases) can hold it across both.

_MESH_GATE = threading.RLock()
_MESH_GATE_STATS = {"dispatches": 0, "longest_wait_ms": 0}
_MESH_GATE_STATS_LOCK = threading.Lock()


@contextmanager
def whole_mesh_dispatch(label: str = "spmd"):
    """Hold the process-wide whole-mesh dispatch slot for one SPMD gang
    dispatch.  Bounded-poll acquisition honors the calling query's
    CancelToken; stats feed scheduler_stats()/bench summaries."""
    from spark_rapids_tpu.utils import watchdog as W
    t0 = time.monotonic()
    while not _MESH_GATE.acquire(timeout=0.05):
        W.check_cancelled()
    waited_ms = int((time.monotonic() - t0) * 1e3)
    with _MESH_GATE_STATS_LOCK:
        _MESH_GATE_STATS["dispatches"] += 1
        _MESH_GATE_STATS["longest_wait_ms"] = max(
            _MESH_GATE_STATS["longest_wait_ms"], waited_ms)
    try:
        yield
    finally:
        _MESH_GATE.release()


def mesh_gate_stats() -> dict:
    with _MESH_GATE_STATS_LOCK:
        return dict(_MESH_GATE_STATS)


# ---------------------------------------------------------------------------
# plan-fingerprint result cache
class _CacheKey:
    """Equality = structural fingerprint + conf fingerprint + IDENTITY
    of the source data objects.  Holding strong refs to the sources
    pins their ids for the entry's lifetime, so a recycled id can never
    alias a dead source."""

    __slots__ = ("structure", "conf_fp", "sources", "_hash")

    def __init__(self, structure: str, conf_fp: tuple, sources: tuple):
        self.structure = structure
        self.conf_fp = conf_fp
        self.sources = sources
        self._hash = hash((structure, conf_fp,
                           tuple(id(s) for s in sources)))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return (isinstance(other, _CacheKey)
                and self.structure == other.structure
                and self.conf_fp == other.conf_fp
                and len(self.sources) == len(other.sources)
                and all(a is b for a, b in zip(self.sources,
                                               other.sources)))


def _fingerprint_node(node, sources: list) -> Optional[str]:
    """Structural fingerprint of one plan node, collecting source-data
    identity objects into `sources`.  None = this plan is not cacheable
    (an unrecognized leaf / stateful wrapper) — never guess."""
    from spark_rapids_tpu.exec.base import (CommonSubplanExec, TpuExec)
    from spark_rapids_tpu.exec.basic import LocalBatchSource, RangeExec
    if not isinstance(node, TpuExec):
        return None
    if isinstance(node, LocalBatchSource):
        # prefer the plan-build-stable identity (the backing pandas
        # partitions, stamped by the CpuSource converter): re-planning
        # the same query uploads FRESH device batches, but the session's
        # source frames persist — those are what "same data" means
        ident = getattr(node, "source_identity", None)
        sources.extend(ident if ident is not None
                       else (b for part in node.partitions
                             for b in part))
        return (f"LocalBatchSource({len(node.partitions)} parts,"
                f"{node.output_schema()})")
    if isinstance(node, RangeExec):
        return node.describe()
    if type(node).__name__ == "TpuFileSourceScanExec":
        # file identity: path + per-file (size, mtime) so a rewritten
        # file invalidates the entry
        try:
            stats = []
            for part in node.scan.partitions:
                for f in part.files:
                    st = os.stat(f.path)
                    stats.append((f.path, st.st_size, st.st_mtime_ns))
            return f"{node.describe()}::{sorted(stats)!r}"
        except Exception:  # noqa: BLE001 — unstatable source: no cache
            return None
    if isinstance(node, CommonSubplanExec) or node.children:
        kids = []
        for c in node.children:
            fp = _fingerprint_node(c, sources)
            if fp is None:
                return None
            kids.append(fp)
        return f"{node.describe()}[{';'.join(kids)}]"
    return None  # unrecognized leaf (stage wrappers, transitions, ...)


def result_cache_key(plan, conf: C.RapidsConf) -> Optional[_CacheKey]:
    """Cache key for a fully-TPU plan under `conf`, or None when result
    caching is disabled / the plan is not fingerprintable."""
    if not conf[C.RESULT_CACHE_ENABLED]:
        return None
    if int(conf[C.RESULT_CACHE_MAX_BYTES]) <= 0:
        return None
    sources: list = []
    try:
        structure = _fingerprint_node(plan, sources)
    except Exception:  # noqa: BLE001 — a fingerprint failure means
        return None    # "don't cache", never "fail the query"
    if structure is None:
        return None
    return _CacheKey(structure, conf.fingerprint(), tuple(sources))


class ResultCache:
    """Byte-bounded LRU of collected query results (pandas frames)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._bytes = 0
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "stores": 0}

    @staticmethod
    def _df_bytes(df) -> int:
        try:
            return int(df.memory_usage(index=True, deep=True).sum())
        except Exception:  # noqa: BLE001
            return 1 << 20

    def get(self, key: _CacheKey):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._stats["misses"] += 1
                return None
            self._entries.move_to_end(key)
            self._stats["hits"] += 1
            df, _ = hit
        # copy OUTSIDE the lock: callers may mutate the returned frame
        return df.copy(deep=True)

    def put(self, key: _CacheKey, df, max_bytes: int) -> None:
        nbytes = self._df_bytes(df)
        if nbytes > max_bytes:
            return  # larger than the whole cache: not worth holding
        frozen = df.copy(deep=True)
        with self._lock:
            if key in self._entries:
                _, old = self._entries.pop(key)
                self._bytes -= old
            self._entries[key] = (frozen, nbytes)
            self._bytes += nbytes
            self._stats["stores"] += 1
            while self._bytes > max_bytes and self._entries:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= evicted
                self._stats["evictions"] += 1

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {**self._stats, "entries": len(self._entries),
                    "bytes": self._bytes}


_RESULT_CACHE = ResultCache()


def result_cache() -> ResultCache:
    return _RESULT_CACHE


def scheduler_stats() -> dict:
    """Scheduler + result-cache counters for bench/CI summary lines."""
    return {**QueryScheduler.get().stats(),
            "result_cache": _RESULT_CACHE.stats(),
            "mesh_gate": mesh_gate_stats()}
