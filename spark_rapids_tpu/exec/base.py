"""Physical operator base (reference `GpuExec.scala:58-123`).

A `TpuExec` produces an iterator of `ColumnarBatch` — the TPU analog of
`doExecuteColumnar(): RDD[ColumnarBatch]`.  The engine is host-driven like
Spark tasks: Python orchestrates batch flow, while all per-batch compute
runs in jitted XLA executables.

The kernel compile cache is the central XLA-fit mechanism (SURVEY.md §7
hard part (a)): executables are keyed on (plan node, batch shape signature)
so ragged Spark batches hit a small set of bucketed compilations.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import EvalContext, Expression
from spark_rapids_tpu.utils import kernelprof as KP
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils.tracing import trace_range


# ---------------------------------------------------------------------------
# coalesce goals (reference GpuCoalesceBatches.scala:91-113)
@dataclasses.dataclass(frozen=True)
class CoalesceGoal:
    pass


@dataclasses.dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    bytes: int


@dataclasses.dataclass(frozen=True)
class RequireSingleBatch(CoalesceGoal):
    pass


def max_goal(a: Optional[CoalesceGoal], b: Optional[CoalesceGoal]
             ) -> Optional[CoalesceGoal]:
    if isinstance(a, RequireSingleBatch) or isinstance(b, RequireSingleBatch):
        return RequireSingleBatch()
    if isinstance(a, TargetSize) and isinstance(b, TargetSize):
        return TargetSize(max(a.bytes, b.bytes))
    return a or b


# ---------------------------------------------------------------------------
def columns_signature(fields, cols) -> tuple:
    """Per-column shape signature entries for the compile cache:
    (dtype, char_cap, narrowed?)."""
    return tuple((f.dtype.id.value,
                  c.char_cap if f.dtype.is_string else 0,
                  c.narrow is not None)
                 for f, c in zip(fields, cols))


def batch_signature(batch: ColumnarBatch) -> tuple:
    """Shape signature for the compile cache: capacity + per-column
    (dtype, char_cap)."""
    return ((batch.capacity,)
            + columns_signature(batch.schema.fields, batch.columns)
            + (batch.sparse is not None,))


def mesh_cache_scope(mesh, axis: str, shardings=()) -> tuple:
    """Cache-key component for whole-mesh (SPMD) executables: the mesh
    shape, its device identity, the partitioned axis, and the sharding
    layout descriptors.  An SPMD program is specialized to all of these
    — a kernel compiled for one mesh/sharding must never be served for
    another, and (because this tuple appears in no per-partition key)
    SPMD and per-partition entries can never collide.  Device identity
    enters as ids, not Device objects, so a dead mesh is not pinned
    beyond its cached executables' LRU lifetime."""
    return ("mesh",
            tuple((name, int(n)) for name, n in mesh.shape.items()),
            tuple(d.id for d in mesh.devices.flat),
            axis,
            tuple(str(s) for s in shardings))


#: process-global executable store (bounded LRU): compiled kernels outlive
#: plan instances, so per-query plan rebuilds and AQE re-plans over the
#: same expressions hit warm executables instead of re-tracing
import collections
import threading

_GLOBAL_KERNELS: "collections.OrderedDict" = collections.OrderedDict()
_GLOBAL_KERNELS_LOCK = threading.Lock()
# one workload's operator x batch-shape set is well under this; XLA CPU
# clients have been observed to segfault with thousands of live loaded
# executables, so the LRU stays conservatively small.  Conf-overridable
# (spark.rapids.sql.kernelCache.maxEntries): fused-stage keys multiply
# cache pressure, so the bound and its eviction count are first-class.
_GLOBAL_KERNELS_MAX = 512
_GLOBAL_KERNELS_EVICTIONS = 0


def _kernel_cache_max_entries() -> int:
    try:
        from spark_rapids_tpu import config as C
        return max(1, int(C.get_active_conf()[C.KERNEL_CACHE_MAX_ENTRIES]))
    except Exception:  # noqa: BLE001 — conf layer unavailable in
        return _GLOBAL_KERNELS_MAX  # stripped-down test harnesses
#: single-flight registry: keys whose builder is currently tracing /
#: compiling on some thread (value: Event set when it lands or fails).
#: XLA compiles run seconds-to-minutes, so they must happen OUTSIDE
#: _GLOBAL_KERNELS_LOCK — but with pipelined execution two threads
#: routinely reach the same (exec, signature) miss together, and
#: compiling the same kernel twice wastes exactly the time pipelining
#: saves.  Losing a rare race anyway (event timeout, builder failure)
#: degrades to the benign double-compile, never to a wrong result.
_GLOBAL_KERNELS_BUILDING: dict = {}


def clear_kernel_cache() -> None:
    with _GLOBAL_KERNELS_LOCK:
        _GLOBAL_KERNELS.clear()


def kernel_cache_size() -> int:
    return len(_GLOBAL_KERNELS)


def kernel_cache_evictions() -> int:
    """LRU evictions since process start (bench summary surfaces this:
    a growing number means kernelCache.maxEntries is churning)."""
    return _GLOBAL_KERNELS_EVICTIONS


#: cumulative trace/compile accounting (telemetry registry): every
#: `_build_watched` builder run lands here, private-cache and global
#: alike, so compile cost is visible process-wide even when the profile
#: span layer is off
_COMPILE_STATS_LOCK = threading.Lock()
_COMPILE_NS_TOTAL = 0
_COMPILE_COUNT = 0


def kernel_cache_compiles() -> int:
    with _COMPILE_STATS_LOCK:
        return _COMPILE_COUNT


def kernel_cache_compile_ms() -> float:
    with _COMPILE_STATS_LOCK:
        return _COMPILE_NS_TOTAL / 1e6


class KernelCache:
    """Caches jitted executables per (scope, key, signature).

    With a `scope` (a structural fingerprint of the exec's bound
    expressions), entries live in the process-global LRU and are shared
    across plan instances.  Without one, the cache is private to the exec
    and dies with the plan (the pre-fingerprint behavior, still used by
    execs whose kernels close over non-fingerprintable state)."""

    def __init__(self, scope: tuple = None):
        self._scope = scope
        self._cache: dict = {} if scope is None else None

    @staticmethod
    def _build_watched(key, builder: Callable[[], Callable],
                       kp_entry=None):
        """Run the (seconds-to-minutes) trace/compile under a
        compile-class watchdog heartbeat, with the compile hang-
        injection site in front so a wedged XLA compile is testable.
        A profiled query additionally records the compile as a span
        (cat 'compile'), so cold-start cost is attributable in the
        wall-clock breakdown; with kernel attribution on, the builder
        wall time also lands on the kernel's catalog entry
        (utils/kernelprof.py — the first DISPATCH, where a lazy jit
        actually compiles, is timed there separately)."""
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        label = f"compile:{key!r:.120}"
        with W.heartbeat(label, kind="compile"), \
                P.span(label, cat=P.CAT_COMPILE):
            W.maybe_hang("compile")
            import time as _time
            t0 = _time.perf_counter_ns()
            try:
                return builder()
            finally:
                global _COMPILE_NS_TOTAL, _COMPILE_COUNT
                dt = _time.perf_counter_ns() - t0
                with _COMPILE_STATS_LOCK:
                    _COMPILE_NS_TOTAL += dt
                    _COMPILE_COUNT += 1
                if kp_entry is not None:
                    kp_entry.note_build(dt)

    def _kp_identity(self, key: tuple) -> tuple:
        """Catalog identity for a kernel of this cache: the structural
        scope when there is one; private caches get a process-unique
        token so unrelated private kernels never merge."""
        if self._scope is not None:
            return (self._scope, key)
        tok = self.__dict__.get("_kp_token")
        if tok is None:
            tok = self.__dict__["_kp_token"] = \
                ("private", KP.private_token())
        return (tok, key)

    def get_or_build(self, key: tuple, builder: Callable[[], Callable],
                     meta: Optional[dict] = None):
        """`meta` (only read while kernel attribution is enabled —
        build it via `TpuExec.kp_meta`, which returns None otherwise)
        attaches dispatch-site context to the kernel's catalog entry:
        a human label, the owning exec, and fused member names."""
        kp_on = KP.enabled()
        if self._scope is None:
            fn = self._cache.get(key)
            if fn is None:
                if kp_on:
                    ident = self._kp_identity(key)
                    fn = self._build_watched(key, builder,
                                             KP.entry_for(ident))
                    fn = KP.watch(ident, fn)
                else:
                    fn = self._build_watched(key, builder)
                self._cache[key] = fn
            elif kp_on and callable(fn) \
                    and not isinstance(fn, KP.WatchedKernel):
                # cached before attribution was enabled: upgrade in
                # place — the executable is already warm, so its first
                # wrapped dispatch is device time, not compile
                fn = KP.watch(self._kp_identity(key), fn, cold=False)
                self._cache[key] = fn
            if kp_on and meta is not None:
                KP.annotate(fn, meta)
            return fn
        from spark_rapids_tpu.utils import watchdog as W
        gk = (self._scope, key)
        claimed: Optional[threading.Event] = None
        while True:
            with _GLOBAL_KERNELS_LOCK:
                fn = _GLOBAL_KERNELS.get(gk)
                if fn is not None:
                    _GLOBAL_KERNELS.move_to_end(gk)
                    if kp_on and callable(fn) \
                            and not isinstance(fn, KP.WatchedKernel):
                        # cached before attribution was enabled:
                        # upgrade the shared entry in place (warm —
                        # its first dispatch is NOT a compile)
                        fn = KP.watch(gk, fn, cold=False)
                        _GLOBAL_KERNELS[gk] = fn
                if fn is None:
                    ev = _GLOBAL_KERNELS_BUILDING.get(gk)
                    if ev is None:
                        # claim the build; compile happens OUTSIDE the
                        # lock
                        claimed = threading.Event()
                        _GLOBAL_KERNELS_BUILDING[gk] = claimed
                        break
            if fn is not None:
                if kp_on and meta is not None:
                    KP.annotate(fn, meta)
                return fn
            # another thread is tracing/compiling this exact kernel:
            # wait for it instead of double-compiling, bounded by the
            # watchdog's compile deadline (and cancellable).  On wake,
            # either the entry is cached (loop hits it) or the builder
            # failed (loop re-claims and this thread builds).  On
            # TIMEOUT the builder may be wedged: fall through and
            # compile in THIS thread — a benign double compile, never
            # a proceed-with-missing-entry.
            if not W.cancellable_wait(ev, W.deadline_for("compile")):
                import logging
                logging.getLogger("spark_rapids_tpu.exec").warning(
                    "kernel single-flight wait exceeded the compile "
                    "deadline for %r; the claiming builder may be "
                    "wedged — compiling in this thread instead",
                    gk[1])
                break
        try:
            # builder runs OUTSIDE the lock
            fn = self._build_watched(key, builder, KP.entry_for(gk)) \
                if kp_on else self._build_watched(key, builder)
        except BaseException:
            if claimed is not None:
                with _GLOBAL_KERNELS_LOCK:
                    if _GLOBAL_KERNELS_BUILDING.get(gk) is claimed:
                        _GLOBAL_KERNELS_BUILDING.pop(gk, None)
                claimed.set()
            raise
        if kp_on:
            fn = KP.watch(gk, fn)
        max_entries = _kernel_cache_max_entries()
        with _GLOBAL_KERNELS_LOCK:
            _GLOBAL_KERNELS[gk] = fn
            global _GLOBAL_KERNELS_EVICTIONS
            while len(_GLOBAL_KERNELS) > max_entries:
                _GLOBAL_KERNELS.popitem(last=False)
                _GLOBAL_KERNELS_EVICTIONS += 1
            if claimed is not None and \
                    _GLOBAL_KERNELS_BUILDING.get(gk) is claimed:
                _GLOBAL_KERNELS_BUILDING.pop(gk, None)
        if claimed is not None:
            claimed.set()
        if kp_on and meta is not None:
            KP.annotate(fn, meta)
        return fn

    def __len__(self):
        if self._scope is None:
            return len(self._cache)
        with _GLOBAL_KERNELS_LOCK:
            return sum(1 for s, _ in _GLOBAL_KERNELS if s == self._scope)




def make_eval_context(columns: list[ColumnVector], capacity: int,
                      num_rows, mask=None) -> EvalContext:
    """`mask` (a sparse selection vector) overrides the prefix row mask —
    sparse-aware kernels fold deferred selections in for free."""
    row_mask = mask if mask is not None else (
        jnp.arange(capacity) < num_rows)
    return EvalContext(columns, capacity, num_rows, row_mask)


import itertools

_EXEC_IDS = itertools.count()


class TpuExec:
    """Base physical operator."""

    def __init__(self, *children: "TpuExec"):
        self._children = list(children)
        self.metrics = M.MetricSet()
        self.exec_id = next(_EXEC_IDS)
        #: serializes top-level collects over THIS plan instance: its
        #: CommonSubplanExec caches, metrics, and release hooks are
        #: instance state, so two sessions sharing one plan object run
        #: one at a time while distinct plan instances run concurrently
        self._plan_lock = threading.Lock()

    @property
    def kernels(self) -> KernelCache:
        """Compile cache, resolved lazily so `cache_scope()` can use
        subclass state set after base __init__.  Scoped execs share the
        bounded global store; unscoped ones keep a private cache."""
        kc = self.__dict__.get("_kernel_cache")
        if kc is None:
            scope = self.cache_scope()
            if scope is not None:
                scope = (type(self).__name__,) + tuple(scope)
            kc = KernelCache(scope)
            self.__dict__["_kernel_cache"] = kc
        return kc

    def cache_scope(self):
        """Structural fingerprint of everything this exec's kernels close
        over (bound expressions, modes, output schema).  None -> private
        cache (no cross-instance sharing)."""
        return None

    def kp_meta(self, label: str, members=None) -> Optional[dict]:
        """Dispatch-site metadata for the kernel catalog
        (utils/kernelprof.py): pass as `get_or_build(..., meta=...)`.
        Returns None — allocating nothing — when kernel attribution is
        off, so the disabled hot path stays byte-identical."""
        if not KP.enabled():
            return None
        return {"label": label, "owner_id": self.exec_id,
                "owner": self.describe()[:120],
                "members": list(members) if members else None}

    @property
    def children(self) -> list["TpuExec"]:
        return self._children

    @property
    def child(self) -> "TpuExec":
        return self._children[0]

    def output_schema(self) -> T.Schema:
        raise NotImplementedError

    # coalesce contract (reference GpuExec.coalesceAfter /
    # childrenCoalesceGoal)
    @property
    def coalesce_after(self) -> bool:
        return False

    def children_coalesce_goal(self) -> list[Optional[CoalesceGoal]]:
        return [None] * len(self._children)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    def output_partition_count(self) -> int:
        """Planning-time partition count (outputPartitioning analog).
        MUST NOT execute anything — planners consult this."""
        if not self._children:
            return 1
        return self._children[0].output_partition_count()

    def execute_partitions(self) -> list[Iterator[ColumnarBatch]]:
        """Partitioned execution (RDD analog).  Default: operators that are
        partition-local map themselves over each child partition."""
        from spark_rapids_tpu.utils import profile as P
        kids = [c.execute_partitions() for c in self._children]
        if not kids:
            return [P.wrap_operator(self, 0, self.execute_columnar())]
        n = len(kids[0])
        return [P.wrap_operator(
                    self, i, self._execute_partition(
                        i, [k[i] for k in kids]))
                for i in range(n)]

    def _execute_partition(self, idx: int, child_iters
                           ) -> Iterator[ColumnarBatch]:
        # default: single-child partition-local operators override
        # execute_columnar using self.child; rebuild with a shim child.
        raise NotImplementedError(
            f"{type(self).__name__} does not support partitioned execution")

    #: bounded deopt attempts: intermediate retries may take optimistic
    #: fast paths with ESCALATED parameters (e.g. a ×4'd group-compact
    #: width) and fail again; only the LAST runs with every fast path
    #: forced off (is_retrying) for a guaranteed-valid result.  The old
    #: single-retry scheme jumped straight to full-width kernels, whose
    #: compile-time buffer assignment OOMed HBM at 8M-row caps.
    MAX_DEOPT_RETRIES = 3

    def collect(self) -> ColumnarBatch:
        """Materialize to one batch; the sync boundary where deferred
        fast-path checks resolve.  On FastPathInvalid: disable/escalate
        the offending fast path and re-execute (plans are pure), up to
        MAX_DEOPT_RETRIES times.

        Concurrency: the outermost collect on a thread with no live
        QueryContext creates one (exec/scheduler.py CollectScope) —
        its own conf snapshot, CancelToken, deferred-check registry,
        profile tracer, and an HBM admission slot — so top-level
        collects from different sessions run CONCURRENTLY, each
        isolated; a saturated device queues or sheds new queries at
        admission instead of thrashing the spill/retry lattice."""
        from spark_rapids_tpu.exec import scheduler as S
        from spark_rapids_tpu.utils import checks as CK
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        if S.current() is None:
            # reset the legacy process-global fallback token so a
            # previous query-less cancellation cannot bleed in
            W.begin_query()
        scope = S.CollectScope(self)
        prof_owner = scope.prof_owner if scope.owns_qc else None
        mark = CK.snapshot()
        prof_error: Optional[BaseException] = None
        try:
            for attempt in range(self.MAX_DEOPT_RETRIES + 1):
                final = attempt == self.MAX_DEOPT_RETRIES
                if attempt:
                    CK.set_retrying(final)
                try:
                    out = self._collect_once().dense()
                    out.prefetch()
                    # ONE verify over batch checks + the query's
                    # registered checks = one stacked flag readback (a
                    # second verify call would pay its own round trip).
                    # Under the async pipeline layer the batch's lazy
                    # row count rides the SAME readback (host-sync
                    # diet: the to_pandas conversion right after this
                    # otherwise pays its own round trip for the count).
                    checks = list(out.checks) + CK.drain_since(mark)
                    from spark_rapids_tpu import config as C
                    if (not out.num_rows_known
                            and C.get_active_conf()[C.PIPELINE_ENABLED]):
                        (rows,) = CK.verify(checks,
                                            scalars=[out.num_rows_i32])
                        out.num_rows = int(rows)
                    else:
                        CK.verify(checks)
                    return out
                except CK.FastPathInvalid as e:
                    if final:
                        prof_error = e
                        raise
                    e.recover_all()
                    P.event(P.EV_DEOPT_RETRY, origin=", ".join(
                        c.origin for c in e.checks))
                    CK.drain_since(mark)  # discard this attempt's rest
                finally:
                    if attempt:
                        CK.set_retrying(False)
        except BaseException as e:
            prof_error = e
            raise
        finally:
            outermost = scope.finish_collect()
            if outermost:
                # only the OUTERMOST collect tears down shared-subtree
                # caches: a nested collect (CpuBroadcastExchange
                # materializing its child mid-plan) must not clear the
                # enclosing query's CommonSubplanExec results
                self.release_execution_state()
                qs = W.query_stats()
                if qs["timeouts"] or qs["cancels"]:
                    # charge watchdog activity to the plan root ONLY on
                    # a tripped query — a clean collect must not force
                    # a metric resolve (device readbacks) it would
                    # otherwise defer
                    self.metrics.add(M.NUM_WATCHDOG_TIMEOUTS,
                                     qs["timeouts"])
                    self.metrics.add(M.NUM_CANCELS, qs["cancels"])
                    self.metrics.add(M.WATCHDOG_DUMPS, qs["dumps"])
                    self.metrics.set_max(
                        M.SLOWEST_HEARTBEAT,
                        qs["slowest_heartbeat_ms"])
                # assemble the QueryProfile LAST so the plan report
                # sees every metric this query charged
                P.end_query(prof_owner, self, error=prof_error)
            # plan lock / admission slot / thread-local context release
            scope.close()

    def _collect_once(self) -> ColumnarBatch:
        from spark_rapids_tpu.columnar.batch import concat_batches, empty_batch
        from spark_rapids_tpu.exec import scheduler as S
        qc = S.current()
        if qc is not None and qc.collect_depth <= 1:
            # new top-level execution attempt: shared subtrees re-run.
            # Nested collects (broadcast materialization inside a plan)
            # must NOT bump the epoch — that would silently invalidate
            # the outer query's CommonSubplanExec caches mid-execution.
            # Epochs are minted from one process-global counter but
            # scoped to THIS query, so a concurrent query's attempt
            # never invalidates this query's shared-subtree caches.
            qc.epoch = S.new_epoch()
        batches = list(self.execute_columnar())
        if not batches:
            return empty_batch(self.output_schema())
        # sparse_ok: collect() densifies right after, so the concat can
        # skip per-input compaction gathers — one gather round total
        return concat_batches(batches, sparse_ok=True)

    def to_pandas(self):
        return self.collect().to_pandas()

    def update_output_metrics(self, batch: ColumnarBatch) -> None:
        self.metrics.add(M.NUM_OUTPUT_ROWS, batch._rows)
        self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)

    def oom_retry_batches(self, batch: ColumnarBatch, body,
                          split: bool = True, out_bytes_fn=None,
                          label: str = None):
        """Reservation-aware batch processing: route one batch's
        materialization through the OOM retry harness (memory/retry.py)
        — reserve HBM for the output, spill under pressure with the
        semaphore yielded, split the input in half and retry on
        reservation failure, and past the row floor degrade via the
        conf'd fallback.  Yields one `body(piece)` result per (possibly
        split) piece in row order, charging this exec's numRetries /
        numSplitRetries / spillBytes / retryBlockTime metrics.

        `split=False` is for single-batch contracts that cannot
        subdivide their input (window frames, RequireSingleBatch
        consumers): pressure there spills + retries in place and the
        floor fallback handles the rest."""
        from spark_rapids_tpu.memory import retry as R
        from spark_rapids_tpu.utils import watchdog as W
        label = label or self.name()
        # batch boundary = cancellation point: a watchdog-cancelled
        # query stops dispatching new work here instead of grinding on
        W.check_cancelled()
        if split:
            for out in R.with_split_retry(
                    batch, body, metrics=self.metrics,
                    out_bytes_fn=out_bytes_fn, label=label):
                W.check_cancelled()
                yield out
        else:
            nbytes = (out_bytes_fn or R.estimate_batch_bytes)(batch)
            yield R.with_retry(lambda: body(batch), out_bytes=nbytes,
                               metrics=self.metrics, label=label)

    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe()
        for c in self._children:
            s += "\n" + c.tree_string(indent + 1)
        return s

    def release_execution_state(self) -> None:
        """Drop per-execution materialized state (CommonSubplanExec
        caches) after a collect completes, so a finished query doesn't
        pin its shared subtrees' device batches."""
        for c in self._children:
            c.release_execution_state()

    def describe(self) -> str:
        return self.name()

    def __repr__(self):
        return self.tree_string()


#: THREAD MODEL (superseding the ADVICE r4 one-query-at-a-time note):
#: execution-attempt epochs, collect nesting depth, the CancelToken,
#: the deferred-check registry, and the profile tracer all live on a
#: per-query QueryContext (exec/scheduler.py) installed by the
#: outermost collect and threaded to helper threads via TaskContext —
#: so top-level collects from DIFFERENT sessions run concurrently,
#: each against its own conf snapshot, serialized only when they share
#: one plan INSTANCE (the per-plan `_plan_lock`).  Epochs are minted
#: from one process-global counter (scheduler.new_epoch) so no two
#: attempts, in any query, can collide on a CommonSubplanExec cache
#: tag.


class CommonSubplanExec(TpuExec):
    """Execute-once wrapper for a subtree shared by several parents
    (plan DAGs with reused CTEs: TPC-DS q64's cross_sales, q23's
    frequent-items subquery).  The role Spark's ReusedExchangeExec
    plays for the reference: without it every consumer re-executes the
    whole shared subtree."""

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._epoch = -1
        self._cached = None

    def output_schema(self):
        return self.child.output_schema()

    def output_partition_count(self):
        return self.child.output_partition_count()

    @property
    def coalesce_after(self) -> bool:
        # transparent for coalesce insertion: a shared subtree rooted
        # at a batch-shrinking exec still wants coalesce above it
        return self.child.coalesce_after

    def describe(self):
        return "CommonSubplanExec"

    def execute_partitions(self):
        from spark_rapids_tpu.exec import scheduler as S
        epoch = S.current_epoch()
        if self._epoch != epoch:
            self._cached = [list(it)
                            for it in self.child.execute_partitions()]
            self._epoch = epoch
        return [iter(p) for p in self._cached]

    def execute_columnar(self):
        for it in self.execute_partitions():
            yield from it

    def release_execution_state(self):
        self._cached = None
        self._epoch = -1
        super().release_execution_state()


class SchemaOnlyExec(TpuExec):
    """Placeholder child carrying just a schema, for internal helper
    execs (merge nodes, shared sorters)."""

    def __init__(self, schema: T.Schema):
        super().__init__()
        self._schema = schema

    def output_schema(self) -> T.Schema:
        return self._schema


class LeafExec(TpuExec):
    def execute_partitions(self):
        from spark_rapids_tpu.utils import profile as P
        return [P.wrap_operator(self, 0, self.execute_columnar())]


class UnaryExecBase(TpuExec):
    """Partition-local single-child operator: processes one child batch
    iterator into an output iterator."""

    def process_partition(self, batches: Iterator[ColumnarBatch]
                          ) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        # preserve partition-local semantics (RDD mapPartitions): process
        # each child partition separately, then chain
        for it in self.execute_partitions():
            yield from it

    def execute_partitions(self):
        from spark_rapids_tpu.utils import profile as P
        return [P.wrap_operator(self, i, self.process_partition(it))
                for i, it in enumerate(self.child.execute_partitions())]


def bind_exprs(exprs: Sequence[Expression], schema: T.Schema
               ) -> list[Expression]:
    return [e.bind(schema) for e in exprs]
