"""Basic physical operators: Project, Filter, Range, Union, LocalSource
(reference `basicPhysicalOperators.scala:35-177`, `limit.scala`).

Project fuses its whole expression list into ONE jitted kernel per batch
bucket — XLA fuses the expression DAG into a single pass over HBM, which is
the TPU answer to cuDF's per-expression kernel launches.

Filter computes a stable compaction inside the kernel (mask -> packed
gather indices via `jnp.nonzero(..., size=capacity)`), returning the new
row count as a device scalar; only that scalar syncs to host.
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import ColumnVector, bucket_capacity
from spark_rapids_tpu.exec.base import (
    LeafExec, TpuExec, UnaryExecBase, batch_signature,
    bind_exprs, make_eval_context)
from spark_rapids_tpu.exprs.base import Expression, output_name
from spark_rapids_tpu.utils import metrics as M


def _register_ansi(flags, labels) -> tuple:
    """Register ANSI-mode expression checks (flags returned by the
    kernel, labels captured at trace time) as FATAL deferred checks."""
    if not flags:
        return ()
    from spark_rapids_tpu.utils import checks as CK
    out = []
    for i, flag in enumerate(flags):
        label = labels[i] if i < len(labels) else "ANSI expression check"
        out.append(CK.register(CK.BatchCheck(
            flag, label,
            error=lambda label=label: ArithmeticError(
                f"{label} (spark.sql.ansi.enabled semantics)"))))
    return tuple(out)


class ProjectExec(UnaryExecBase):
    """Reference GpuProjectExec."""

    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        super().__init__(child)
        self.exprs = list(exprs)
        child_schema = child.output_schema()
        self._bound = bind_exprs(self.exprs, child_schema)
        self._schema = T.Schema(tuple(
            T.Field(output_name(e, i), b.data_type(child_schema))
            for i, (e, b) in enumerate(zip(self.exprs, self._bound))))

    def output_schema(self) -> T.Schema:
        return self._schema

    def cache_scope(self):
        from spark_rapids_tpu.exprs.base import fingerprint
        return (fingerprint(self._bound),)

    def describe(self):
        return f"ProjectExec({', '.join(map(repr, self.exprs))})"

    def _kernel(self, batch: ColumnarBatch):
        key = ("project", batch_signature(batch))

        def build():
            bound = self._bound
            cap = batch.capacity

            labels: list = []

            @jax.jit
            def kernel(columns, num_rows, mask=None):
                ctx = make_eval_context(columns, cap, num_rows, mask)
                out = [e.eval(ctx) for e in bound]
                # labels are static per trace; flags are traced outputs
                labels.clear()
                labels.extend(l for l, _ in ctx.pending_checks)
                return out, tuple(f for _, f in ctx.pending_checks)

            kernel._ansi_labels = labels
            return kernel

        return self.kernels.get_or_build(key, build,
                                         meta=self.kp_meta("project"))

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        for batch in batches:
            with self.metrics.timed(M.TOTAL_TIME):
                kernel = self._kernel(batch)
                if batch.sparse is not None:
                    out_cols, pend = kernel(batch.columns,
                                            batch.num_rows_i32,
                                            batch.sparse)
                else:
                    out_cols, pend = kernel(batch.columns,
                                            batch.num_rows_i32)
                checks = batch.checks + _register_ansi(
                    pend, kernel._ansi_labels)
                out = ColumnarBatch(self._schema, list(out_cols),
                                    batch._rows, checks,
                                    batch.sparse)
                self.update_output_metrics(out)
            yield out


class FilterExec(UnaryExecBase):
    """Reference GpuFilterExec; sets coalesce_after since filtering shrinks
    batches (GpuExec.coalesceAfter)."""

    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__(child)
        self.condition = condition
        self._bound = condition.bind(child.output_schema())
        self._schema = child.output_schema()

    @property
    def coalesce_after(self) -> bool:
        return True

    def output_schema(self) -> T.Schema:
        return self._schema

    def cache_scope(self):
        from spark_rapids_tpu.exprs.base import fingerprint
        return (fingerprint(self._bound),)

    def describe(self):
        return f"FilterExec({self.condition!r})"

    def _kernel(self, batch: ColumnarBatch):
        key = ("filter", batch_signature(batch))

        def build():
            bound = self._bound
            cap = batch.capacity

            labels: list = []

            @jax.jit
            def kernel(columns, num_rows, mask=None):
                ctx = make_eval_context(columns, cap, num_rows, mask)
                pred = bound.eval(ctx)
                keep = pred.validity & pred.data.astype(bool) & ctx.row_mask
                labels.clear()
                labels.extend(l for l, _ in ctx.pending_checks)
                return (keep, keep.sum().astype(jnp.int32),
                        tuple(f for _, f in ctx.pending_checks))

            kernel._ansi_labels = labels
            return kernel

        return self.kernels.get_or_build(key, build,
                                         meta=self.kp_meta("filter"))

    def process_partition(self, batches) -> Iterator[ColumnarBatch]:
        for batch in batches:
            with self.metrics.timed(M.TOTAL_TIME):
                kernel = self._kernel(batch)
                if batch.sparse is not None:
                    keep, count, pend = kernel(batch.columns,
                                               batch.num_rows_i32,
                                               batch.sparse)
                else:
                    keep, count, pend = kernel(batch.columns,
                                               batch.num_rows_i32)
                # DEFERRED SELECTION: no compaction here — the kept rows
                # ride as a sparse mask; sparse-aware consumers fold it
                # into their row masking, everyone else compacts lazily
                checks = batch.checks + _register_ansi(
                    pend, kernel._ansi_labels)
                out = ColumnarBatch(self._schema, batch.columns, count,
                                    checks, sparse=keep)
                self.update_output_metrics(out)
            yield out


class LocalBatchSource(LeafExec):
    """Test/source exec over in-memory batches (one partition per list)."""

    def __init__(self, partitions: list[list[ColumnarBatch]],
                 schema: Optional[T.Schema] = None):
        super().__init__()
        self.partitions = partitions
        first = next((b for p in partitions for b in p), None)
        self._schema = schema or (first.schema if first else T.Schema(()))

    def output_schema(self) -> T.Schema:
        return self._schema

    def output_partition_count(self) -> int:
        return max(1, len(self.partitions))

    def execute_columnar(self):
        for part in self.partitions:
            yield from part

    def execute_partitions(self):
        return [iter(p) for p in self.partitions]

    @staticmethod
    def from_pandas(df, num_partitions: int = 1) -> "LocalBatchSource":
        n = len(df)
        if num_partitions <= 1 or n == 0:
            return LocalBatchSource([[ColumnarBatch.from_pandas(df)]])
        bounds = np.linspace(0, n, num_partitions + 1).astype(int)
        parts = []
        for i in range(num_partitions):
            chunk = df.iloc[bounds[i]: bounds[i + 1]].reset_index(drop=True)
            parts.append([ColumnarBatch.from_pandas(chunk)]
                         if len(chunk) else [])
        return LocalBatchSource(parts)


class RangeExec(LeafExec):
    """Reference GpuRangeExec: generate [start, end) step in target-size
    chunks, on device via iota."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1, target_rows: int = 1 << 20,
                 name: str = "id"):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)
        self.target_rows = target_rows
        self._schema = T.Schema.of((name, T.INT64, False))

    def output_partition_count(self) -> int:
        return self.num_partitions

    def output_schema(self) -> T.Schema:
        return self._schema

    def _partition_bounds(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_partitions)
        for p in range(self.num_partitions):
            lo = min(p * per, total)
            hi = min((p + 1) * per, total)
            yield lo, hi

    def _gen(self, lo: int, hi: int) -> Iterator[ColumnarBatch]:
        i = lo
        while i < hi:
            n = min(self.target_rows, hi - i)
            cap = bucket_capacity(n)
            data = (self.start
                    + (jnp.arange(cap, dtype=jnp.int64) + i) * self.step)
            validity = jnp.arange(cap) < n
            col = ColumnVector(T.INT64, data, validity)
            batch = ColumnarBatch(self._schema, [col], n)
            self.update_output_metrics(batch)
            yield batch
            i += n

    def execute_columnar(self):
        for lo, hi in self._partition_bounds():
            yield from self._gen(lo, hi)

    def execute_partitions(self):
        return [self._gen(lo, hi) for lo, hi in self._partition_bounds()]


class UnionExec(TpuExec):
    """Reference GpuUnionExec: concatenation of children's partitions."""

    def __init__(self, *children: TpuExec):
        super().__init__(*children)
        self._schema = children[0].output_schema()

    def output_schema(self):
        return self._schema

    def execute_columnar(self):
        for c in self.children:
            for b in c.execute_columnar():
                out = ColumnarBatch(self._schema, b.columns, b._rows,
                                    b.checks, b.sparse)
                self.update_output_metrics(out)
                yield out

    def output_partition_count(self) -> int:
        return sum(c.output_partition_count() for c in self.children)

    def execute_partitions(self):
        parts = []
        for c in self.children:
            parts.extend(c.execute_partitions())
        return parts


class CoalescePartitionsExec(UnaryExecBase):
    """Reference GpuCoalesceExec (partition coalesce, not batch coalesce)."""

    def __init__(self, num_partitions: int, child: TpuExec):
        super().__init__(child)
        self.num_partitions = max(1, num_partitions)

    def output_partition_count(self) -> int:
        return min(self.num_partitions, self.child.output_partition_count())

    def output_schema(self):
        return self.child.output_schema()

    def execute_partitions(self):
        kids = self.child.execute_partitions()
        groups: list[list] = [[] for _ in range(
            min(self.num_partitions, max(1, len(kids))))]
        for i, it in enumerate(kids):
            groups[i % len(groups)].append(it)

        def chain(its):
            for it in its:
                yield from it
        return [chain(g) for g in groups]

    def execute_columnar(self):
        for it in self.execute_partitions():
            yield from it
