"""Plugin entry & lifecycle (reference `SQLPlugin.scala:28`,
`Plugin.scala:50-237`).

The reference splits into three hooks that Spark's PluginContainer drives:

* `SQLPlugin` — the `spark.plugins=...` SPI entry returning a driver plugin
  and an executor plugin (`SQLPlugin.scala:28`).
* `RapidsDriverPlugin.init` — fixes up session configs (injects the SQL
  extension, validates the serializer) and returns the `spark.rapids.*`
  conf map that Spark broadcasts to every executor
  (`Plugin.scala:68-112`).
* `RapidsExecutorPlugin.init` — device + memory-pool + semaphore bring-up;
  a failure kills the executor process so the cluster manager replaces it
  (`Plugin.scala:117-146`).

Here the same lifecycle drives the TPU engine: the driver plugin owns conf
fix-up and propagation, the executor plugin owns `ResourceEnv` (TPU
binding, HBM arena accounting, device->host->disk spill chain, task
semaphore).  `activate()` is the local-mode convenience that plays both
roles in-process, the way tests and single-host runs use it.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.memory.env import ResourceEnv

log = logging.getLogger(__name__)

_SQL_EXTENSION = "spark_rapids_tpu.plugin.SQLExecPlugin"
_KRYO_REGISTRATOR = "spark_rapids_tpu.plugin.TpuKryoRegistrator"


class ExecutorInitError(RuntimeError):
    """Raised when executor-side bring-up fails.  The reference calls
    `System.exit(1)` (`Plugin.scala:132-139`) so Spark replaces the
    executor; embedders of this engine should treat this exception as
    process-fatal the same way."""


def fixup_configs(spark_conf: dict) -> dict:
    """Driver-side conf surgery (reference `RapidsPluginUtils.fixupConfigs`
    `Plugin.scala:68-100`): inject the SQL extension that installs the
    columnar override rules, and make the serializer registrator-aware so
    broadcast batches round-trip."""
    out = dict(spark_conf)
    exts = [e for e in str(out.get("spark.sql.extensions", "")).split(",")
            if e]
    if _SQL_EXTENSION not in exts:
        exts.append(_SQL_EXTENSION)
    out["spark.sql.extensions"] = ",".join(exts)

    serializer = out.get("spark.serializer", "")
    if "KryoSerializer" in serializer:
        regs = [r for r in
                str(out.get("spark.kryo.registrator", "")).split(",") if r]
        if _KRYO_REGISTRATOR not in regs:
            regs.append(_KRYO_REGISTRATOR)
        out["spark.kryo.registrator"] = ",".join(regs)
    elif serializer and "JavaSerializer" not in serializer:
        raise ValueError(
            f"spark.serializer={serializer} is not supported "
            "(reference Plugin.scala:90-98: only the Java and Kryo "
            "serializers are)")
    return out


def _rapids_conf_map(spark_conf: dict) -> dict:
    """The subset the driver ships to executors (`Plugin.scala:107-111`
    filters to `spark.rapids.*`)."""
    return {k: v for k, v in spark_conf.items()
            if k.startswith("spark.rapids.")}


class DriverPlugin:
    """Reference `RapidsDriverPlugin` (`Plugin.scala:106-112`)."""

    def __init__(self):
        self.conf: Optional[C.RapidsConf] = None

    def init(self, spark_conf: dict) -> dict:
        fixed = fixup_configs(spark_conf)
        spark_conf.clear()
        spark_conf.update(fixed)
        self.conf = C.RapidsConf(dict(spark_conf))
        return _rapids_conf_map(spark_conf)


class ExecutorPlugin:
    """Reference `RapidsExecutorPlugin` (`Plugin.scala:117-146`)."""

    def __init__(self):
        self.env: Optional[ResourceEnv] = None

    def init(self, extra_conf: dict,
             hbm_total: Optional[int] = None,
             spill_dir: Optional[str] = None) -> None:
        try:
            conf = C.RapidsConf(dict(extra_conf))
            self.env = ResourceEnv.init(conf, hbm_total=hbm_total,
                                        spill_dir=spill_dir)
            TpuKryoRegistrator.register_all()
            # only a successfully validated conf becomes process-active
            C.set_active_conf(conf)
        except Exception as e:  # noqa: BLE001 - init failure is fatal
            log.error("Exception in the executor plugin: %s", e)
            raise ExecutorInitError(str(e)) from e

    def shutdown(self) -> None:
        if self.env is not None:
            ResourceEnv.shutdown()
            self.env = None


class SQLPlugin:
    """`spark.plugins` SPI entry (reference `SQLPlugin.scala:28`)."""

    def driver_plugin(self) -> DriverPlugin:
        return DriverPlugin()

    def executor_plugin(self) -> ExecutorPlugin:
        return ExecutorPlugin()


class SQLExecPlugin:
    """Session-extension hook (reference `Plugin.scala:50-57`): installs
    the columnar override rules (pre = plan rewrite, post = transitions)
    and the AQE query-stage prep rule."""

    @staticmethod
    def apply(extensions: "SparkSessionExtensions") -> None:
        extensions.inject_columnar(lambda conf: _ColumnarOverrideRules(conf))

        def _prep_builder(conf):
            # shim resolution DEFERRED to build time: apply() may run
            # before the session conf is active on this thread, and the
            # builder receives the real per-session conf
            from spark_rapids_tpu.shims import current_shims
            return current_shims(conf).make_query_stage_prep_rule(
                conf, _query_stage_prep)
        extensions.inject_query_stage_prep_rule(_prep_builder)


class SparkSessionExtensions:
    """Minimal extension registry mirroring Spark's
    `SparkSessionExtensions` surface the plugin touches."""

    def __init__(self):
        self.columnar_rules: list[Callable] = []
        self.query_stage_prep_rules: list[Callable] = []

    def inject_columnar(self, builder: Callable) -> None:
        self.columnar_rules.append(builder)

    def inject_query_stage_prep_rule(self, builder: Callable) -> None:
        self.query_stage_prep_rules.append(builder)


class _ColumnarOverrideRules:
    """pre/post columnar transition rules (`Plugin.scala:38-45`)."""

    def __init__(self, conf: C.RapidsConf):
        self.conf = conf

    def pre_columnar_transitions(self, plan):
        from spark_rapids_tpu.plan.overrides import accelerate
        return accelerate(plan, self.conf)

    def post_columnar_transitions(self, plan):
        return plan  # accelerate() already runs the transition pass


def _query_stage_prep(conf: C.RapidsConf):
    from spark_rapids_tpu.plan.aqe import query_stage_prep
    return lambda plan: query_stage_prep(plan, conf)


class TpuKryoRegistrator:
    """Serializer registry for broadcast/shuffle payload classes
    (reference `GpuKryoRegistrator.scala:34`, which registers
    `SerializeConcatHostBuffersDeserializeBatch` and friends with Kryo).
    Here: class -> (serialize, deserialize) over the engine's host-buffer
    wire format (`columnar/serde.py`)."""

    _registry: dict[type, tuple[Callable, Callable]] = {}
    _lock = threading.Lock()

    @classmethod
    def register(cls, klass: type, ser: Callable, deser: Callable) -> None:
        with cls._lock:
            cls._registry[klass] = (ser, deser)

    @classmethod
    def lookup(cls, klass: type) -> Optional[tuple[Callable, Callable]]:
        for base in klass.__mro__:
            hit = cls._registry.get(base)
            if hit is not None:
                return hit
        return None

    @classmethod
    def serialize(cls, obj: Any) -> bytes:
        hit = cls.lookup(type(obj))
        if hit is None:
            raise TypeError(f"no serializer registered for {type(obj)}")
        return hit[0](obj)

    @classmethod
    def deserialize(cls, klass: type, blob: bytes) -> Any:
        hit = cls.lookup(klass)
        if hit is None:
            raise TypeError(f"no serializer registered for {klass}")
        return hit[1](blob)

    @classmethod
    def register_all(cls) -> None:
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.serde import (deserialize_batch,
                                                     serialize_batch)
        cls.register(ColumnarBatch, serialize_batch,
                     lambda blob: deserialize_batch(blob))


# ---------------------------------------------------------------------------
_ACTIVE: dict = {}
_ACTIVE_LOCK = threading.Lock()


def activate(settings: Optional[dict] = None,
             hbm_total: Optional[int] = None,
             spill_dir: Optional[str] = None) -> C.RapidsConf:
    """Local-mode bring-up: run the driver plugin's conf fix-up and the
    executor plugin's resource init in this process (driver and executor
    are the same process in Spark local mode), install the session
    extension, and make the resulting conf active."""
    with _ACTIVE_LOCK:
        spark_conf = dict(settings or {})
        driver = DriverPlugin()
        driver.init(spark_conf)  # fixes up spark_conf in place
        executor = ExecutorPlugin()
        # local mode: driver and executor share the process, so the
        # executor sees the full fixed-up conf (a cluster would ship only
        # the spark.rapids.* map and merge it into executor-side confs)
        executor.init(spark_conf, hbm_total=hbm_total,
                      spill_dir=spill_dir)
        extensions = SparkSessionExtensions()
        SQLExecPlugin.apply(extensions)
        _ACTIVE.update(driver=driver, executor=executor,
                       extensions=extensions)
        return C.get_active_conf()


def deactivate() -> None:
    with _ACTIVE_LOCK:
        executor = _ACTIVE.pop("executor", None)
        if executor is not None:
            executor.shutdown()
        _ACTIVE.clear()
        C.set_active_conf(C.RapidsConf())
