"""Shuffle fault recovery: FetchFailed-driven map recomputation, peer
health, and bounded stage retries.

Reference: `RapidsShuffleIterator` converts transport failures into
Spark `FetchFailedException` precisely so the DAG scheduler can
invalidate the lost map outputs and re-run the producing stage.  This
engine is its own scheduler, so the recovery loop lives here:

  * **ShuffleRecoveryDriver** — wraps the reduce side of a manager-lane
    exchange.  A `FetchFailedError` invalidates the failed peer's
    entries in `MapOutputRegistry` (bumping the shuffle's epoch so
    stale registrations are rejected), recomputes ONLY the lost map
    tasks from the exchange's retained map-side lineage, and retries
    the reduce — bounded by spark.rapids.shuffle.recovery
    .maxStageAttempts, after which it degrades to a descriptive
    `FetchFailedError`.  Never a hang, never a partial result.
  * **PeerHealth** — process-global consecutive-failure blacklisting
    with decay: a flapping peer is routed around (reads pick the
    MapStatus's alternate address, map placement skips it) before we
    waste its full timeout, and rejoins service once the blacklist
    entry decays.

Theseus (PAPERS.md) makes the same argument for distributed GPU query
engines: data movement is its own failure domain and must be
recoverable without restarting the query.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.shuffle.client_server import FetchFailedError
from spark_rapids_tpu.shuffle.manager import (
    MapOutputRegistry, StaleMapStatusError)
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils import profile as P

log = logging.getLogger("spark_rapids_tpu.shuffle.recovery")

#: injectable clock (tests advance it to exercise blacklist decay
#: without sleeping)
_now = time.monotonic


class PeerHealth:
    """Consecutive-failure peer blacklisting with decay (the role of
    Spark's executor blacklist/excludeOnFailure for shuffle fetches).
    Keyed by peer ADDRESS — one executor's loop and TCP lanes are
    tracked independently, but recovery records failures on both."""

    _GLOBAL: Optional["PeerHealth"] = None
    _global_lock = threading.Lock()

    @classmethod
    def get(cls) -> "PeerHealth":
        with cls._global_lock:
            if cls._GLOBAL is None:
                cls._GLOBAL = PeerHealth()
            return cls._GLOBAL

    def __init__(self):
        self._lock = threading.Lock()
        # addr -> [consecutive_failures, blacklisted_since | None]
        self._state: dict[str, list] = {}
        #: monotonic count of not-blacklisted -> blacklisted transitions
        self.blacklist_events = 0

    def _conf(self):
        c = C.get_active_conf()
        return (max(1, int(c[C.SHUFFLE_BLACKLIST_THRESHOLD])),
                float(c[C.SHUFFLE_BLACKLIST_DECAY_S]))

    def record_failure(self, address: str) -> bool:
        """Count a recovery-attributed failure; returns True when this
        failure newly blacklisted the address."""
        threshold, _ = self._conf()
        with self._lock:
            st = self._state.setdefault(address, [0, None])
            st[0] += 1
            if st[1] is None and st[0] >= threshold:
                st[1] = _now()
                self.blacklist_events += 1
                log.warning("shuffle peer %s blacklisted after %d "
                            "consecutive failures", address, st[0])
                P.event(P.EV_PEER_BLACKLISTED, address=address,
                        consecutive_failures=st[0])
                return True
            return False

    def record_success(self, address: str) -> None:
        with self._lock:
            self._state.pop(address, None)

    def is_blacklisted(self, address: str) -> bool:
        _, decay = self._conf()
        with self._lock:
            st = self._state.get(address)
            if st is None or st[1] is None:
                return False
            if _now() - st[1] > decay:
                # decayed: the peer gets a fresh failure budget
                self._state.pop(address, None)
                return False
            return True

    def clear(self) -> None:
        with self._lock:
            self._state.clear()
            self.blacklist_events = 0


class ShuffleRecoveryDriver:
    """Reduce-side retry loop for one shuffle of one exchange.

    `recompute(lost_map_ids, epoch)` is the exchange's retained map-side
    lineage: it re-runs exactly those child partitions, re-splits them,
    and commits their map outputs at `epoch` (a commit racing a further
    invalidation is rejected as stale and the next round re-derives
    what is missing)."""

    def __init__(self, manager, shuffle_id: int,
                 recompute: Callable[[list[int], int], None],
                 conf: Optional[C.RapidsConf] = None,
                 metrics: Optional[M.MetricSet] = None,
                 read_timeout: float = 30.0):
        self.manager = manager
        self.shuffle_id = shuffle_id
        self.recompute = recompute
        self.conf = conf or C.get_active_conf()
        self.metrics = metrics if metrics is not None else M.MetricSet()
        self.read_timeout = read_timeout
        self.max_attempts = max(
            1, int(self.conf[C.SHUFFLE_RECOVERY_MAX_STAGE_ATTEMPTS]))
        self.health = PeerHealth.get()
        # one recovery at a time per shuffle: concurrent reduce readers
        # (prefetch producers) funnel their FetchFailures through here
        self._lock = threading.Lock()

    def read_partition(self, p: int) -> list:
        """Fetch one reduce partition, recovering from peer loss.
        Returns the partition's batches as a LIST: a retried attempt
        restarts the partition from scratch, so nothing may be yielded
        downstream until an attempt completes (no double counting)."""
        attempt = 1
        while True:
            epoch0 = MapOutputRegistry.epoch(self.shuffle_id)
            try:
                items = list(self.manager.get_reader(
                    self.shuffle_id, p, timeout=self.read_timeout,
                    with_map_ids=True, metrics=self.metrics))
                # deterministic map order: a recompute relocates map
                # outputs between executors, which would otherwise
                # reorder batches (local-first) vs the failure-free run
                items.sort(key=lambda t: t[0])
                return [b for _, b in items]
            except FetchFailedError as e:
                self.metrics.add(M.NUM_FETCH_FAILURES, 1)
                P.event(P.EV_FETCH_FAILURE, shuffle_id=self.shuffle_id,
                        partition=p, address=e.address,
                        attempt=attempt, error=str(e)[:200])
                if attempt >= self.max_attempts:
                    P.event(P.EV_RECOVERY_EXHAUSTED,
                            shuffle_id=self.shuffle_id, partition=p,
                            attempts=attempt)
                    raise FetchFailedError(
                        e.address, e.block,
                        f"shuffle {self.shuffle_id} partition {p} "
                        f"still failing after {attempt} stage "
                        f"attempt(s) (spark.rapids.shuffle.recovery."
                        f"maxStageAttempts={self.max_attempts}): "
                        f"{e}") from e
                attempt += 1
                self._recover(e, epoch0)

    def _recover(self, e: FetchFailedError, epoch_seen: int) -> None:
        with self._lock:
            t0 = time.perf_counter_ns()
            try:
                if MapOutputRegistry.epoch(self.shuffle_id) != epoch_seen \
                        and not MapOutputRegistry.missing_maps(
                            self.shuffle_id):
                    # another reader already recovered this loss while
                    # we waited on the lock: just retry the read
                    return
                lost = MapOutputRegistry.invalidate_address(
                    self.shuffle_id, e.address)
                if not lost and not MapOutputRegistry.missing_maps(
                        self.shuffle_id):
                    # unattributable failure (no MapStatus advertises
                    # that address): conservative whole-stage
                    # invalidation of every remote peer
                    lost = MapOutputRegistry.invalidate_others(
                        self.shuffle_id, self.manager.executor_id)
                by_exec: dict[str, set] = {}
                for st in lost.values():
                    by_exec.setdefault(st.executor_id, set()).update(
                        st.addresses())
                for eid, addrs in by_exec.items():
                    flags = [self.health.record_failure(a)
                             for a in sorted(addrs)]
                    if any(flags):
                        self.metrics.add(M.NUM_PEERS_BLACKLISTED, 1)
                # replica promotion first (replication.factor >= 2):
                # a lost map output whose serialized copy lives on a
                # surviving executor is re-registered pointing THERE —
                # no recompute, no device work.  Lineage recompute
                # remains the fallback for un-replicated outputs.
                promoted = self._promote_replicas(lost, set(by_exec))
                todo = sorted((set(lost) - promoted) | set(
                    MapOutputRegistry.missing_maps(self.shuffle_id)))
                if todo:
                    epoch = MapOutputRegistry.epoch(self.shuffle_id)
                    log.warning(
                        "shuffle %d recovery: recomputing map tasks %s "
                        "at epoch %d after %s", self.shuffle_id, todo,
                        epoch, e)
                    P.event(P.EV_MAP_RECOMPUTE,
                            shuffle_id=self.shuffle_id,
                            map_ids=list(todo), epoch=epoch,
                            address=e.address)
                    try:
                        with P.span(f"map-recompute:s{self.shuffle_id}",
                                    cat=P.CAT_SHUFFLE) \
                                if P.tracer() is not None \
                                else P._NULL_SPAN:
                            self.recompute(todo, epoch)
                    except StaleMapStatusError as stale:
                        # a racing invalidation superseded this
                        # recompute; the next attempt re-derives the
                        # missing set at the fresh epoch
                        log.warning("shuffle %d recompute superseded: "
                                    "%s", self.shuffle_id, stale)
                    self.metrics.add(M.NUM_MAP_RECOMPUTES, len(todo))
                self.metrics.add(M.NUM_STAGE_RETRIES, 1)
                P.event(P.EV_STAGE_RETRY, shuffle_id=self.shuffle_id,
                        recomputed=len(todo))
            finally:
                self.metrics.add(M.RECOVERY_TIME,
                                 time.perf_counter_ns() - t0)

    def _promote_replicas(self, lost: dict, dead_execs: set) -> set:
        """Re-register each lost map output whose replica survives on a
        live executor; returns the promoted map ids.  The promoted
        MapStatus keeps the primary's partition sizes (zero/nonzero
        routing is what readers consult) and the remaining replicas."""
        from spark_rapids_tpu.shuffle.manager import MapStatus
        promoted: set = set()
        transport = self.manager.transport
        for map_id, st in sorted(lost.items()):
            pick = None
            for eid, addr, tcp in st.replicas:
                if eid in dead_execs:
                    continue
                cands = [a for a in (addr, tcp)
                         if a and transport.can_reach(a)
                         and not self.health.is_blacklisted(a)]
                if cands:
                    pick = (eid, addr, tcp)
                    break
            if pick is None:
                continue
            eid, addr, tcp = pick
            survivors = [r for r in st.replicas
                         if r[0] != eid and r[0] not in dead_execs]
            new_st = MapStatus(eid, addr, list(st.partition_sizes),
                               tcp_address=tcp, replicas=survivors)
            try:
                MapOutputRegistry.register(self.shuffle_id, map_id,
                                           new_st)
            except StaleMapStatusError:
                continue  # a racing invalidation superseded us
            promoted.add(map_id)
            self.metrics.add(M.NUM_REPLICA_PROMOTIONS, 1)
            log.warning("shuffle %d recovery: promoted replica on %s "
                        "for map %d (no recompute)", self.shuffle_id,
                        eid, map_id)
            P.event(P.EV_REPLICA_PROMOTED, shuffle_id=self.shuffle_id,
                    map_id=map_id, replica_executor=eid)
        return promoted
