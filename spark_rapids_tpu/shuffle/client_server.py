"""Shuffle client/server protocol state machines (transport-agnostic).

Reference: `RapidsShuffleClient.scala` (metadata request/response,
transfer-request issuance, `BufferReceiveState` chunk assembly, retry) and
`RapidsShuffleServer.scala` (`handleMetadataRequest:284`,
`BufferSendState:380` — acquire from any tier, stage through send bounce
buffers, throttled).  These classes hold no sockets: the Connection /
request-handler SPI injects the wire, so protocol behavior is unit-tested
with mocked transports exactly like the reference's `tests/.../shuffle`
suites (SURVEY.md §4 tier 2).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional, Sequence

from spark_rapids_tpu import config as C
from spark_rapids_tpu.memory.buffer import BufferId
from spark_rapids_tpu.shuffle.catalog import (
    ShuffleBufferCatalog, ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.transport import (
    BlockIdMsg, Connection, InflightLimiter, MsgKind, ShuffleTransport,
    TableMetaMsg, Transaction, TransactionStatus, meta_request,
    parse_meta_response)

log = logging.getLogger("spark_rapids_tpu.shuffle")


class FetchFailedError(Exception):
    """Maps to Spark's FetchFailedException semantics: the recovery
    driver (shuffle/recovery.py) invalidates the failed peer's map
    outputs and regenerates them (reference RapidsShuffleIterator error
    path).  `address` is the REAL peer that failed and `block` (when
    known) pins the shuffle/map ids, so recovery invalidates exactly
    the right executor's outputs."""

    def __init__(self, address: str, block: Optional[BlockIdMsg],
                 message: str):
        super().__init__(f"fetch failed from {address} ({block}): {message}")
        self.address = address
        self.block = block

    @property
    def shuffle_id(self) -> Optional[int]:
        return self.block.shuffle_id if self.block is not None else None

    @property
    def map_id(self) -> Optional[int]:
        return self.block.map_id if self.block is not None else None


def _cancellable_backoff_sleep(seconds: float) -> None:
    """Default retry sleep: bounded-poll + cancel-token check, so a
    backoff never outlives a watchdog-cancelled query."""
    from spark_rapids_tpu.utils import watchdog as W
    W.cancellable_sleep(seconds)


#: injectable so soak tests can capture/skip the retry sleeps
_backoff_sleep = _cancellable_backoff_sleep

#: in-flight fetch registry, surfaced by the watchdog's diagnostic dump
#: so a timed-out query names the peer + blocks it was waiting on
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT: dict[int, dict] = {}
_INFLIGHT_IDS = iter(range(1, 1 << 62))


def inflight_fetches() -> list[dict]:
    """Snapshot of fetches currently in flight: address, block ids,
    attempt, and seconds in flight."""
    now = time.monotonic()
    with _INFLIGHT_LOCK:
        snaps = [dict(v) for v in _INFLIGHT.values()]
    for f in snaps:
        f["in_flight_s"] = round(now - f.pop("_t0"), 2)
    return snaps


def inflight_count() -> int:
    """Fetches in flight right now — the cheap telemetry-gauge /
    sampler probe (no snapshot copies)."""
    with _INFLIGHT_LOCK:
        return len(_INFLIGHT)


# ---------------------------------------------------------------------------
# fetch-latency tracking for hedged reads: completed fetch durations
# feed the hedge trigger's delay quantile, so "straggling" is judged
# against what fetches in THIS process actually cost, with
# shuffle.hedge.delayMs as the floor and the cold-start fallback
_LATENCY_LOCK = threading.Lock()
_LATENCY_SAMPLES: "list[float]" = []
_LATENCY_MAX_SAMPLES = 256
_HEDGE_MIN_SAMPLES = 8


def note_fetch_duration(seconds: float) -> None:
    with _LATENCY_LOCK:
        _LATENCY_SAMPLES.append(float(seconds))
        if len(_LATENCY_SAMPLES) > _LATENCY_MAX_SAMPLES:
            del _LATENCY_SAMPLES[:len(_LATENCY_SAMPLES)
                                 - _LATENCY_MAX_SAMPLES]


def reset_fetch_latency() -> None:
    with _LATENCY_LOCK:
        _LATENCY_SAMPLES.clear()


def hedge_delay_s(conf: Optional[C.RapidsConf] = None) -> float:
    """How long a fetch may be outstanding before a hedge fires:
    max(hedge.delayMs, the hedge.quantile of recent fetch durations)
    once enough samples exist, else the delayMs floor alone."""
    conf = conf or C.get_active_conf()
    floor = float(conf[C.SHUFFLE_HEDGE_DELAY_MS]) / 1e3
    q = min(1.0, max(0.0, float(conf[C.SHUFFLE_HEDGE_QUANTILE])))
    with _LATENCY_LOCK:
        if len(_LATENCY_SAMPLES) < _HEDGE_MIN_SAMPLES:
            return floor
        ordered = sorted(_LATENCY_SAMPLES)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return max(floor, ordered[idx])


class ShuffleReceiveHandler:
    """Callback surface the iterator implements (reference
    RapidsShuffleFetchHandler): batchReceived / transferError."""

    def start(self, expected_batches: int) -> None:
        ...

    def batch_received(self, bid: BufferId) -> None:
        ...

    def buffer_received(self, wire_bytes: int, raw_bytes: int) -> None:
        """One assembled wire payload landed: its on-the-wire
        (compressed) and uncompressed sizes, so readers can charge
        per-exchange compression metrics."""
        ...

    def corruption_detected(self) -> None:
        """A DATA frame failed its CRC and the transfer will retry —
        surfaced so the exchange can meter wire damage
        (numWireCorruptions) instead of it hiding inside the retry
        path."""
        ...

    def transfer_error(self, message: str) -> None:
        ...


class BufferReceiveState:
    """Assembles DATA chunks into whole serialized batches, releasing the
    inflight budget as each buffer lands in the host store (reference
    BufferReceiveState RapidsShuffleClient.scala:108)."""

    def __init__(self, metas: Sequence[TableMetaMsg],
                 received_catalog: ShuffleReceivedBufferCatalog,
                 host_store, task_attempt_id: int,
                 limiter: InflightLimiter,
                 handler: ShuffleReceiveHandler,
                 progress: Optional[Callable[[], None]] = None):
        self.metas = {m.table_id: m for m in metas}
        self.received_catalog = received_catalog
        self.host_store = host_store
        self.task_attempt_id = task_attempt_id
        self.limiter = limiter
        self.handler = handler
        self.progress = progress
        self.completed: set[int] = set()
        self._chunks: dict[int, list[bytes]] = {}
        self._lock = threading.Lock()

    def on_chunk(self, table_id: int, seq: int, chunk: bytes,
                 is_last: bool, codec_id: int = -1,
                 raw_len: int = 0) -> None:
        if self.progress is not None:
            self.progress()  # chunk landed: the fetch is alive
        with self._lock:
            parts = self._chunks.setdefault(table_id, [])
            assert seq == len(parts), (
                f"out-of-order chunk {seq} for table {table_id}")
            parts.append(chunk)
            if not is_last:
                return
            blob = b"".join(self._chunks.pop(table_id))
            self.completed.add(table_id)
        wire_len = len(blob)
        if codec_id != -1:
            # wire payload was codec-compressed by the server
            # (reference GpuCompressedColumnVector decompress-on-receive)
            from spark_rapids_tpu.shuffle.compression import get_codec
            blob = get_codec(codec_id).decompress(blob, raw_len)
        # movement ledger, receive side: mirrors the sender's record so
        # in-process conservation (bytes served == bytes assembled) is
        # checkable; 'recv' sites are excluded from edge totals
        from spark_rapids_tpu.utils import movement as MV
        MV.record(MV.EDGE_WIRE, wire_len, site="recv",
                  raw_bytes=len(blob))
        self.handler.buffer_received(wire_len, len(blob))
        meta_msg = self.metas[table_id]
        bid = BufferId(self.received_catalog.new_buffer_id().table_id,
                       meta_msg.shuffle_id, meta_msg.map_id,
                       meta_msg.partition)
        # provenance: received buffers land in the host tier under a
        # reduce-side site, distinct from the sender's map buffers
        from spark_rapids_tpu.utils import residency as RES
        with RES.site_scope("shuffle-recv"):
            self.host_store.add_blob(bid, blob, meta_msg.table_meta())
        self.received_catalog.add_received(self.task_attempt_id, bid)
        self.limiter.release(meta_msg.size_bytes)  # mirrors the acquire
        self.handler.batch_received(bid)

    def drop_partial(self, table_id: int) -> None:
        with self._lock:
            self._chunks.pop(table_id, None)


class ShuffleClient:
    """Per-peer fetch driver (reference RapidsShuffleClient).  Two-phase:
    metadata round-trip, then transfer with bounded inflight bytes and
    bounded retries on transient transport errors (FetchRetry:406),
    spaced by exponential backoff with jitter so a struggling peer is
    not hammered with immediate reconnects."""

    #: legacy default; the effective budget comes from
    #: spark.rapids.shuffle.fetch.maxRetries
    MAX_RETRIES = 3

    def __init__(self, connection: Connection, transport: ShuffleTransport,
                 received_catalog: ShuffleReceivedBufferCatalog,
                 host_store, address: str = "peer",
                 conf: Optional[C.RapidsConf] = None):
        self.connection = connection
        self.transport = transport
        self.received_catalog = received_catalog
        self.host_store = host_store
        self.address = address
        conf = conf or C.get_active_conf()
        self.conf = conf
        self.max_retries = int(conf[C.SHUFFLE_FETCH_MAX_RETRIES])
        self._backoff_base = \
            float(conf[C.SHUFFLE_FETCH_BACKOFF_BASE_MS]) / 1000.0
        self._backoff_cap = \
            float(conf[C.SHUFFLE_FETCH_BACKOFF_CAP_MS]) / 1000.0
        seed = int(conf[C.SHUFFLE_FAULT_SEED])
        # seeded jitter -> deterministic retry schedules in soak tests
        self._rng = random.Random(seed if seed else None)

    def _backoff(self, attempt: int) -> float:
        delay = min(self._backoff_cap,
                    self._backoff_base * (2 ** max(0, attempt - 1)))
        delay *= 0.5 + 0.5 * self._rng.random()
        if delay > 0:
            _backoff_sleep(delay)
        return delay

    def fetch_blocks(self, blocks: Sequence[BlockIdMsg],
                     task_attempt_id: int,
                     handler: ShuffleReceiveHandler) -> list[TableMetaMsg]:
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        fid = next(_INFLIGHT_IDS)
        with _INFLIGHT_LOCK:
            _INFLIGHT[fid] = {
                "address": self.address, "attempt": 0,
                "blocks": [str(b) for b in blocks[:8]],
                "_t0": time.monotonic()}
        with W.heartbeat(f"shuffle-fetch:{self.address}",
                         kind="task", conf=self.conf) as hb, \
                P.span(f"shuffle-fetch:{self.address}",
                       cat=P.CAT_SHUFFLE):
            t0 = time.monotonic()
            try:
                out = self._fetch_blocks(blocks, task_attempt_id,
                                         handler, hb, fid)
                # completed fetches feed the hedge trigger's latency
                # quantile (hedge_delay_s)
                note_fetch_duration(time.monotonic() - t0)
                return out
            finally:
                with _INFLIGHT_LOCK:
                    _INFLIGHT.pop(fid, None)

    def _fetch_blocks(self, blocks, task_attempt_id, handler, hb, fid
                      ) -> list[TableMetaMsg]:
        from spark_rapids_tpu.utils import watchdog as W
        kind, payload = self.connection.request(meta_request(blocks))
        if kind != MsgKind.METADATA_RESPONSE:
            raise FetchFailedError(self.address, blocks[0] if blocks else
                                   None, f"unexpected response {kind}")
        metas = parse_meta_response(payload)
        real = [m for m in metas if not m.is_degenerate]
        degenerate = [m for m in metas if m.is_degenerate]
        handler.start(len(metas))
        # degenerate (rows-only) batches need no data phase; they become
        # metadata-only buffers on the receive side too (a serialized b""
        # blob would fail deserialization)
        from spark_rapids_tpu.memory.buffer import DegenerateBuffer
        for m in degenerate:
            bid = BufferId(self.received_catalog.new_buffer_id().table_id,
                           m.shuffle_id, m.map_id, m.partition)
            self.received_catalog.catalog.register(
                DegenerateBuffer(bid, m.table_meta()))
            self.received_catalog.add_received(task_attempt_id, bid)
            handler.batch_received(bid)
        if not real:
            return metas
        state = BufferReceiveState(real, self.received_catalog,
                                   self.host_store, task_attempt_id,
                                   self.transport.receive_limiter, handler,
                                   progress=hb.beat)
        pending = list(real)
        attempt = 0
        while pending:
            # round boundary = cancellation point (a cancelled query
            # must not issue fresh transfer requests)
            W.check_cancelled()
            with _INFLIGHT_LOCK:
                if fid in _INFLIGHT:
                    _INFLIGHT[fid]["attempt"] = attempt
            batch_ids = []
            budget_taken = []
            for m in pending:
                if not self.transport.receive_limiter.acquire(
                        m.size_bytes, timeout=None if not batch_ids
                        else 0.0):
                    break  # send what we have; rest in the next round
                batch_ids.append(m.table_id)
                budget_taken.append(m)
            txn = self.connection.fetch(batch_ids, state.on_chunk)
            if txn.status != TransactionStatus.SUCCESS:
                if txn.corrupt:
                    # detected wire damage is first-class: metered on
                    # the exchange (numWireCorruptions) and correlated
                    # in the event log, not buried in the retry path
                    handler.corruption_detected()
                    from spark_rapids_tpu.utils import profile as _P
                    _P.event(_P.EV_WIRE_CORRUPTION, address=self.address,
                             error=str(txn.error)[:200])
                # return the budget of buffers that did not complete
                for m in budget_taken:
                    if m.table_id not in state.completed:
                        state.drop_partial(m.table_id)
                        self.transport.receive_limiter.release(m.size_bytes)
                pending = [m for m in pending
                           if m.table_id not in state.completed]
                attempt += 1
                from spark_rapids_tpu.utils import profile as P
                if attempt > self.max_retries:
                    handler.transfer_error(txn.error or "transfer failed")
                    P.event(P.EV_FETCH_FAILURE, address=self.address,
                            attempts=attempt,
                            error=str(txn.error)[:200])
                    raise FetchFailedError(
                        self.address,
                        blocks[0] if blocks else None,
                        f"transfer failed after {attempt} attempts: "
                        f"{txn.error}")
                log.warning("shuffle fetch retry %d from %s: %s", attempt,
                            self.address, txn.error)
                P.event(P.EV_FETCH_RETRY, address=self.address,
                        attempt=attempt, error=str(txn.error)[:200])
                self._backoff(attempt)
                # a mid-stream abort leaves the socket dead on the
                # server side: reconnect before retrying (the reference
                # re-registers the UCX endpoint on a failed Transaction)
                try:
                    fresh = self.transport.make_client(self.address)
                except Exception:
                    fresh = None
                if fresh is not None:
                    try:
                        self.connection.close()
                    except Exception:
                        pass
                    self.connection = fresh
                continue
            pending = [m for m in pending
                       if m.table_id not in state.completed]
        return metas


class ShuffleServer:
    """Serves metadata + data for locally-stored shuffle buffers
    (reference RapidsShuffleServer).  `BufferSendState` slices each
    serialized buffer into bounce-buffer-sized chunks; buffers are
    acquired from whatever tier they live in (device or spilled)."""

    def __init__(self, shuffle_catalog: ShuffleBufferCatalog,
                 transport: ShuffleTransport, codec=None,
                 executor_id: Optional[str] = None):
        self.shuffle_catalog = shuffle_catalog
        self.transport = transport
        # payload codec for the wire (reference TableCompressionCodec;
        # conf spark.rapids.shuffle.compression.codec)
        self.codec = codec
        #: owning executor, so the seeded slow-peer injector can
        #: target ONE server (faultInjection.slowVictim)
        self.executor_id = executor_id

    def handle_metadata_request(self, blocks: Sequence[BlockIdMsg]
                                ) -> list[TableMetaMsg]:
        out = []
        for b in blocks:
            bids = self.shuffle_catalog.blocks_for_partition(
                b.shuffle_id, b.partition, map_ids=[b.map_id])
            for bid in bids:
                out.append(TableMetaMsg.of(
                    bid, self.shuffle_catalog.meta_for(bid)))
        return out

    def acquire_buffer_bytes(self, table_id: int) -> bytes:
        """Serialize a catalog buffer for the wire, whichever tier holds
        it (reference BufferSendState acquires from catalog :380)."""
        catalog = self.shuffle_catalog.catalog
        bid = self.shuffle_catalog.lookup_table(table_id)
        with catalog.acquired(bid) as buf:
            return buf.get_host_bytes()

    def send_state(self, table_ids: Sequence[int],
                   emit: Callable[[int, int, bytes, bool], None],
                   wire: bool = True) -> Transaction:
        """Stream requested buffers as bounce-buffer-sized chunks.  With a
        synchronous `emit` the chunks are zero-copy slices; the send
        bounce pool (reference BufferSendState) only sizes the chunks —
        an async transport would stage through `transport.send_bounce`
        to bound its in-flight copies.

        `wire=False` (loopback fetches) skips the payload codec: the
        bytes never leave the process, so compressing them would be pure
        CPU waste."""
        from spark_rapids_tpu.utils import watchdog as W
        total = 0
        chunk_size = self.transport.send_bounce.buffer_size
        codec = self.codec if wire else None
        # server handlers run on transport threads with no session
        # conf installed; the transport's construction-time conf
        # carries the watchdog/injection settings
        wconf = getattr(self.transport, "conf", None)
        from spark_rapids_tpu.utils import profile as P
        try:
            # server handlers run on transport threads with no captured
            # span context: the span parents under the query root, which
            # still names the thread + timeline in the Chrome trace
            with W.heartbeat("shuffle-server", kind="task",
                             conf=wconf) as hb, \
                    P.span("shuffle-server", cat=P.CAT_SHUFFLE):
                from spark_rapids_tpu.shuffle.compression import (
                    note_compression)
                from spark_rapids_tpu.utils import movement as MV
                wire_site = "send:dcn" if wire else "send:loop"
                for tid in table_ids:
                    t0 = time.perf_counter_ns()
                    blob = self.acquire_buffer_bytes(tid)
                    raw_len = len(blob)
                    codec_id = -1
                    if codec is not None:
                        blob = codec.compress(blob)
                        codec_id = codec.codec_id
                        note_compression(codec.name, raw_len, len(blob))
                    n = len(blob)
                    nchunks = max(1, -(-n // chunk_size))
                    for i in range(nchunks):
                        # a handler wedged between chunks is the
                        # server-stall failure mode: the heartbeat
                        # names it and the hang injector fakes it
                        W.maybe_hang("shuffle-server", conf=wconf)
                        chunk = blob[i * chunk_size:
                                     (i + 1) * chunk_size]
                        emit(tid, i, chunk, i == nchunks - 1,
                             codec_id, raw_len)
                        hb.beat()
                        total += len(chunk)
                    # movement ledger: one wire record per served
                    # buffer — compressed payload + uncompressed size,
                    # timed over acquire+compress+emit.  Loopback
                    # fetches run on the CLIENT's thread, so the
                    # record lands in the fetching query's ledger;
                    # TCP handlers fall back to the newest tracer.
                    MV.record(MV.EDGE_WIRE, n, site=wire_site,
                              raw_bytes=raw_len,
                              dur_ns=time.perf_counter_ns() - t0,
                              codec=codec.name if codec else "none")
                    # seeded slow-peer injection: a degraded server
                    # serves each buffer slowFactor x slower.  After
                    # the buffer so a hedged winner can land staged
                    # partial results; cancellable, so a losing hedge
                    # parked here wakes on its AttemptToken.
                    W.maybe_slow("shuffle-server", conf=wconf,
                                 executor_id=self.executor_id)
        except Exception as e:  # noqa: BLE001 — surface as transaction
            return Transaction(TransactionStatus.ERROR, str(e), total)
        return Transaction(TransactionStatus.SUCCESS,
                           bytes_transferred=total)
