"""ICI/DCN shuffle transport (the default `ShuffleTransport` impl; conf
`spark.rapids.shuffle.transport.class`).

Reference parallel: `shuffle-plugin/.../ucx/UCXShuffleTransport.scala` +
`UCX.scala` — UCX tag-matching with a TCP management handshake and a
dedicated progress thread.  TPU redesign, two lanes:

  * **ICI lane (intra-slice)**: executors on one pod slice share the XLA
    runtime, so batch exchange is the SPMD all-to-all in
    `parallel/collective_exchange.py` — it never goes through this SPI.
    Within a host (and in local mode / tests) peers are reached by direct
    loopback: the "connection" invokes the peer server's handlers
    in-process, zero-copy of the control plane.
  * **DCN lane (cross-host)**: a TCP data-plane socket per peer pair, with
    length-prefixed control frames and bounce-buffer-sized DATA frames —
    the role UCX tag messages play in the reference.  Each server runs an
    accept loop + per-connection handler threads (the progress-thread
    analog).

Peer addressing: `loop://<executor_id>` for in-process peers,
`tcp://host:port` for remote ones — the address travels in MapStatus like
the reference's UCX port in `BlockManagerId.topologyInfo`
(`RapidsShuffleInternalManager.scala:170-186`).
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Optional, Sequence

from spark_rapids_tpu import config as C
from spark_rapids_tpu.shuffle.transport import (
    Connection, MsgKind, ShuffleTransport, Transaction, TransactionStatus,
    WireCorruption, decode_frame, encode_data, meta_response,
    transfer_request)

_LOOP_REGISTRY_LOCK = threading.Lock()
_LOOP_REGISTRY: dict[str, "object"] = {}  # executor_id -> request handler


class LoopbackConnection(Connection):
    """In-process peer: drives the server state machine directly."""

    def __init__(self, handler, transport: ShuffleTransport,
                 eid: Optional[str] = None):
        self.server = handler
        self.transport = transport
        self.eid = eid

    def _check_alive(self) -> None:
        # a peer_kill-ed executor disappears from the loop registry;
        # the held handler object must not keep serving it (the wire
        # analog: the socket is dead even if the process isn't)
        if self.eid is None:
            return
        with _LOOP_REGISTRY_LOCK:
            alive = _LOOP_REGISTRY.get(self.eid) is self.server
        if not alive:
            raise ConnectionError(f"loopback peer {self.eid} is gone")

    def request(self, frame: bytes):
        self._check_alive()
        kind, payload = decode_frame(frame[4:])
        if kind == MsgKind.METADATA_REQUEST:
            from spark_rapids_tpu.shuffle.transport import BlockIdMsg
            blocks = [BlockIdMsg(*b) for b in payload["blocks"]]
            metas = self.server.handle_metadata_request(blocks)
            resp = meta_response(metas)
            return decode_frame(resp[4:])
        raise ValueError(f"unexpected request {kind}")

    def fetch(self, table_ids: Sequence[int],
              on_chunk: Callable[[int, int, bytes, bool], None]
              ) -> Transaction:
        try:
            self._check_alive()
        except ConnectionError as e:
            return Transaction(TransactionStatus.ERROR, str(e))
        faults = getattr(self.server.transport, "faults", None)
        if faults is not None and faults.kill_after_frames > 0:
            server_transport = self.server.transport

            def counted(tid, seq, chunk, is_last, codec_id=-1,
                        raw_len=0):
                if faults.note_frame():
                    # the serving executor dies mid-stream: both its
                    # lanes go dark, not just this transfer
                    server_transport.kill_self()
                    raise _InjectedDrop()
                on_chunk(tid, seq, chunk, is_last, codec_id, raw_len)

            return self.server.send_state(table_ids, counted, wire=False)
        # in-process fetch: bytes never hit a wire, skip the codec
        return self.server.send_state(table_ids, on_chunk, wire=False)


class FaultInjector:
    """Deterministic wire-fault injection for soak tests (the reference
    builds UCX with --enable-fault-injection for the same purpose):
    `drop` aborts the transfer mid-stream (the server stops sending and
    the transaction fails, so the client must drop partials, reconnect
    and retry), `corrupt` flips a byte in a DATA chunk (the frame crc32
    must catch it), `peer_kill` takes the whole peer down after it has
    served kill_after_frames DATA frames — sockets close mid-stream,
    the accept loop stops, the loopback registration disappears — so
    retries CANNOT succeed and the stage-recovery layer must recompute
    the lost map outputs. Rates come from the faultInjection.* confs;
    rate 0 (the default) injects nothing."""

    def __init__(self, drop_rate: float, corrupt_rate: float,
                 seed: int, kill_after_frames: int = 0):
        import random
        self.drop_rate = float(drop_rate)
        self.corrupt_rate = float(corrupt_rate)
        self.kill_after_frames = int(kill_after_frames)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected_drops = 0
        self.injected_corruptions = 0
        self.frames_served = 0
        self.peer_killed = False

    @property
    def active(self) -> bool:
        return self.drop_rate > 0 or self.corrupt_rate > 0 \
            or self.kill_after_frames > 0

    def note_frame(self) -> bool:
        """Count one served DATA frame; True once the peer_kill budget
        is exhausted (and forever after — a dead peer stays dead)."""
        with self._lock:
            if self.kill_after_frames <= 0:
                return False
            if self.peer_killed:
                return True
            self.frames_served += 1
            if self.frames_served >= self.kill_after_frames:
                self.peer_killed = True
                return True
        return False

    def maybe_drop(self) -> bool:
        with self._lock:
            if self._rng.random() < self.drop_rate:
                self.injected_drops += 1
                return True
        return False

    def maybe_corrupt_frame(self, frame: bytes,
                            payload_off: int) -> bytes:
        """Flip a byte in the PAYLOAD of an already-encoded frame —
        after the header's crc32 was computed, like real wire damage
        (corrupting before encoding would be re-checksummed and sail
        through undetected)."""
        with self._lock:
            if len(frame) > payload_off and \
                    self._rng.random() < self.corrupt_rate:
                self.injected_corruptions += 1
                i = self._rng.randrange(payload_off, len(frame))
                return frame[:i] + bytes([frame[i] ^ 0xFF]) \
                    + frame[i + 1:]
        return frame


class _InjectedDrop(Exception):
    pass


class TcpServer:
    """Accept loop + per-connection handler threads (the reference's UCX
    progress thread / management-port pair collapsed into one socket)."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 faults: Optional[FaultInjector] = None,
                 on_kill: Optional[Callable[[], None]] = None):
        self.faults = faults
        self.on_kill = on_kill
        self.server = server
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address = f"tcp://{host}:{self._sock.getsockname()[1]}"
        self._closing = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="tpu-shuffle-server",
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        from spark_rapids_tpu.shuffle.transport import BlockIdMsg
        try:
            while True:
                frame = _recv_frame(conn,
                                    alive=lambda: not self._closing)
                if frame is None:
                    return
                # actively serving: restore blocking I/O so a large
                # response send never trips the idle-poll timeout
                conn.settimeout(None)
                if self.faults is not None and self.faults.peer_killed:
                    # a killed peer stops answering — no polite error
                    # frame, the client sees a dead wire
                    return
                kind, payload = decode_frame(frame)
                if kind == MsgKind.METADATA_REQUEST:
                    blocks = [BlockIdMsg(*b) for b in payload["blocks"]]
                    metas = self.server.handle_metadata_request(blocks)
                    _send_all(conn, meta_response(metas))
                elif kind == MsgKind.TRANSFER_REQUEST:
                    faults = self.faults

                    def emit(tid, seq, chunk, is_last, codec_id=-1,
                             raw_len=0):
                        frame = encode_data(
                            tid, (seq << 1) | int(is_last), chunk,
                            codec_id, raw_len)
                        if faults is not None and faults.active:
                            if faults.note_frame():
                                # peer_kill: the whole executor goes
                                # dark mid-stream, permanently
                                conn.close()
                                if self.on_kill is not None:
                                    self.on_kill()
                                raise _InjectedDrop()
                            if faults.maybe_drop():
                                # simulated connection loss: kill the
                                # socket so the peer sees a dead wire,
                                # not a polite error frame
                                conn.close()
                                raise _InjectedDrop()
                            # frame payload starts after the 4-byte
                            # length prefix + 26-byte DATA header
                            frame = faults.maybe_corrupt_frame(frame, 30)
                        _send_all(conn, frame)
                    txn = self.server.send_state(payload["table_ids"], emit)
                    _send_all(conn, _txn_frame(txn))
                else:
                    return
        except OSError:
            return
        finally:
            conn.close()

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass


def _txn_frame(txn: Transaction) -> bytes:
    from spark_rapids_tpu.shuffle.transport import encode_control
    return encode_control(MsgKind.TRANSFER_RESPONSE, {
        "status": txn.status.value, "error": txn.error,
        "bytes": txn.bytes_transferred})


def _send_all(conn: socket.socket, data: bytes) -> None:
    conn.sendall(data)


#: idle-poll slice for server-side reads: a handler thread parked on an
#: idle connection wakes at this cadence to notice server close instead
#: of blocking on recv forever (the bounded-poll wait discipline)
_SERVE_POLL_S = 0.25


def _recv_frame(conn: socket.socket, alive=None) -> Optional[bytes]:
    hdr = _recv_exact(conn, 4, alive)
    if hdr is None:
        return None
    (length,) = struct.unpack("<I", hdr)
    return _recv_exact(conn, length, alive)


def _recv_exact(conn: socket.socket, n: int,
                alive=None) -> Optional[bytes]:
    """Read exactly `n` bytes.  With `alive` the read is a bounded
    poll: the socket gets a short timeout and each timeout slice
    re-checks alive(), so a closing server reclaims handler threads
    instead of leaking them parked on idle connections."""
    if alive is not None:
        conn.settimeout(_SERVE_POLL_S)
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except socket.timeout:
            if alive is not None and not alive():
                return None
            continue
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class TcpConnection(Connection):
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        #: overall per-read inactivity budget; the socket itself polls
        #: in short slices so a watchdog cancellation interrupts a
        #: client parked on a dead wire instead of waiting out the
        #: full timeout (the "bounded-poll + token check" discipline)
        self._read_timeout = timeout
        self._sock.settimeout(0.25)
        self._lock = threading.Lock()  # one outstanding exchange per conn

    def _recv_exact(self, n: int) -> Optional[bytes]:
        from spark_rapids_tpu.utils import watchdog as W
        import time
        buf = bytearray()
        deadline = time.monotonic() + self._read_timeout
        while len(buf) < n:
            W.check_cancelled()
            try:
                chunk = self._sock.recv(n - len(buf))
            except socket.timeout:
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"no data from peer for "
                        f"{self._read_timeout:.0f}s") from None
                continue
            if not chunk:
                return None
            buf += chunk
            deadline = time.monotonic() + self._read_timeout
        return bytes(buf)

    def _recv_frame(self) -> Optional[bytes]:
        hdr = self._recv_exact(4)
        if hdr is None:
            return None
        (length,) = struct.unpack("<I", hdr)
        return self._recv_exact(length)

    def request(self, frame: bytes):
        with self._lock:
            _send_all(self._sock, frame)
            resp = self._recv_frame()
            if resp is None:
                raise ConnectionError("peer closed during request")
            return decode_frame(resp)

    def fetch(self, table_ids: Sequence[int],
              on_chunk: Callable[[int, int, bytes, bool], None]
              ) -> Transaction:
        with self._lock:
            try:
                _send_all(self._sock, transfer_request(table_ids))
                while True:
                    frame = self._recv_frame()
                    if frame is None:
                        return Transaction(TransactionStatus.ERROR,
                                           "peer closed during transfer")
                    kind, payload = decode_frame(frame)
                    if kind == MsgKind.DATA:
                        tid, packed, chunk, codec_id, raw_len = payload
                        on_chunk(tid, packed >> 1, chunk,
                                 bool(packed & 1), codec_id, raw_len)
                    elif kind == MsgKind.TRANSFER_RESPONSE:
                        return Transaction(
                            TransactionStatus(payload["status"]),
                            payload.get("error"), payload.get("bytes", 0))
                    else:
                        return Transaction(TransactionStatus.ERROR,
                                           f"unexpected frame {kind}")
            except WireCorruption as e:
                return Transaction(TransactionStatus.ERROR, str(e),
                                   corrupt=True)
            except OSError as e:
                return Transaction(TransactionStatus.ERROR, str(e))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class IciShuffleTransport(ShuffleTransport):
    """Default transport: loopback for same-process peers, TCP for DCN."""

    def __init__(self, conf: C.RapidsConf):
        super().__init__(conf)
        self._servers: list[TcpServer] = []
        self._executor_ids: list[str] = []
        self.faults = FaultInjector(
            conf[C.SHUFFLE_FAULT_DROP_RATE],
            conf[C.SHUFFLE_FAULT_CORRUPT_RATE],
            conf[C.SHUFFLE_FAULT_SEED],
            conf[C.SHUFFLE_FAULT_PEER_KILL_FRAMES])

    def make_server(self, executor_id: str, request_handler):
        with _LOOP_REGISTRY_LOCK:
            _LOOP_REGISTRY[executor_id] = request_handler
        self._executor_ids.append(executor_id)
        tcp = TcpServer(request_handler,
                        faults=self.faults if self.faults.active
                        else None,
                        on_kill=self.kill_self)
        self._servers.append(tcp)
        # peers prefer loopback when they share the process
        return type("ServerHandle", (), {
            "loop_address": f"loop://{executor_id}",
            "tcp_address": tcp.address})()

    def kill_self(self) -> None:
        """peer_kill landing point: this transport's executor(s) go
        dark on BOTH lanes — TCP listeners close, loopback
        registrations vanish — so no retry against them can succeed."""
        self.faults.peer_killed = True
        for s in self._servers:
            s.close()
        with _LOOP_REGISTRY_LOCK:
            for eid in self._executor_ids:
                _LOOP_REGISTRY.pop(eid, None)

    def can_reach(self, address: str) -> bool:
        # loop:// resolves only inside the process that registered it;
        # cross-process readers must fall back to the MapStatus's wire
        # address
        if address.startswith("loop://"):
            eid = address[len("loop://"):]
            with _LOOP_REGISTRY_LOCK:
                return eid in _LOOP_REGISTRY
        return True

    def make_client(self, peer_address: str) -> Connection:
        if peer_address.startswith("loop://"):
            eid = peer_address[len("loop://"):]
            with _LOOP_REGISTRY_LOCK:
                handler = _LOOP_REGISTRY.get(eid)
            if handler is None:
                raise ConnectionError(f"no loopback peer {eid}")
            return LoopbackConnection(handler, self, eid=eid)
        if peer_address.startswith("tcp://"):
            host, port = peer_address[len("tcp://"):].rsplit(":", 1)
            return TcpConnection(host, int(port))
        raise ValueError(f"bad peer address {peer_address}")

    def shutdown(self) -> None:
        for s in self._servers:
            s.close()
        self._servers.clear()
        with _LOOP_REGISTRY_LOCK:
            for eid in self._executor_ids:
                _LOOP_REGISTRY.pop(eid, None)
        self._executor_ids.clear()
