"""Shuffle payload compression codecs (reference
`TableCompressionCodec.scala:42-120`, `CopyCompressionCodec.scala`).

The reference compresses contiguous GPU tables with a pluggable codec and
carries codec descriptors in the FlatBuffers `BufferMeta`
(`ShuffleCommon.fbs` CodecBufferDescriptor); at the v0.2 snapshot only the
testing `copy` codec exists.

TPU redesign: device-resident batches are typed XLA arrays, not byte
buffers, and the TPU has no codec kernels — so compression applies to the
*serialized host payload* on the wire (the DCN lane, where bandwidth is
scarcest; the intra-slice ICI lane rides XLA collectives and never sees
bytes).  The codec id + uncompressed size travel in every DATA frame (the
role of the reference's CodecBufferDescriptor), and the receive side
decompresses before the blob lands in the host store.  Real codecs are
backed by Arrow's host codecs (lz4/zstd) — the role nvcomp would play in
a later reference snapshot.  The reference's BatchedTableCompressor
exists to amortize GPU codec kernel launches across small tables; host
codecs have no launch cost, so this SPI deliberately compresses one
payload at a time.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

# codec ids on the wire (reference format/CodecType.java: COPY = 0)
CODEC_NONE = -1   # never on the wire; "no compression" sentinel
CODEC_COPY = 0
CODEC_LZ4 = 1
CODEC_ZSTD = 2


class TableCompressionCodec:
    """SPI: compress/decompress one serialized table payload."""

    #: short name used in conf + logging
    name: str = "?"
    #: wire id (CodecType analog)
    codec_id: int = CODEC_NONE

    def compress(self, blob: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, blob: bytes, uncompressed_size: int) -> bytes:
        raise NotImplementedError


class CopyCompressionCodec(TableCompressionCodec):
    """Pass-through codec for protocol testing (reference
    `CopyCompressionCodec.scala`: a device memcpy)."""

    name = "copy"
    codec_id = CODEC_COPY

    def compress(self, blob: bytes) -> bytes:
        return bytes(blob)

    def decompress(self, blob: bytes, uncompressed_size: int) -> bytes:
        if len(blob) != uncompressed_size:
            raise ValueError(
                f"copy codec size mismatch: {len(blob)} != "
                f"{uncompressed_size}")
        return bytes(blob)


class _ArrowCodec(TableCompressionCodec):
    """Host codec backed by pyarrow's buffer compression."""

    _arrow_name: str = "?"

    def __init__(self):
        import pyarrow as pa
        self._codec = pa.Codec(self._arrow_name)

    def compress(self, blob: bytes) -> bytes:
        return self._codec.compress(blob, asbytes=True)

    def decompress(self, blob: bytes, uncompressed_size: int) -> bytes:
        return self._codec.decompress(
            blob, decompressed_size=uncompressed_size, asbytes=True)


class Lz4CompressionCodec(_ArrowCodec):
    name = "lz4"
    codec_id = CODEC_LZ4
    _arrow_name = "lz4"


class ZstdCompressionCodec(_ArrowCodec):
    name = "zstd"
    codec_id = CODEC_ZSTD
    _arrow_name = "zstd"


_BY_NAME = {c.name: c for c in
            (CopyCompressionCodec, Lz4CompressionCodec,
             ZstdCompressionCodec)}
# names an earlier conf doc advertised before the codecs existed
_BY_NAME["lz4-host"] = Lz4CompressionCodec
_BY_NAME["zstd-host"] = ZstdCompressionCodec
_BY_ID = {c.codec_id: c for c in _BY_NAME.values()}
_CACHE: dict[int, TableCompressionCodec] = {}
_CACHE_LOCK = threading.Lock()


def get_codec(name_or_id) -> Optional[TableCompressionCodec]:
    """Codec lookup with instance cache (reference
    `TableCompressionCodec.getCodec`).  Accepts the conf short name or
    the wire id; "none"/CODEC_NONE -> None (no compression)."""
    if name_or_id in (None, "none", CODEC_NONE):
        return None
    if isinstance(name_or_id, str):
        cls = _BY_NAME.get(name_or_id)
        if cls is None:
            raise ValueError(f"Unknown table codec: {name_or_id}")
        key = cls.codec_id
    else:
        cls = _BY_ID.get(int(name_or_id))
        if cls is None:
            raise ValueError(f"Unknown codec ID: {name_or_id}")
        key = cls.codec_id
    with _CACHE_LOCK:
        inst = _CACHE.get(key)
        if inst is None:
            inst = _CACHE[key] = cls()
        return inst


def codec_from_conf(conf) -> Optional[TableCompressionCodec]:
    from spark_rapids_tpu import config as C
    return get_codec(str(conf[C.SHUFFLE_COMPRESSION_CODEC]).lower())


# ---------------------------------------------------------------------------
# per-codec wire accounting (always-on, like the host-sync counter):
# every compressed payload the shuffle server serves notes its raw and
# wire sizes here, so codec choice is visible as a measured ratio in
# bench and the movement report, not a conf value taken on faith
_STATS_LOCK = threading.Lock()
_STATS: dict[str, list] = {}  # codec name -> [raw_bytes, wire_bytes, n]


def note_compression(codec_name: str, raw_bytes: int,
                     wire_bytes: int) -> None:
    """Record one payload's compression outcome for `codec_name`."""
    with _STATS_LOCK:
        st = _STATS.setdefault(codec_name, [0, 0, 0])
        st[0] += int(raw_bytes)
        st[1] += int(wire_bytes)
        st[2] += 1


def compression_stats() -> dict:
    """{codec: {raw_bytes, wire_bytes, payloads, ratio}} copy; ratio is
    wire/raw (< 1.0 means the codec is earning its CPU)."""
    with _STATS_LOCK:
        return {name: {"raw_bytes": r, "wire_bytes": w, "payloads": n,
                       "ratio": round(w / r, 4) if r else 1.0}
                for name, (r, w, n) in _STATS.items()}


def reset_compression_stats() -> None:
    with _STATS_LOCK:
        _STATS.clear()
