"""Shuffle exchange (reference `GpuShuffleExchangeExec.scala` +
`ShuffledBatchRDD.scala`).

The local-mode exchange: every upstream partition's batches are split with
the bound partitioner (device-side murmur3 + stable reorder + slice), and
each downstream partition concatenates its slices.  This is the analog of
the reference's default path (GPU partition -> serializer -> Spark netty
shuffle -> deserialize); the accelerated multi-chip path lives in
`parallel/collective_exchange.py` (ICI all-to-all under shard_map), and
`shuffle/transport.py` defines the pluggable cross-host transport SPI.

Also here: BroadcastExchangeExec (reference GpuBroadcastExchangeExec) —
collects the build side once and hands the same batch to every consumer.
"""
from __future__ import annotations

import threading
from typing import Iterator, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, concat_batches
from spark_rapids_tpu.exec.base import TpuExec, UnaryExecBase
from spark_rapids_tpu.shuffle.partitioning import (
    RangePartitioning, TpuPartitioning)
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils import profile as P


class ShuffleExchangeExec(UnaryExecBase):
    def __init__(self, partitioning: TpuPartitioning, child: TpuExec,
                 coalesce_small: bool = False):
        super().__init__(child)
        self._schema = child.output_schema()
        self.partitioning = partitioning.bind(self._schema)
        #: planner-set: the consumer only needs key CLUSTERING (e.g. a
        #: final aggregation), not index-aligned co-partitioning with a
        #: sibling exchange, so a small input may skip the split kernels
        #: entirely and land in one partition (AQE-style coalescing;
        #: reference analog: AQE coalesced shuffle reader,
        #: GpuCustomShuffleReaderExec).  NEVER set for join inputs.
        self.coalesce_small = coalesce_small

    def output_schema(self) -> T.Schema:
        return self._schema

    def output_partition_count(self) -> int:
        return self.partitioning.num_partitions

    def describe(self):
        return (f"ShuffleExchangeExec({type(self.partitioning).__name__}, "
                f"n={self.partitioning.num_partitions})")

    #: below this many input rows a range exchange degenerates to a
    #: single partition: a one-partition local sort is already globally
    #: ordered, and skipping bounds sampling + the split kernel saves
    #: several device round trips (AQE-style small-input coalescing)
    SMALL_RANGE_INPUT_ROWS = 1 << 15

    #: a coalesce_small exchange whose total input CAPACITY (static —
    #: no sync needed, unlike lazy row counts) stays at or below this
    #: emits one partition and skips the split kernels: dozens of tiny
    #: slice/concat dispatches through the tunnel cost far more than
    #: single-partition consumption of a few thousand rows
    SMALL_COALESCE_INPUT_CAP = 1 << 16

    #: max map-side batches whose split outputs may be device-resident
    #: at once in the two-phase split pipeline (see _materialize); deep
    #: enough that count readbacks fully overlap, shallow enough that an
    #: arbitrarily large map side can't OOM the device
    SPLIT_PIPELINE_DEPTH = 8

    def _range_inputs(self):
        """Range partitioning needs two passes over the child (sample
        bounds, then split), so its inputs are materialized once here.
        Returns (inputs, small) — `small` means a one-partition exchange
        suffices.  Hash/round-robin callers must NOT use this: they
        stream batch-at-a-time so pre-split inputs are freed as they go."""
        inputs = [b.dense() for it in self.child.execute_partitions()
                  for b in it if b.maybe_nonempty()]
        inputs = [b for b in inputs if b.num_rows > 0]
        total = sum(b.num_rows for b in inputs)
        n = self.partitioning.num_partitions
        small = total <= self.SMALL_RANGE_INPUT_ROWS or n == 1
        if not small and self.partitioning.bounds is None:
            self.partitioning.bounds = self._sample_bounds(
                self.partitioning, inputs)
        return inputs, small

    def _map_input_iter(self):
        """Map-side input stream (hash/round-robin lanes): child batches
        across all partitions, prefetched so the child's compute runs
        ahead of the split kernels (map side of the exchange pipeline
        break)."""
        from spark_rapids_tpu.exec.pipeline import maybe_prefetch
        return maybe_prefetch(
            (b for it in self.child.execute_partitions()
             for b in it if b.maybe_nonempty()),
            label="exchange-map", metrics=self.metrics)

    def _materialize(self) -> list[list[ColumnarBatch]]:
        """Run the map side: split every input batch; bucket by target."""
        buckets: list[list[ColumnarBatch]] = [
            [] for _ in range(self.partitioning.num_partitions)]
        for p, s in self._split_slices():
            buckets[p].append(s)
        return buckets

    def _split_slices(self):
        """Map side as an incremental stream of (partition, slice)
        pairs: each input batch's split lands as soon as its count
        readback does, so a downstream consumer (AQE's streaming stage
        materialization) can overlap reduce-side work with the rest of
        the map side instead of waiting for every bucket."""
        part = self.partitioning
        n = part.num_partitions
        if isinstance(part, RangePartitioning):
            inputs, small = self._range_inputs()
            if small:
                for b in inputs:
                    yield 0, b
                return
            batch_iter = iter(inputs)
        else:
            batch_iter = self._map_input_iter()
            if self.coalesce_small and n > 1:
                with self.metrics.timed(M.TOTAL_TIME):
                    head, cap_seen = [], 0
                    exhausted = True
                    for b in batch_iter:
                        head.append(b)
                        cap_seen += b.capacity
                        if cap_seen > self.SMALL_COALESCE_INPUT_CAP:
                            exhausted = False
                            break
                if exhausted:
                    for b in head:
                        self.metrics.add("dataSize", b.device_size_bytes())
                        yield 0, b
                    return
                import itertools
                batch_iter = itertools.chain(head, batch_iter)
        if hasattr(part, "split_device"):
            # two-phase pipeline: queue split kernels back-to-back and
            # overlap the count readbacks, finishing the oldest batch
            # once SPLIT_PIPELINE_DEPTH are in flight.  By the time a
            # batch becomes the oldest its async count readback has
            # landed, so the whole map side still pays ~one effective
            # host round trip — but peak device memory is bounded at
            # SPLIT_PIPELINE_DEPTH full-capacity split outputs instead
            # of the entire map side.
            pending: list = []

            def finish_oldest():
                c, k, b = pending.pop(0)
                return part.finish_split(c, k, b)

            for batch in batch_iter:
                # constant label: the profiled span costs one global
                # read + a shared null context when profiling is off
                with self.metrics.timed(M.TOTAL_TIME), \
                        P.span("exchange-split", cat=P.CAT_SHUFFLE):
                    t = part.split_device(batch)
                    try:
                        t[1].copy_to_host_async()
                    except Exception:
                        pass
                    pending.append(t)
                    slices = (finish_oldest()
                              if len(pending) >= self.SPLIT_PIPELINE_DEPTH
                              else None)
                if slices is not None:
                    yield from self._emit_slices(slices)
            while pending:
                with self.metrics.timed(M.TOTAL_TIME), \
                        P.span("exchange-split", cat=P.CAT_SHUFFLE):
                    slices = finish_oldest()
                yield from self._emit_slices(slices)
        else:
            for batch in batch_iter:
                with self.metrics.timed(M.TOTAL_TIME), \
                        P.span("exchange-split", cat=P.CAT_SHUFFLE):
                    slices = part.partition_batch(batch)
                yield from self._emit_slices(slices)

    def _emit_slices(self, slices):
        for p, s in enumerate(slices):
            if s is not None and s.maybe_nonempty():
                self.metrics.add("dataSize", s.device_size_bytes())
                yield p, s

    def _sample_bounds(self, part: RangePartitioning, inputs):
        """Driver-side reservoir sampling for range bounds (reference
        GpuRangePartitioner.sketch/SamplingUtils)."""
        import numpy as np
        import jax.numpy as jnp
        from spark_rapids_tpu.columnar.vector import bucket_capacity
        samples = []
        sample_rows = 0
        target = 20 * part.num_partitions
        for batch in inputs:
            # evenly-spaced sample of each batch (the reference uses
            # reservoir sampling; deterministic striding is equivalent
            # for bound estimation and cheaper on device)
            take = min(batch.num_rows, max(2, target))
            idx = np.linspace(0, batch.num_rows - 1, take).astype(int)
            cap = bucket_capacity(take)
            sel = jnp.asarray(np.pad(idx, (0, cap - take)))
            valid = jnp.arange(cap) < take
            samples.append(batch.gather(sel, valid, take))
            sample_rows += take
            if sample_rows >= 4 * target:
                break
        if not samples:
            from spark_rapids_tpu.columnar.batch import empty_batch
            return empty_batch(self._schema)
        sample = concat_batches(samples)
        return RangePartitioning.compute_bounds(
            sample, part.order, part.num_partitions)

    def execute_partitions(self):
        from spark_rapids_tpu import config as C
        mesh_axis = self._mesh_routable()
        if mesh_axis is not None:
            return self._execute_via_mesh(*mesh_axis)
        if C.get_active_conf()[C.RAPIDS_SHUFFLE_ENABLED]:
            return self._execute_via_manager()
        from spark_rapids_tpu.exec.pipeline import maybe_prefetch
        buckets = self._materialize()
        # reduce side of the exchange pipeline break: each partition's
        # merge/consolidation dispatches run ahead of its consumer
        return [maybe_prefetch(self._merged_reader(bs),
                               label="exchange-reduce",
                               metrics=self.metrics)
                for bs in buckets]

    #: reduce-side consolidation target (the role GpuCoalesceBatches
    #: plays after GPU shuffles, `GpuCoalesceBatches.scala:53`): a
    #: partition's split slices merge device-side up to this capacity
    #: before flowing downstream.  Without it every map-side batch
    #: contributes one slice per partition PER HOP, so a deep
    #: exchange chain multiplies batch count exponentially — TPC-DS
    #: q64 (19 exchanges) reached tens of thousands of live 1K-cap
    #: batches and tens of GB of device arrays.
    MERGE_TARGET_CAP = 1 << 16

    def _merged_reader(self, bs: list[ColumnarBatch]):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.columnar.vector import bucket_capacity
        # scale the consolidation target with the session's batch-row
        # budget: a 26M-row reduce partition under the 64K floor came
        # out as ~400 tiny batches — 400 probe/agg dispatches downstream
        target_cap = max(self.MERGE_TARGET_CAP, bucket_capacity(
            int(C.get_active_conf()[C.MAX_BATCH_ROWS])))
        group: list[ColumnarBatch] = []
        cap_sum = 0

        def flush():
            if len(group) == 1:
                m = group[0]
            elif self.coalesce_small:
                # consumer is a final aggregation / window that compacts
                # its groups right away, so the lazy concat's worst-case
                # capacity (bounded by MERGE_TARGET_CAP per flush group)
                # never propagates — and skipping the count sync keeps
                # the whole collect down to ONE readback wave (the
                # count sync below was measured at ~130ms through the
                # tunnel on the milestone-2 groupby: it must WAIT for
                # every queued partial-agg kernel before reading)
                m = concat_batches(list(group))
            else:
                # sync the slices' row counts (ONE stacked readback)
                # and concat TIGHT: the sync-free lazy concat keeps
                # the summed worst-case capacity, and across a deep
                # exchange chain that re-inflates every hop to the
                # merge target no matter how few real rows flow
                import jax.numpy as jnp
                import numpy as np
                dense = [b.dense() for b in group]
                unknown = [b for b in dense if not b.num_rows_known]
                if unknown:
                    from spark_rapids_tpu.utils import checks as CK
                    CK.note_host_sync("exchange.merge",
                                      nbytes=4 * len(unknown))
                    vals = np.asarray(jnp.stack(
                        [b.num_rows_i32 for b in unknown])).tolist()
                    it = iter(vals)
                    dense = [b if b.num_rows_known else
                             ColumnarBatch(b.schema, list(b.columns),
                                           int(next(it)), b.checks)
                             for b in dense]
                m = concat_batches([b for b in dense if b.num_rows > 0]
                                   or dense[:1])
            self.metrics.add(M.NUM_OUTPUT_ROWS, m._rows)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            return m

        for b in bs:
            if group and cap_sum + b.capacity > target_cap:
                yield flush()
                group, cap_sum = [], 0
            group.append(b)
            cap_sum += b.capacity
        if group:
            yield flush()

    def _mesh_routable(self):
        """The accelerated ICI lane applies when: the conf enables it, a
        device mesh is active, the partitioning is murmur3 hash over plain
        bound columns, and the partition count equals the mesh size (so
        device d IS partition d).  Anything else falls back to the
        local/manager lane — mirroring the reference, whose UCX data plane
        only takes over when the rapids shuffle manager is installed
        (RapidsShuffleInternalManager.scala:199)."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.exprs.base import BoundReference
        from spark_rapids_tpu.parallel import mesh as PM
        from spark_rapids_tpu.shuffle.partitioning import HashPartitioning
        if not C.get_active_conf()[C.MESH_EXCHANGE_ENABLED]:
            return None
        active = PM.get_active_mesh()
        if active is None:
            return None
        mesh, axis = active
        part = self.partitioning
        if not isinstance(part, HashPartitioning):
            return None
        if part.num_partitions != mesh.shape[axis]:
            return None
        if not all(isinstance(e, BoundReference) for e in part.exprs):
            return None
        return mesh, axis

    #: test-facing counter (ExecutionPlanCapture discipline): number of
    #: exchanges actually routed through the mesh collective lane
    _MESH_EXCHANGES_RUN = 0
    #: oversized single batches sharded across the mesh (SURVEY §5)
    _OVERSIZED_SPLITS = 0

    def _execute_via_mesh(self, mesh, axis):
        """Accelerated path: one SPMD all-to-all over the mesh replaces
        the per-batch split + bucket copy of the local lane.  Each mesh
        device owns one output partition; received rows are compacted
        device-side into a worst-case-sized (overflow-proof) batch."""
        import numpy as np
        from spark_rapids_tpu.columnar.batch import empty_batch
        from spark_rapids_tpu.columnar.vector import bucket_capacity
        from spark_rapids_tpu.parallel.collective_exchange import (
            build_all_to_all_exchange, build_count_exchange,
            stack_batches, stacked_payload_bytes, unstack_batches,
            watched_collective)
        n = self.partitioning.num_partitions
        from spark_rapids_tpu import config as C
        max_rows = C.get_active_conf()[C.MAX_BATCH_ROWS]
        groups: list[list[ColumnarBatch]] = [[] for _ in range(n)]
        slot = 0
        for it in self.child.execute_partitions():
            for b in it:
                if not b.maybe_nonempty():
                    continue
                # size LAZY batches by CAPACITY (a safe upper bound on
                # rows): coalesce's lazy_bounded pass-through emits
                # batches up to LAZY_PASS_MULT x the row cap whole, and
                # those must not skip HBM-budget sharding and land
                # entire on one chip.  Only the must-shard shape pays
                # the count sync (b.num_rows below).
                est_rows = (b.num_rows if b.num_rows_known
                            else b.capacity)
                if est_rows > max_rows and b.num_rows > max_rows:
                    # SURVEY §5 long-context analog: ONE batch larger
                    # than the per-chip budget is sharded ACROSS the
                    # mesh before the all-to-all (the sp lane), instead
                    # of overflowing one chip's HBM (reference guard:
                    # GpuCoalesceBatches.scala:166-169 + spill tiers)
                    per = -(-b.num_rows // n)
                    ShuffleExchangeExec._OVERSIZED_SPLITS += 1
                    for lo in range(0, b.num_rows, per):
                        groups[slot % n].append(
                            b.slice(lo, min(per, b.num_rows - lo)))
                        slot += 1
                else:
                    groups[slot % n].append(b)
                    slot += 1
        locals_ = [concat_batches(g).dense() if g
                   else empty_batch(self._schema)
                   for g in groups]
        cap = max(b.capacity for b in locals_)
        locals_ = [b if b.capacity == cap else b.with_capacity(cap)
                   for b in locals_]
        key_idx = tuple(e.ordinal for e in self.partitioning.exprs)
        # process-global LRU (bounded + clearable): mesh identity enters
        # the key as device ids, not the Mesh object, so dead meshes are
        # not pinned beyond the cached executable's LRU lifetime
        from spark_rapids_tpu.exec.base import KernelCache
        cache = KernelCache((
            "mesh_exchange", axis,
            tuple(d.id for d in mesh.devices.flat),
            tuple((f.name, str(f.dtype)) for f in self._schema.fields),
            key_idx))
        schema = self._schema
        ShuffleExchangeExec._MESH_EXCHANGES_RUN += 1
        # the whole-mesh dispatch gate covers every enqueue touching
        # the sharded arrays (count phase, data phase, AND the
        # unstack slicing): concurrent whole-mesh programs enqueued
        # from two threads can invert per-device queue order and
        # deadlock the collective rendezvous (exec/scheduler.py)
        from spark_rapids_tpu.exec import scheduler as S
        with self.metrics.timed(M.TOTAL_TIME), \
                P.span("mesh-exchange", cat=P.CAT_SHUFFLE), \
                S.whole_mesh_dispatch(label="mesh-exchange"):
            arrs, num_rows = stack_batches(locals_, cap)
            # explicit mesh layout (the pjit/GDA pattern): device d of
            # the data axis owns stacked slot d.  Also REQUIRED for
            # committed single-device inputs (an upstream SPMD gang's
            # outputs live on the default device) — shard_map rejects
            # them without the reshard.
            import jax
            from spark_rapids_tpu.parallel import mesh as PM
            arrs, num_rows = jax.device_put(
                (arrs, num_rows), PM.data_sharding(mesh, axis))
            # movement ledger: the payload the data-phase all-to-all
            # ships over ICI — every column's stacked data + validity
            # (+ lengths) arrays (the count phase is n_dev ints, noise)
            from spark_rapids_tpu.utils import movement as MV
            payload = 0
            if MV.ledger() is not None:
                payload = stacked_payload_bytes(arrs)
                self.metrics.add(M.COLLECTIVE_BYTES, payload)
            # two-phase exchange (ADVICE r2): a counts-only all-to-all
            # sizes the data phase's receive buffers from ACTUAL totals
            # — the old n_dev*cap worst case OOMs HBM-scale batches
            count_fn = cache.get_or_build(
                ("count", cap),
                lambda: build_count_exchange(mesh, axis, schema,
                                             key_idx, cap))
            from spark_rapids_tpu.utils import checks as CK
            CK.note_host_sync("exchange.mesh", nbytes=4 * n)
            totals = watched_collective(
                lambda: np.asarray(count_fn(arrs, num_rows)),
                label="mesh-count")
            out_cap = int(bucket_capacity(max(int(totals.max()), 1)))
            step = cache.get_or_build(
                ("step", cap, out_cap),
                lambda: build_all_to_all_exchange(
                    mesh, axis, schema, key_idx, cap,
                    out_capacity=out_cap))
            out_arrs, out_rows = watched_collective(
                lambda: step(arrs, num_rows), label="mesh-exchange",
                nbytes=payload)
            out = unstack_batches(out_arrs, np.asarray(out_rows),
                                  self._schema)
        for b in out:
            self.metrics.add("dataSize", b.device_size_bytes())

        def reader(b: ColumnarBatch):
            if b.num_rows > 0:
                self.metrics.add(M.NUM_OUTPUT_ROWS, b.num_rows)
                self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
                yield b
        return [reader(b) for b in out]

    _SHUFFLE_IDS = iter(range(1, 1 << 31))

    def _execute_via_manager(self):
        """Accelerated path: map outputs land in the spillable shuffle
        catalog; reducers pull through the caching reader (reference
        RapidsShuffleManager write/read, SURVEY.md §3.4).

        Fault recovery (shuffle/recovery.py): map tasks spread across
        spark.rapids.shuffle.localExecutors in-process executors
        (round-robin over NON-blacklisted peers); the reduce side runs
        through a ShuffleRecoveryDriver whose recompute closure retains
        this exchange's map lineage — a lost peer's map tasks re-run
        from `self.child` and land on the (always-alive) reducing
        executor."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.shuffle.manager import (
            MapOutputRegistry, TpuShuffleManager)
        from spark_rapids_tpu.shuffle.recovery import (
            PeerHealth, ShuffleRecoveryDriver)
        conf = C.get_active_conf()
        n_execs = max(1, int(conf[C.SHUFFLE_LOCAL_EXECUTORS]))
        names = (["local"] if n_execs == 1
                 else [f"local-{i}" for i in range(n_execs)])
        mgrs = [TpuShuffleManager.get_or_create(nm) for nm in names]
        primary = mgrs[0]
        health = PeerHealth.get()
        shuffle_id = next(ShuffleExchangeExec._SHUFFLE_IDS)
        for m in mgrs:
            m.register_shuffle(shuffle_id)
        part = self.partitioning
        if isinstance(part, RangePartitioning) and part.bounds is None:
            # two passes needed: materialize per-map batches once so the
            # bounds sample and the split see the same data
            per_map = [[b for b in it if b.num_rows > 0]
                       for it in self.child.execute_partitions()]
            part.bounds = self._sample_bounds(
                part, [b for bs in per_map for b in bs])
            map_iters = [iter(bs) for bs in per_map]
        else:
            from spark_rapids_tpu.exec.pipeline import maybe_prefetch
            map_iters = [maybe_prefetch(it, label="exchange-map",
                                        metrics=self.metrics)
                         for it in self.child.execute_partitions()]
        n = part.num_partitions
        repl_factor = max(1, int(conf[C.SHUFFLE_REPLICATION_FACTOR]))

        def healthy_mgrs():
            ok = [m for m in mgrs
                  if not any(health.is_blacklisted(a) for a in
                             (m.loop_address, m.tcp_address) if a)]
            return ok or [primary]

        def replicas_for(mgr):
            """factor-1 backup executors for a map task hosted on
            `mgr`: the next healthy peers in ring order."""
            if repl_factor < 2:
                return ()
            pool_ = [m for m in healthy_mgrs() if m is not mgr]
            return tuple(pool_[:repl_factor - 1])

        def write_map_task(map_id, batch_iter, mgr, epoch=None,
                           first_wins=False):
            from spark_rapids_tpu.utils import watchdog as W
            writer = mgr.get_writer(shuffle_id, map_id,
                                    replicas=replicas_for(mgr))
            sp = P.span(f"shuffle-map:s{shuffle_id}m{map_id}",
                        cat=P.CAT_SHUFFLE) \
                if P.tracer() is not None else P._NULL_SPAN
            try:
                with sp:
                    for batch in batch_iter:
                        # batch boundary = cancellation point: a losing
                        # speculative attempt stops here, promptly
                        W.check_cancelled()
                        # seeded slow-task injection (the straggler
                        # model speculation must beat)
                        W.maybe_slow("map-task", conf=conf,
                                     executor_id=mgr.executor_id)
                        if batch.num_rows == 0:
                            continue
                        with self.metrics.timed(M.TOTAL_TIME):
                            slices = part.partition_batch(batch)
                        for p, s in enumerate(slices):
                            if s is not None and s.num_rows > 0:
                                writer.write_partition(p, s)
                                self.metrics.add("dataSize",
                                                 s.device_size_bytes())
            except BaseException:
                writer.abort()
                raise
            writer.commit(n, epoch=epoch, first_wins=first_wins)
            if writer.replicated_bytes:
                self.metrics.add(M.REPLICATED_BYTES,
                                 writer.replicated_bytes)

        def lineage(map_id):
            # retained map-side lineage (shared with recovery): a
            # FRESH run of exactly this child partition
            return self.child.execute_partitions()[map_id]

        def backup_for(exclude_mgr):
            ok = [m for m in healthy_mgrs() if m is not exclude_mgr]
            return ok[0] if ok else None

        from spark_rapids_tpu.exec import speculation as SPEC
        spec = SPEC.maybe_create(
            shuffle_id, conf, self.metrics, write_map_task, lineage,
            backup_for, num_executors=len(mgrs))
        try:
            pool = healthy_mgrs()
            for map_id, it in enumerate(map_iters):
                mgr = pool[map_id % len(pool)]
                if spec is not None:
                    spec.run_task(map_id, it, mgr)
                else:
                    write_map_task(map_id, it, mgr)
            # arm the partial-read guard: a reduce over fewer outputs
            # than this must FetchFail, never return partial data
            MapOutputRegistry.set_expected_maps(shuffle_id,
                                                len(map_iters))
        except BaseException:
            # failed map stage: free completed tasks' buffers too — no
            # reader will ever run _done()
            for m in mgrs:
                m.unregister_shuffle(shuffle_id)
            raise
        finally:
            if spec is not None:
                spec.finish()

        driver = None
        if conf[C.SHUFFLE_RECOVERY_ENABLED]:
            def recompute(lost_map_ids, epoch):
                # retained map-side lineage: re-run ONLY the lost map
                # partitions of the child, splitting with the same
                # bound partitioning (range bounds already sampled),
                # and land them on the reducing executor — the one
                # peer recovery can rely on being alive
                its = self.child.execute_partitions()
                for map_id in lost_map_ids:
                    write_map_task(map_id, its[map_id], primary,
                                   epoch=epoch)
            driver = ShuffleRecoveryDriver(
                primary, shuffle_id, recompute, conf=conf,
                metrics=self.metrics)

        # free the shuffle's spillable buffers + map-output entries once
        # every partition reader is exhausted (or closed early)
        remaining = [n]
        lock = threading.Lock()

        def _done():
            with lock:
                remaining[0] -= 1
                last = remaining[0] == 0
            if last:
                for m in mgrs:
                    m.unregister_shuffle(shuffle_id)

        def reader(p: int):
            try:
                batches = (driver.read_partition(p)
                           if driver is not None
                           else primary.get_reader(shuffle_id, p,
                                                   metrics=self.metrics))
                for b in batches:
                    self.metrics.add(M.NUM_OUTPUT_ROWS, b.num_rows)
                    self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
                    yield b
            finally:
                _done()
        from spark_rapids_tpu.exec.pipeline import maybe_prefetch
        return [maybe_prefetch(reader(p), label="exchange-reduce",
                               metrics=self.metrics)
                for p in range(n)]

    def execute_columnar(self):
        for it in self.execute_partitions():
            yield from it


class BroadcastTimeoutError(RuntimeError):
    """Build-side materialization exceeded spark.sql.broadcastTimeout
    (reference GpuBroadcastExchangeExec: 'Could not execute broadcast
    in N secs' from the collect future's timeout)."""


class BroadcastTooLargeError(RuntimeError):
    """Build side exceeded spark.rapids.tpu.maxBroadcastTableBytes
    (Spark's 8GB broadcast-table limit analog)."""


class BroadcastExchangeExec(UnaryExecBase):
    """Collect the (small) build side once; every consumer gets the same
    single batch (reference GpuBroadcastExchangeExec +
    SerializeConcatHostBuffersDeserializeBatch semantics, minus the
    torrent wire format).

    Guards (reference GpuBroadcastExchangeExec.scala:238): the build
    collect is bounded by spark.sql.broadcastTimeout and the total
    device bytes by spark.rapids.tpu.maxBroadcastTableBytes, so a
    runaway build side fails with a clear error instead of hanging the
    query or exhausting HBM.  Design shift: the reference runs the
    collect on a dedicated thread pool and times out the future; this
    engine executes one query at a time on the driver thread, so the
    timeout is COOPERATIVE — checked between build-side batches (a
    single wedged batch kernel is the driver's watchdog's job)."""

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._schema = child.output_schema()
        self._cached: Optional[ColumnarBatch] = None

    def output_schema(self):
        return self._schema

    def output_partition_count(self) -> int:
        return 1

    def broadcast_batch(self) -> ColumnarBatch:
        if self._cached is None:
            import time
            from spark_rapids_tpu import config as C
            conf = C.get_active_conf()
            timeout_s = conf[C.BROADCAST_TIMEOUT]
            max_bytes = conf[C.MAX_BROADCAST_TABLE_BYTES]
            with self.metrics.timed("broadcastTime"):
                t0 = time.monotonic()
                batches, total = [], 0
                for it in self.child.execute_partitions():
                    for b in it:
                        if not b.maybe_nonempty():
                            continue
                        batches.append(b)
                        total += b.device_size_bytes()
                        if total > max_bytes:
                            raise BroadcastTooLargeError(
                                f"broadcast build side reached {total} "
                                f"bytes > spark.rapids.tpu."
                                f"maxBroadcastTableBytes={max_bytes}")
                        if time.monotonic() - t0 > timeout_s:
                            raise BroadcastTimeoutError(
                                f"could not execute broadcast in "
                                f"{timeout_s} secs "
                                f"(spark.sql.broadcastTimeout)")
                if batches:
                    self._cached = concat_batches(batches).dense()
                else:
                    from spark_rapids_tpu.columnar.batch import empty_batch
                    self._cached = empty_batch(self._schema)
                self.metrics.add("dataSize",
                                 self._cached.device_size_bytes())
        return self._cached

    def execute_columnar(self):
        yield self.broadcast_batch()

    def execute_partitions(self):
        return [self.execute_columnar()]
