"""Shuffle buffer catalogs over the tiered-store BufferCatalog.

Reference: `ShuffleBufferCatalog.scala` (shuffleId -> blockId -> bufferIds
mapping for map-side outputs held in the device store) and
`ShuffleReceivedBufferCatalog.scala` (reduce-side received buffers).
Registration is per-shuffle so unregistering a shuffle frees every
associated buffer across all tiers.
"""
from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu.memory.buffer import BufferId, TableMeta
from spark_rapids_tpu.memory.catalog import BufferCatalog


class ShuffleBufferCatalog:
    """Map-side catalog: tracks which buffer ids make up each shuffle
    block (shuffle_id, map_id, partition)."""

    def __init__(self, catalog: BufferCatalog):
        self.catalog = catalog
        self._lock = threading.Lock()
        # shuffle_id -> {(map_id, partition): [BufferId]}
        self._blocks: dict[int, dict[tuple[int, int], list[BufferId]]] = {}
        self._by_table: dict[int, BufferId] = {}

    def register_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._blocks.setdefault(shuffle_id, {})

    def has_active_shuffle(self, shuffle_id: int) -> bool:
        with self._lock:
            return shuffle_id in self._blocks

    def next_shuffle_buffer_id(self, shuffle_id: int, map_id: int,
                               partition: int) -> BufferId:
        bid = BufferId(self.catalog.next_table_id(), shuffle_id, map_id,
                       partition)
        with self._lock:
            if shuffle_id not in self._blocks:
                raise ValueError(f"shuffle {shuffle_id} not registered")
            self._blocks[shuffle_id].setdefault(
                (map_id, partition), []).append(bid)
            self._by_table[bid.table_id] = bid
        return bid

    def lookup_table(self, table_id: int) -> BufferId:
        with self._lock:
            return self._by_table[table_id]

    def blocks_for_partition(self, shuffle_id: int, partition: int,
                             map_ids: Optional[list[int]] = None
                             ) -> list[BufferId]:
        with self._lock:
            blocks = self._blocks.get(shuffle_id, {})
            out = []
            for (m, p), bids in sorted(blocks.items()):
                if p != partition:
                    continue
                if map_ids is not None and m not in map_ids:
                    continue
                out.extend(bids)
            return out

    def meta_for(self, bid: BufferId) -> TableMeta:
        with self.catalog.acquired(bid) as buf:
            return buf.meta

    def remove_buffers(self, bids: list[BufferId]) -> None:
        """Remove EXACTLY these buffers (a failed/losing attempt's own
        writes).  Attempt-scoped, unlike `remove_task_buffers`: with
        speculation or replication two attempts' buffers can share one
        (map_id, partition) slot in this catalog, and a loser's cleanup
        must never free the winner's data."""
        with self._lock:
            for bid in bids:
                blocks = self._blocks.get(bid.shuffle_id, {})
                lst = blocks.get((bid.map_id, bid.partition))
                if lst is not None and bid in lst:
                    lst.remove(bid)
                    if not lst:
                        del blocks[(bid.map_id, bid.partition)]
                self._by_table.pop(bid.table_id, None)
        for bid in bids:
            if self.catalog.is_registered(bid):
                self.catalog.remove(bid)

    def remove_task_buffers(self, shuffle_id: int, map_id: int) -> None:
        """Failed-task cleanup (reference RapidsCachingWriter cleanup)."""
        with self._lock:
            blocks = self._blocks.get(shuffle_id, {})
            doomed = [(k, v) for k, v in blocks.items() if k[0] == map_id]
            for k, bids in doomed:
                del blocks[k]
                for bid in bids:
                    self._by_table.pop(bid.table_id, None)
        for _, bids in doomed:
            for bid in bids:
                self.catalog.remove(bid)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            blocks = self._blocks.pop(shuffle_id, {})
            for bids in blocks.values():
                for bid in bids:
                    self._by_table.pop(bid.table_id, None)
        for bids in blocks.values():
            for bid in bids:
                self.catalog.remove(bid)


class ShuffleReceivedBufferCatalog:
    """Reduce-side catalog for buffers fetched from remote executors
    (reference ShuffleReceivedBufferCatalog.scala)."""

    def __init__(self, catalog: BufferCatalog):
        self.catalog = catalog
        self._lock = threading.Lock()
        self._received: dict[int, list[BufferId]] = {}  # per task attempt

    def add_received(self, task_attempt_id: int, bid: BufferId) -> None:
        with self._lock:
            self._received.setdefault(task_attempt_id, []).append(bid)

    def new_buffer_id(self) -> BufferId:
        return BufferId(self.catalog.next_table_id())

    def take_task(self, task_attempt_id: int) -> list[BufferId]:
        """Detach a task attempt's received buffers WITHOUT freeing
        them (hedged-fetch winner adoption: the staging attempt's
        buffers are re-registered under the consuming reader's attempt
        id, whose release_task then owns their cleanup)."""
        with self._lock:
            return self._received.pop(task_attempt_id, [])

    def release_task(self, task_attempt_id: int) -> None:
        with self._lock:
            bids = self._received.pop(task_attempt_id, [])
        for bid in bids:
            if self.catalog.is_registered(bid):
                self.catalog.remove(bid)
