"""TPU partitioners (reference `GpuHashPartitioning.scala`,
`GpuRoundRobinPartitioning.scala`, `GpuSinglePartitioning.scala`,
`GpuRangePartitioner.scala` + `GpuPartitioning.scala` contiguous split).

Each partitioner computes per-row target partition ids on device, then
`contiguous_split` stably reorders rows by partition and returns per-
partition slices — the analog of cuDF's `Table.contiguousSplit` after a
murmur3 partition kernel.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import bucket_capacity
from spark_rapids_tpu.exec.base import KernelCache, batch_signature, \
    columns_signature, make_eval_context
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.ops.murmur3 import partition_ids
from spark_rapids_tpu.ops.sort_encode import multi_key_argsort


class TpuPartitioning:
    num_partitions: int

    def bind(self, schema: T.Schema) -> "TpuPartitioning":
        return self

    def partition_batch(self, batch: ColumnarBatch
                        ) -> list[ColumnarBatch]:
        """Split a batch into num_partitions batches (possibly empty)."""
        raise NotImplementedError


def _split_kernel_for(cache: KernelCache, batch: ColumnarBatch,
                      pid_fn, num_partitions: int, extra_key=()):
    """Shared: sort rows by partition id, count per partition.  `pid_fn`
    receives a traced `extra` pytree (e.g. range bounds) so data-dependent
    parameters stay kernel ARGUMENTS — one compile serves any bounds."""
    key = ("split", num_partitions, extra_key, batch_signature(batch))

    def build():
        cap = batch.capacity

        @jax.jit
        def kernel(columns, num_rows, salt, extra, mask=None):
            ctx = make_eval_context(columns, cap, num_rows, mask)
            pids = pid_fn(ctx, salt, extra)
            pids = jnp.where(ctx.row_mask, pids, num_partitions)
            cols, counts = _payload_sort_reorder(
                pids, columns, ctx.row_mask, num_partitions)
            return cols, counts

        return kernel

    return cache.get_or_build(key, build)


def _payload_sort_reorder(pids, columns, row_mask, npart: int):
    """Stable partition reorder via ONE payload-carrying sort network.

    Every column array (data, validity, lengths, narrow shadows) rides
    the pid sort as a PAYLOAD operand: measured at 4M rows, the u32
    sort network costs ~172ms and six 64-bit payload operands add <10%
    — while the old two-step (counting-sort ranks + inversion scatter
    ~202ms, then per-stream gathers at ~53ns per 4-byte ELEMENT,
    ~250ms for two streams) paid per element moved.  Random access is
    the most expensive primitive on this chip; the sort network moves
    payloads with vectorized compare-exchanges instead.

    Only string CHAR MATRICES (2D) can't ride along (lax.sort operands
    must share one shape) — those gather through a carried iota order.
    Returns (reordered ColumnVectors, per-partition counts)."""
    from jax import lax
    from spark_rapids_tpu.columnar.vector import ColumnVector
    cap = pids.shape[0]
    # counts via one-hot reduce (bincount lowers to a serialized
    # scatter-add on XLA:TPU)
    counts = (pids[:, None] ==
              jnp.arange(npart, dtype=pids.dtype)[None, :]
              ).astype(jnp.int32).sum(axis=0)
    ops = [pids.astype(jnp.uint32)]
    any_string = any(c.dtype.is_string for c in columns)
    if any_string:
        ops.append(lax.iota(jnp.int32, cap))
    ops.append(row_mask)
    slots = []
    for c in columns:
        start = len(ops)
        if c.dtype.is_string:
            ops.extend([c.validity, c.lengths])
        else:
            ops.append(c.data)
            ops.append(c.validity)
            if c.narrow is not None:
                ops.append(c.narrow)
        slots.append((start, len(ops)))
    sortd = lax.sort(ops, num_keys=1, is_stable=True)
    pos = 2 if any_string else 1
    order = sortd[1] if any_string else None
    valid = sortd[pos]
    out = []
    for c, (start, _end) in zip(columns, slots):
        if c.dtype.is_string:
            v, ln = sortd[start], sortd[start + 1]
            data = jnp.take(c.data, order, axis=0, mode="clip")
            out.append(ColumnVector(c.dtype, data, v & valid, ln))
        else:
            data = sortd[start]
            v = sortd[start + 1]
            narrow = sortd[start + 2] if c.narrow is not None else None
            out.append(ColumnVector(c.dtype, data, v & valid, None,
                                    narrow))
    return out, counts


def _gather_reordered(columns, order, valid, packed_bits=None):
    """Row reorder with the fewest random-access streams (each costs
    ~70ns/row on this chip, dwarfing bandwidth): all 4-byte value
    streams AND the packed validity word ride ONE stacked gather, f64
    streams another (`gather_columns_grouped`).  Strings keep the
    general ColumnVector.gather (char tensors need their own streams
    anyway).  `packed_bits` lets a caller that gathers the same
    columns repeatedly (the partition cut kernel) pack the validity
    mask once."""
    from spark_rapids_tpu.columnar.vector import gather_columns_grouped
    return gather_columns_grouped(columns, order, valid, packed_bits)


#: lazy slicing keeps slices at the INPUT batch's capacity (the count is
#: still on device), so it only pays off when that capacity is small;
#: past this cap the ~150ms count sync amortizes over real compute and
#: tightly-bucketed slices matter more than the round trip.
LAZY_SLICE_MAX_CAP = 1 << 16


_CUT_CACHE = KernelCache(("partition_cut",))


def _cut_kernel_for(schema: T.Schema, cols, total_cap: int, n_parts: int):
    """ONE jitted dispatch that cuts the pid-sorted batch into all
    n_parts full-capacity slices (plus their lazy row counts).  The
    per-partition lazy-slice loop this replaces paid ~6 eager
    dispatches per COLUMN per partition — on a deep plan (TPC-DS q64:
    18 joins, ~30 exchanges) that dominated wall-clock; here XLA fuses
    the whole cut and the engine pays one dispatch per input batch."""
    key = (total_cap, n_parts) + columns_signature(schema.fields, cols)

    def build():
        from spark_rapids_tpu.columnar.vector import pack_validity_bits
        base = jnp.arange(total_cap)

        @jax.jit
        def kernel(columns, counts):
            offs = jnp.cumsum(counts) - counts
            packed_bits = pack_validity_bits(columns)
            outs = []
            for p in range(n_parts):
                valid = base < counts[p]
                idx = jnp.where(valid, base + offs[p], 0)
                outs.append((_gather_reordered(columns, idx, valid,
                                               packed_bits),
                             counts[p].astype(jnp.int32)))
            return outs

        return kernel

    return _CUT_CACHE.get_or_build(key, build)


def _slice_partitions(batch_cols, counts, schema: T.Schema,
                      total_cap: int, checks: tuple = ()
                      ) -> list[ColumnarBatch]:
    """Cut the pid-sorted batch into per-partition batches.  `counts`
    may be a DEVICE vector: small batches slice sync-free (one fused
    cut kernel, lazy row counts); large ones sync once and cut tight
    host-side slices.  (Lazy slicing at ANY capacity for
    clustering-only consumers was tried and measured SLOWER — the
    full-capacity slices make every downstream per-slice kernel pay the
    input capacity, which costs more than the count sync saves.)"""
    n_parts = counts.shape[0]
    if not isinstance(counts, np.ndarray) and total_cap <= LAZY_SLICE_MAX_CAP:
        kern = _cut_kernel_for(schema, batch_cols, total_cap, n_parts)
        return [ColumnarBatch(schema, cols, n, checks)
                for cols, n in kern(list(batch_cols), counts)]
    if not isinstance(counts, np.ndarray):
        from spark_rapids_tpu.utils import checks as CK
        CK.note_host_sync("partition.cut", nbytes=4 * n_parts)
    counts = np.asarray(counts)
    out = []
    offsets = np.concatenate([[0], np.cumsum(counts)])
    reordered = ColumnarBatch(schema, list(batch_cols), int(offsets[-1]),
                              checks)
    for p in range(len(counts)):
        n = int(counts[p])
        if n == 0:
            out.append(None)
            continue
        out.append(reordered.slice(int(offsets[p]), n))
    return out


@dataclasses.dataclass
class HashPartitioning(TpuPartitioning):
    """murmur3(keys) pmod n — bit-identical to Spark's HashPartitioning so
    TPU and CPU stages can co-shuffle."""
    exprs: Sequence[Expression]
    num_partitions: int

    def bind(self, schema):
        from spark_rapids_tpu.exprs.base import fingerprint
        bound = [e.bind(schema) for e in self.exprs]
        b = HashPartitioning(bound, self.num_partitions)
        b._cache = KernelCache(("HashPartitioning", fingerprint(bound),
                                self.num_partitions))
        return b

    def split_device(self, batch):
        """Phase 1 of the two-phase split: run the device kernel and
        return (cols, device counts, src batch) WITHOUT syncing.  The
        exchange runs this for every input batch back-to-back, then
        overlaps all the count readbacks — one effective round trip for
        the whole map side instead of one per batch."""
        cache = getattr(self, "_cache", None)
        if cache is None:
            cache = self._cache = KernelCache()
        bound = self.exprs
        n = self.num_partitions

        def pid_fn(ctx, salt, extra):
            keys = [e.eval(ctx) for e in bound]
            return partition_ids(keys, n)

        kern = _split_kernel_for(cache, batch, pid_fn, n, "hash")
        cols, counts = kern(batch.columns, batch.num_rows_i32,
                            jnp.int32(0), (), batch.sparse)
        return cols, counts, batch

    @staticmethod
    def finish_split(cols, counts, batch):
        """Phase 2: cut slices with the (prefetched) counts."""
        if batch.capacity > LAZY_SLICE_MAX_CAP:
            from spark_rapids_tpu.utils import checks as CK
            CK.note_host_sync("partition.cut",
                              nbytes=int(counts.size) * 4)
            counts = np.asarray(counts)
        return _slice_partitions(cols, counts, batch.schema,
                                 batch.capacity, batch.checks)

    def partition_batch(self, batch):
        cols, counts, src = self.split_device(batch)
        return self.finish_split(cols, counts, src)


@dataclasses.dataclass
class RoundRobinPartitioning(TpuPartitioning):
    num_partitions: int

    def bind(self, schema):
        b = RoundRobinPartitioning(self.num_partitions)
        b._cache = KernelCache(("RoundRobinPartitioning",
                                self.num_partitions))
        return b

    def partition_batch(self, batch):
        cache = getattr(self, "_cache", None)
        if cache is None:
            cache = self._cache = KernelCache()
        n = self.num_partitions

        def pid_fn(ctx, salt, extra):
            from jax import lax
            return lax.rem(jnp.arange(ctx.capacity, dtype=jnp.int32) + salt,
                           jnp.int32(n))

        kern = _split_kernel_for(cache, batch, pid_fn, n, "rr")
        salt = np.random.randint(0, n)  # start-partition randomization
        cols, counts = kern(batch.columns, batch.num_rows_i32,
                            jnp.int32(salt), (), batch.sparse)
        return _slice_partitions(cols, counts, batch.schema,
                                 batch.capacity, batch.checks)


@dataclasses.dataclass
class SinglePartitioning(TpuPartitioning):
    num_partitions: int = 1

    def partition_batch(self, batch):
        return [batch]


@dataclasses.dataclass
class RangePartitioning(TpuPartitioning):
    """Driver-side reservoir-sampled bounds + per-row binary search
    (reference GpuRangePartitioner/GpuRangePartitioning + SamplingUtils).

    `bounds` are computed once from sampled child data via
    `compute_bounds`; rows route to the first bound >= key.
    """
    order: Sequence  # list[SortOrder]
    num_partitions: int
    bounds: Optional[ColumnarBatch] = None  # (num_partitions-1) rows

    def bind(self, schema):
        from spark_rapids_tpu.exec.sort import SortOrder
        from spark_rapids_tpu.exprs.base import fingerprint
        bound = [SortOrder(o.expr.bind(schema), o.ascending,
                           o.nulls_first) for o in self.order]
        b = RangePartitioning(bound, self.num_partitions, self.bounds)
        # bounds ride in as traced kernel args, so the executable is
        # shareable across bounds values / plan instances
        b._cache = KernelCache(("RangePartitioning", fingerprint(bound),
                                self.num_partitions))
        return b

    @staticmethod
    def compute_bounds(sample: ColumnarBatch, order, num_partitions: int
                       ) -> ColumnarBatch:
        """Sort the sample and take evenly spaced split points."""
        from spark_rapids_tpu.exec.basic import LocalBatchSource
        from spark_rapids_tpu.exec.sort import SortExec
        s = SortExec(order, LocalBatchSource([[sample]]))
        srt = s.collect()
        n = srt.num_rows
        k = num_partitions - 1
        if n == 0 or k <= 0:
            return srt.slice(0, 0)
        idx = [min(n - 1, max(0, int(round((i + 1) * n / num_partitions))))
               for i in range(k)]
        parts = [srt.slice(i, 1) for i in idx]
        from spark_rapids_tpu.columnar.batch import concat_batches
        return concat_batches(parts)

    def partition_batch(self, batch):
        assert self.bounds is not None, "compute_bounds first"
        cache = getattr(self, "_cache", None)
        if cache is None:
            cache = self._cache = KernelCache()
        n = self.num_partitions
        order = self.order
        # key columns of the bounds, aligned to batch capacity for compare
        bounds = self.bounds
        k = bounds.num_rows

        def pid_fn(ctx, salt, extra):
            # composite comparison row-vs-bound via pairwise key compare:
            # pid = number of bounds strictly less-or-equal (k small)
            bcols = extra
            keys = [o.expr.eval(ctx) for o in order]
            pid = jnp.zeros(ctx.capacity, jnp.int32)
            for bi in range(k):
                le = _row_less_than_bound(keys, bcols, bi, order)
                # row > bound_bi -> belongs at least to partition bi+1
                pid = jnp.where(le, pid, jnp.int32(bi + 1))
            return pid

        bounds_sig = tuple(
            (str(c.dtype), c.capacity,
             c.char_cap if c.dtype.is_string else 0)
            for c in bounds.columns)
        kern = _split_kernel_for(cache, batch, pid_fn, n,
                                 ("range", k, bounds_sig))
        cols, counts = kern(batch.columns, batch.num_rows_i32,
                            jnp.int32(0), tuple(bounds.columns),
                            batch.sparse)
        return _slice_partitions(cols, counts, batch.schema,
                                 batch.capacity, batch.checks)


def _row_less_than_bound(keys, bounds, bi: int, order) -> jnp.ndarray:
    """row <= bound_bi under the sort order (null ordering included).
    `bounds` is a ColumnarBatch or a sequence of its key ColumnVectors."""
    from spark_rapids_tpu.exprs.predicates import _compare
    bcols = bounds.columns if hasattr(bounds, "columns") else bounds
    cap = keys[0].capacity
    lt_all = jnp.zeros(cap, bool)
    eq_all = jnp.ones(cap, bool)
    for key_col, o, bcol in zip(keys, order, bcols):
        bv = _broadcast_row(bcol, bi, cap)
        lt, eq = _compare(key_col, bv)
        if not o.ascending:
            lt = ~(lt | eq)
        # null handling: null vs value ordering by nulls_first
        knull = ~key_col.validity
        bnull = ~bv.validity
        nf = o.resolved_nulls_first
        lt = jnp.where(knull & ~bnull, nf, lt)
        lt = jnp.where(~knull & bnull, not nf, lt)
        eqv = jnp.where(knull | bnull, knull & bnull, eq)
        lt_all = lt_all | (eq_all & lt)
        eq_all = eq_all & eqv
    return lt_all | eq_all


def _broadcast_row(col, row: int, cap: int):
    from spark_rapids_tpu.columnar.vector import ColumnVector
    data = jnp.broadcast_to(col.data[row:row + 1], (cap,) +
                            col.data.shape[1:])
    validity = jnp.broadcast_to(col.validity[row:row + 1], (cap,))
    lengths = None if col.lengths is None else jnp.broadcast_to(
        col.lengths[row:row + 1], (cap,))
    return ColumnVector(col.dtype, data, validity, lengths)
