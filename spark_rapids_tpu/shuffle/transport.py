"""Shuffle transport SPI + control protocol.

Reference: `RapidsShuffleTransport.scala:38-659` — the pluggable transport
trait (`makeClient`/`makeServer`, bounce-buffer pools, inflight-bytes
throttle, `Transaction` lifecycle) and the FlatBuffers control messages
(`ShuffleMetadataRequest/Response.fbs`, `ShuffleTransferRequest.fbs`).
The reference loads the implementation reflectively by class name
(`spark.rapids.shuffle.transport.class`); `make_transport` does the same.

TPU redesign notes: UCX tag-matching RDMA becomes two lanes —
intra-slice exchanges ride XLA collectives (parallel/collective_exchange),
while this SPI carries the DCN/cross-host lane and local-mode loopback:
a two-phase pull (metadata then data) of serialized batches staged through
fixed-size bounce buffers, exactly the reference's protocol shape.

Wire format (length-prefixed frames):
  control frame: u32 len | u8 kind | json payload
  data frame:    u32 len | u8 DATA | u64 table_id | u32 seq | bytes
"""
from __future__ import annotations

import dataclasses
import enum
import importlib
import json
import struct
import zlib
import threading
from typing import Callable, Optional, Sequence

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.memory.buffer import BufferId, TableMeta


class MsgKind(enum.IntEnum):
    METADATA_REQUEST = 1
    METADATA_RESPONSE = 2
    TRANSFER_REQUEST = 3
    TRANSFER_RESPONSE = 4
    DATA = 5


@dataclasses.dataclass(frozen=True)
class BlockIdMsg:
    """One shuffle block coordinate (shuffle_id, map_id, partition)."""
    shuffle_id: int
    map_id: int
    partition: int


@dataclasses.dataclass(frozen=True)
class TableMetaMsg:
    """Wire TableMeta (reference ShuffleCommon.fbs TableMeta)."""
    table_id: int
    shuffle_id: int
    map_id: int
    partition: int
    num_rows: int
    size_bytes: int
    schema_fields: tuple  # ((name, dtype_value, nullable), ...)

    @staticmethod
    def of(bid: BufferId, meta: TableMeta) -> "TableMetaMsg":
        return TableMetaMsg(
            bid.table_id, bid.shuffle_id, bid.map_id, bid.partition,
            meta.num_rows, meta.size_bytes,
            tuple((f.name, f.dtype.id.value, f.nullable)
                  for f in meta.schema.fields))

    def buffer_id(self) -> BufferId:
        return BufferId(self.table_id, self.shuffle_id, self.map_id,
                        self.partition)

    def table_meta(self) -> TableMeta:
        schema = T.Schema(tuple(
            T.Field(n, T.DataType(T.TypeId(d)), nl)
            for n, d, nl in self.schema_fields))
        return TableMeta(schema, self.num_rows, self.size_bytes)

    @property
    def is_degenerate(self) -> bool:
        return self.size_bytes == 0


# -- frame encode/decode ------------------------------------------------------
def encode_control(kind: MsgKind, payload: dict) -> bytes:
    body = json.dumps(payload, separators=(",", ":")).encode()
    return struct.pack("<IB", len(body) + 1, int(kind)) + body


class WireCorruption(Exception):
    """A DATA frame failed its payload CRC — the chunk was damaged in
    flight; the fetch transaction fails and the bounded-retry path
    re-requests it."""


def encode_data(table_id: int, seq: int, chunk: bytes,
                codec_id: int = -1, raw_len: int = 0) -> bytes:
    """DATA frame; codec_id/raw_len play the reference's
    CodecBufferDescriptor role (ShuffleCommon.fbs): -1 = uncompressed,
    else the payload is `codec_id`-compressed and inflates to raw_len.
    A crc32 of the payload rides in the header so wire damage is
    detected at the receiver (the spill files carry the same framing)."""
    return struct.pack("<IBQIBQI", len(chunk) + 26, int(MsgKind.DATA),
                       table_id, seq, codec_id + 1, raw_len,
                       zlib.crc32(chunk) & 0xFFFFFFFF) + chunk


def decode_frame(frame: bytes) -> tuple[MsgKind, object]:
    kind = MsgKind(frame[0])
    if kind == MsgKind.DATA:
        table_id, seq, codec_byte, raw_len, crc = struct.unpack_from(
            "<QIBQI", frame, 1)
        chunk = frame[26:]
        if zlib.crc32(chunk) & 0xFFFFFFFF != crc:
            raise WireCorruption(
                f"DATA frame for table {table_id} seq {seq >> 1} failed "
                f"crc32")
        return kind, (table_id, seq, chunk, codec_byte - 1, raw_len)
    return kind, json.loads(frame[1:].decode())


def meta_request(blocks: Sequence[BlockIdMsg]) -> bytes:
    return encode_control(MsgKind.METADATA_REQUEST, {
        "blocks": [[b.shuffle_id, b.map_id, b.partition] for b in blocks]})


def meta_response(metas: Sequence[TableMetaMsg]) -> bytes:
    return encode_control(MsgKind.METADATA_RESPONSE, {
        "tables": [[m.table_id, m.shuffle_id, m.map_id, m.partition,
                    m.num_rows, m.size_bytes, list(map(list,
                                                       m.schema_fields))]
                   for m in metas]})


def parse_meta_response(payload: dict) -> list[TableMetaMsg]:
    return [TableMetaMsg(t[0], t[1], t[2], t[3], t[4], t[5],
                         tuple(tuple(f) for f in t[6]))
            for t in payload["tables"]]


def transfer_request(table_ids: Sequence[int]) -> bytes:
    return encode_control(MsgKind.TRANSFER_REQUEST,
                          {"table_ids": list(table_ids)})


# ---------------------------------------------------------------------------
class BounceBufferManager:
    """Fixed pool of staging buffers (reference
    BounceBufferManager.scala:55-128: slices one registered buffer into N
    fixed bounce buffers with blocking acquire)."""

    def __init__(self, buffer_size: int, count: int):
        self.buffer_size = buffer_size
        self._free = [bytearray(buffer_size) for _ in range(count)]
        self._cv = threading.Condition()

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> Optional[bytearray]:
        with self._cv:
            while not self._free:
                if not blocking:
                    return None
                if not self._cv.wait(timeout):
                    return None
            return self._free.pop()

    def release(self, buf: bytearray) -> None:
        with self._cv:
            self._free.append(buf)
            self._cv.notify()

    @property
    def free_count(self) -> int:
        with self._cv:
            return len(self._free)


class InflightLimiter:
    """Byte-budget throttle for outstanding receives (reference
    maxReceiveInflightBytes, RapidsShuffleClient.scala:108)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._used = 0
        self._cv = threading.Condition()

    def acquire(self, nbytes: int, timeout: Optional[float] = None) -> bool:
        nbytes = min(nbytes, self.max_bytes)
        with self._cv:
            while self._used + nbytes > self.max_bytes:
                if not self._cv.wait(timeout):
                    return False
            self._used += nbytes
            return True

    def release(self, nbytes: int) -> None:
        nbytes = min(nbytes, self.max_bytes)
        with self._cv:
            self._used -= nbytes
            self._cv.notify_all()


# ---------------------------------------------------------------------------
class TransactionStatus(enum.Enum):
    SUCCESS = "success"
    ERROR = "error"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class Transaction:
    """Completed-exchange record (reference Transaction trait :311-380).
    `corrupt` marks a failure caused by a DATA-frame CRC mismatch
    (WireCorruption), so the client's retry loop can count detected
    wire damage separately from plain connection loss."""
    status: TransactionStatus
    error: Optional[str] = None
    bytes_transferred: int = 0
    corrupt: bool = False


class Connection:
    """Client-side connection to one peer executor.

    `request` performs a control round-trip; `fetch` streams the DATA
    frames of the requested tables to `on_chunk(table_id, seq, bytes,
    is_last, codec_id, raw_len)` — the bounce-buffer receive path
    (codec_id -1 = uncompressed payload)."""

    def request(self, frame: bytes) -> tuple[MsgKind, object]:
        raise NotImplementedError

    def fetch(self, table_ids: Sequence[int],
              on_chunk: Callable[[int, int, bytes, bool], None]
              ) -> Transaction:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ShuffleTransport:
    """Transport SPI (reference RapidsShuffleTransport trait)."""

    def __init__(self, conf: C.RapidsConf):
        self.conf = conf
        self.receive_limiter = InflightLimiter(
            conf[C.SHUFFLE_MAX_RECV_INFLIGHT])
        self.send_bounce = BounceBufferManager(
            conf[C.SHUFFLE_BOUNCE_BUFFER_SIZE],
            conf[C.SHUFFLE_BOUNCE_BUFFER_COUNT])

    def make_server(self, executor_id: str, request_handler) -> "object":
        """Start serving this executor's shuffle data.  `request_handler`
        exposes handle_metadata_request(blocks)->[TableMetaMsg] and
        acquire_buffer_bytes(table_id)->bytes."""
        raise NotImplementedError

    def can_reach(self, address: str) -> bool:
        """Whether this transport instance can open `address` from THIS
        process (loopback addresses are per-process)."""
        return True

    def make_client(self, peer_address: str) -> Connection:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


def make_transport(conf: Optional[C.RapidsConf] = None) -> ShuffleTransport:
    """Reflective load by conf class name (reference
    RapidsShuffleTransport.makeTransport, RapidsConf.scala:592)."""
    conf = conf or C.get_active_conf()
    path = conf[C.SHUFFLE_TRANSPORT_CLASS]
    mod_name, cls_name = path.rsplit(".", 1)
    cls = getattr(importlib.import_module(mod_name), cls_name)
    return cls(conf)
