"""Accelerated shuffle manager: caching writer/reader over the spillable
catalog + transport.

Reference: `RapidsShuffleInternalManager.scala` — `RapidsCachingWriter`
(map output stays in the device store, spillable; MapStatus advertises the
transport address), `RapidsCachingReader` (local partitions read straight
from the catalog; remote ones fetched via the transport), and
`RapidsShuffleIterator` (fetch orchestration, semaphore on materialize,
timeout -> FetchFailed).

The driver-side MapOutputRegistry plays Spark's MapOutputTracker: map
task -> (executor, per-partition sizes).  Executor environments register
here so local mode and tests can run many "executors" in one process —
multi-executor behavior without a cluster, like the reference's
mocked-transport suites.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Iterator, Optional, Sequence

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.buffer import (
    BufferId, DegenerateBuffer, degenerate_meta)
from spark_rapids_tpu.memory.env import ResourceEnv
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.spill_priorities import (
    OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
from spark_rapids_tpu.shuffle.catalog import (
    ShuffleBufferCatalog, ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client_server import (
    FetchFailedError, ShuffleClient, ShuffleReceiveHandler, ShuffleServer)
from spark_rapids_tpu.shuffle.transport import BlockIdMsg, make_transport


class MapStatus:
    """Map-task completion record (reference MapStatus with the transport
    address in BlockManagerId.topologyInfo).  Carries BOTH the loopback
    and the wire (TCP) address: in-process readers take the loop lane,
    readers in another process fall back to the wire — how the reference
    serves local vs UCX-remote blocks from one MapStatus."""

    def __init__(self, executor_id: str, address: str,
                 partition_sizes: list[int],
                 tcp_address: str | None = None,
                 replicas: Optional[list[tuple]] = None):
        self.executor_id = executor_id
        self.address = address
        self.partition_sizes = partition_sizes
        self.tcp_address = tcp_address
        #: backup executors holding a serialized copy of this map
        #: output (spark.rapids.shuffle.replication.factor >= 2):
        #: [(executor_id, loop_address, tcp_address), ...].  Hedged
        #: fetches race a replica against a slow primary; recovery
        #: promotes one to primary on peer loss instead of recomputing.
        self.replicas = list(replicas or [])
        #: registry epoch this status was registered under (stamped by
        #: MapOutputRegistry.register; stale re-registrations from a
        #: superseded map run are rejected)
        self.epoch = 0

    def addresses(self) -> list[str]:
        return [a for a in (self.address, self.tcp_address) if a]

    def hedge_address(self, transport, health=None) -> Optional[str]:
        """A usable replica address to hedge a slow primary fetch
        against: reachable on this transport and not blacklisted, or
        None when no replica qualifies."""
        for _eid, addr, tcp in self.replicas:
            for a in (addr, tcp):
                if not a or not transport.can_reach(a):
                    continue
                if health is not None and health.is_blacklisted(a):
                    continue
                return a
        return None

    def reachable_address(self, transport, health=None) -> str:
        """Pick the lane to fetch from: loopback when it resolves in
        this process, the wire otherwise — and when a PeerHealth
        tracker is supplied, route around blacklisted addresses before
        wasting their full timeout (the flapping-peer diet)."""
        cands = self.addresses()
        reach = [a for a in cands if transport.can_reach(a)] or cands
        if health is not None:
            ok = [a for a in reach if not health.is_blacklisted(a)]
            if ok:
                reach = ok
        return reach[0]


class StaleMapStatusError(Exception):
    """A MapStatus registration carried a superseded epoch: the shuffle's
    outputs were invalidated (peer loss) after the producing map run
    started, so its result must not be served to reducers."""


class MapOutputRegistry:
    """Driver-side map output tracker (process-global).  Plays Spark's
    MapOutputTracker INCLUDING the fault-recovery surface: per-shuffle
    epochs (bumped on every invalidation, so stale registrations are
    rejected), executor/address invalidation (the FetchFailed ->
    unregisterMapOutput path), and an expected-map-count so a reduce
    read over an incomplete output set fails loudly instead of
    returning partial data."""

    _lock = threading.Lock()
    _outputs: dict[int, dict[int, MapStatus]] = {}
    _epochs: dict[int, int] = {}
    _expected: dict[int, int] = {}

    @classmethod
    def register(cls, shuffle_id: int, map_id: int,
                 status: MapStatus, epoch: Optional[int] = None,
                 first_wins: bool = False) -> None:
        """`first_wins` (speculative attempts) makes the registration
        atomic-or-reject: if the map output is already committed at the
        current epoch, the caller LOST the race and must not publish —
        the same StaleMapStatusError contract recovery's epoch guard
        uses, so a losing attempt frees its buffers and stands down."""
        with cls._lock:
            cur = cls._epochs.get(shuffle_id, 0)
            if epoch is not None and epoch != cur:
                raise StaleMapStatusError(
                    f"map output {shuffle_id}/{map_id} registered at "
                    f"epoch {epoch} but the shuffle is at epoch {cur}: "
                    f"the producing map run was superseded by a "
                    f"recovery invalidation")
            outs = cls._outputs.setdefault(shuffle_id, {})
            if first_wins and map_id in outs:
                err = StaleMapStatusError(
                    f"map output {shuffle_id}/{map_id} was already "
                    f"committed by a faster attempt (first-wins "
                    f"speculation): this attempt lost the race")
                err.race_lost = True
                raise err
            status.epoch = cur
            outs[map_id] = status

    @classmethod
    def outputs_for(cls, shuffle_id: int) -> dict[int, MapStatus]:
        with cls._lock:
            return dict(cls._outputs.get(shuffle_id, {}))

    @classmethod
    def epoch(cls, shuffle_id: int) -> int:
        with cls._lock:
            return cls._epochs.get(shuffle_id, 0)

    @classmethod
    def set_expected_maps(cls, shuffle_id: int, num_maps: int) -> None:
        """Record how many map tasks the shuffle has, arming the
        missing-output guard in `missing_maps`."""
        with cls._lock:
            cls._expected[shuffle_id] = num_maps

    @classmethod
    def missing_maps(cls, shuffle_id: int) -> list[int]:
        """Map ids whose outputs are invalidated-and-not-yet-recomputed
        (empty when the expected count was never declared)."""
        with cls._lock:
            n = cls._expected.get(shuffle_id)
            if n is None:
                return []
            outs = cls._outputs.get(shuffle_id, {})
            return [m for m in range(n) if m not in outs]

    @classmethod
    def invalidate_address(cls, shuffle_id: int, address: str
                           ) -> dict[int, MapStatus]:
        """Drop every map output owned by the executor(s) advertising
        `address` and bump the shuffle's epoch.  Returns the removed
        {map_id: MapStatus} so recovery can recompute exactly those."""
        with cls._lock:
            outs = cls._outputs.get(shuffle_id, {})
            execs = {s.executor_id for s in outs.values()
                     if address in (s.address, s.tcp_address)}
            lost = {m: s for m, s in outs.items()
                    if s.executor_id in execs}
            for m in lost:
                del outs[m]
            if lost:
                cls._epochs[shuffle_id] = \
                    cls._epochs.get(shuffle_id, 0) + 1
            return lost

    @classmethod
    def invalidate_others(cls, shuffle_id: int, keep_executor_id: str
                          ) -> dict[int, MapStatus]:
        """Unattributable failure fallback: drop every map output NOT
        owned by `keep_executor_id` (the reducing executor itself) and
        bump the epoch — a conservative whole-stage invalidation."""
        with cls._lock:
            outs = cls._outputs.get(shuffle_id, {})
            lost = {m: s for m, s in outs.items()
                    if s.executor_id != keep_executor_id}
            for m in lost:
                del outs[m]
            if lost:
                cls._epochs[shuffle_id] = \
                    cls._epochs.get(shuffle_id, 0) + 1
            return lost

    @classmethod
    def unregister_shuffle(cls, shuffle_id: int) -> None:
        with cls._lock:
            cls._outputs.pop(shuffle_id, None)
            cls._epochs.pop(shuffle_id, None)
            cls._expected.pop(shuffle_id, None)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._outputs.clear()
            cls._epochs.clear()
            cls._expected.clear()


class TpuShuffleManager:
    """Executor-side shuffle environment (reference GpuShuffleEnv +
    RapidsShuffleInternalManagerBase)."""

    # RLock: get_or_create constructs under the lock and the
    # constructor re-acquires it to register itself
    _registry_lock = threading.RLock()
    _managers: dict[str, "TpuShuffleManager"] = {}

    def __init__(self, executor_id: str,
                 env: Optional[ResourceEnv] = None,
                 conf: Optional[C.RapidsConf] = None):
        self.executor_id = executor_id
        self.conf = conf or C.get_active_conf()
        self.env = env or ResourceEnv.get()
        self.shuffle_catalog = ShuffleBufferCatalog(self.env.catalog)
        self.received_catalog = ShuffleReceivedBufferCatalog(
            self.env.catalog)
        self.transport = make_transport(self.conf)
        from spark_rapids_tpu.shuffle.compression import codec_from_conf
        self.server = ShuffleServer(self.shuffle_catalog, self.transport,
                                    codec=codec_from_conf(self.conf),
                                    executor_id=executor_id)
        handle = self.transport.make_server(executor_id, self.server)
        self.loop_address = handle.loop_address
        self.tcp_address = handle.tcp_address
        with TpuShuffleManager._registry_lock:
            TpuShuffleManager._managers[executor_id] = self

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def get(cls, executor_id: str) -> Optional["TpuShuffleManager"]:
        with cls._registry_lock:
            return cls._managers.get(executor_id)

    @classmethod
    def live_executors(cls) -> int:
        """Registered in-process shuffle executors (telemetry gauge)."""
        with cls._registry_lock:
            return len(cls._managers)

    @classmethod
    def get_or_create(cls, executor_id: str,
                      env: Optional[ResourceEnv] = None,
                      conf: Optional[C.RapidsConf] = None
                      ) -> "TpuShuffleManager":
        """ATOMIC get-or-create.  The old `get(id) or Manager(id)`
        idiom raced under concurrent queries: two threads both
        constructed 'local-1', the second's server silently replaced
        the first's loopback registration, and every map output the
        first query had registered resolved to a server whose catalog
        never saw that shuffle — which answered fetches with ZERO
        tables, a clean-looking empty read, i.e. silent partial data."""
        with cls._registry_lock:
            m = cls._managers.get(executor_id)
            if m is None:
                m = TpuShuffleManager(executor_id, env, conf)
            return m

    def close(self) -> None:
        self.transport.shutdown()
        with TpuShuffleManager._registry_lock:
            TpuShuffleManager._managers.pop(self.executor_id, None)

    def register_shuffle(self, shuffle_id: int) -> None:
        self.shuffle_catalog.register_shuffle(shuffle_id)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        self.shuffle_catalog.unregister_shuffle(shuffle_id)
        MapOutputRegistry.unregister_shuffle(shuffle_id)

    # -- write side ----------------------------------------------------------
    def get_writer(self, shuffle_id: int, map_id: int,
                   replicas: Sequence["TpuShuffleManager"] = ()
                   ) -> "CachingShuffleWriter":
        return CachingShuffleWriter(self, shuffle_id, map_id,
                                    replicas=replicas)

    # -- read side -----------------------------------------------------------
    _attempt_ids = itertools.count(1)

    def get_reader(self, shuffle_id: int, partition: int,
                   task_attempt_id: Optional[int] = None,
                   timeout: float = 30.0,
                   with_map_ids: bool = False,
                   metrics=None) -> Iterator:
        """Iterate one reduce partition's batches.  `with_map_ids`
        yields (map_id, batch) tuples instead, so a recovery-aware
        consumer can re-establish deterministic map order after a
        recompute moved outputs between executors.  `metrics` (the
        owning exchange's MetricSet) is charged the wire
        compressed/uncompressed byte counters so codec choice shows in
        EXPLAIN-with-metrics."""
        if task_attempt_id is None:
            # unique per reader so per-task receive cleanup cannot free a
            # concurrent reader's buffers
            task_attempt_id = next(TpuShuffleManager._attempt_ids)
        it = CachingShuffleReader(
            self, shuffle_id, partition, task_attempt_id, timeout,
            metrics=metrics).read()
        if with_map_ids:
            return it
        return (b for _, b in it)


class CachingShuffleWriter:
    """Stores each partition's batch in the device store via the shuffle
    catalog; degenerate (rows-only) batches store metadata alone
    (reference RapidsCachingWriter.write :74-191).

    With `replicas` (spark.rapids.shuffle.replication.factor >= 2) each
    partition's serialized payload is additionally pushed into every
    replica executor's catalog at write time — the MapStatus advertises
    them, so hedged fetches can race a replica against a slow primary
    and recovery can promote one on peer loss without recompute.
    Cleanup is attempt-scoped (exact buffer ids), so a losing
    speculative attempt's abort can never free a winner's buffers that
    share the same (map_id, partition) slot."""

    def __init__(self, manager: TpuShuffleManager, shuffle_id: int,
                 map_id: int,
                 replicas: Sequence[TpuShuffleManager] = ()):
        self.manager = manager
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.replicas = [r for r in replicas if r is not manager]
        self._sizes: dict[int, int] = {}
        #: every buffer this writer minted, per owning shuffle catalog
        #: (primary + replicas) — abort removes exactly these
        self._written: list[tuple] = []
        self.replicated_bytes = 0

    def write_partition(self, partition: int, batch: ColumnarBatch) -> None:
        cat = self.manager.shuffle_catalog
        bid = cat.next_shuffle_buffer_id(self.shuffle_id, self.map_id,
                                         partition)
        self._written.append((cat, bid))
        if batch.num_columns == 0:
            meta = degenerate_meta(batch.schema, batch.num_rows)
            cat.catalog.register(DegenerateBuffer(bid, meta))
            self._sizes[partition] = 0
            for r in self.replicas:
                rbid = r.shuffle_catalog.next_shuffle_buffer_id(
                    self.shuffle_id, self.map_id, partition)
                r.shuffle_catalog.catalog.register(
                    DegenerateBuffer(rbid, meta))
                self._written.append((r.shuffle_catalog, rbid))
            return
        buf = self.manager.env.device_store.add_batch(
            bid, batch, OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
        self._sizes[partition] = self._sizes.get(partition, 0) + \
            buf.size_bytes
        if self.replicas:
            self._replicate(partition, batch)

    def _replicate(self, partition: int, batch: ColumnarBatch) -> None:
        """Push one partition slice's serialized payload to every
        replica executor's host store (serialized once, shared)."""
        from spark_rapids_tpu.columnar.serde import serialize_batch
        from spark_rapids_tpu.memory.buffer import meta_for_batch
        from spark_rapids_tpu.utils import movement as MV
        from spark_rapids_tpu.utils import residency as RES
        blob = serialize_batch(batch)
        meta = meta_for_batch(batch)
        for r in self.replicas:
            rbid = r.shuffle_catalog.next_shuffle_buffer_id(
                self.shuffle_id, self.map_id, partition)
            # provenance: replica copies are not the primary map
            # output — their residency shows up under their own site
            with RES.site_scope("shuffle-replica"):
                r.env.host_store.add_blob(rbid, blob, meta)
            self._written.append((r.shuffle_catalog, rbid))
            self.replicated_bytes += len(blob)
        if MV.ledger() is not None:
            MV.record(MV.EDGE_WIRE, len(blob) * len(self.replicas),
                      site="replicate")

    def commit(self, num_partitions: int,
               epoch: Optional[int] = None,
               first_wins: bool = False) -> MapStatus:
        """Register the map output.  `epoch` (recovery recomputes only)
        pins the registration to the registry epoch the recompute was
        planned under: if another invalidation raced in, the commit is
        rejected (StaleMapStatusError) and the written buffers freed —
        a superseded map run must never serve reducers.  `first_wins`
        (speculative attempts) additionally rejects the commit when a
        sibling attempt already published this map output."""
        status = MapStatus(
            self.manager.executor_id, self.manager.loop_address,
            [self._sizes.get(p, 0) for p in range(num_partitions)],
            tcp_address=self.manager.tcp_address,
            replicas=[(r.executor_id, r.loop_address, r.tcp_address)
                      for r in self.replicas])
        try:
            MapOutputRegistry.register(self.shuffle_id, self.map_id,
                                       status, epoch=epoch,
                                       first_wins=first_wins)
        except StaleMapStatusError as e:
            self.abort()
            if not getattr(e, "race_lost", False):
                # epoch-stale (superseded by a recovery invalidation):
                # also sweep the invalidated OLDER run's buffers for
                # this map task, which nothing else will free until
                # unregister.  A first-wins race loss must NOT sweep —
                # the winning sibling's buffers share this slot.
                self.manager.shuffle_catalog.remove_task_buffers(
                    self.shuffle_id, self.map_id)
            raise
        return status

    def abort(self) -> None:
        """Failed-task cleanup (reference :159-167): frees exactly the
        buffers THIS writer minted, across primary + replica catalogs."""
        by_cat: dict[int, tuple] = {}
        for cat, bid in self._written:
            by_cat.setdefault(id(cat), (cat, []))[1].append(bid)
        for cat, bids in by_cat.values():
            cat.remove_buffers(bids)
        self._written.clear()


class _IteratorHandler(ShuffleReceiveHandler):
    def __init__(self, q: "queue.Queue", current: dict,
                 wire_stats: Optional[dict] = None):
        self.q = q
        #: mutable cell the fetch loop updates with the peer address it
        #: is currently draining, so errors carry the REAL peer (the
        #: old literal "remote" hid which executor to invalidate)
        self.current = current
        #: {"compressed": n, "raw": n, "corruptions": n} accumulator
        #: the owning reader charges to the exchange's compression /
        #: wire-integrity metrics
        self.wire_stats = wire_stats
        self.expected = 0

    def start(self, expected_batches: int) -> None:
        self.expected = expected_batches

    def batch_received(self, bid: BufferId) -> None:
        self.q.put(("batch", bid))

    def buffer_received(self, wire_bytes: int, raw_bytes: int) -> None:
        if self.wire_stats is not None:
            self.wire_stats["compressed"] += wire_bytes
            self.wire_stats["raw"] += raw_bytes

    def corruption_detected(self) -> None:
        if self.wire_stats is not None:
            self.wire_stats["corruptions"] = \
                self.wire_stats.get("corruptions", 0) + 1

    def transfer_error(self, message: str) -> None:
        self.q.put(("error", (self.current.get("addr"), message)))


class _StagingHandler(ShuffleReceiveHandler):
    """Buffers one hedged attempt's results instead of streaming them:
    first-wins hedging must deliver EITHER the primary's batches OR the
    replica's, never an interleaving, so each attempt stages until it
    completes and only the winner's buffers reach the real handler."""

    def __init__(self):
        self.bids: list[BufferId] = []
        self.wire = 0
        self.raw = 0
        self.corruptions = 0

    def start(self, expected_batches: int) -> None:
        pass

    def batch_received(self, bid: BufferId) -> None:
        self.bids.append(bid)

    def buffer_received(self, wire_bytes: int, raw_bytes: int) -> None:
        self.wire += wire_bytes
        self.raw += raw_bytes

    def corruption_detected(self) -> None:
        self.corruptions += 1

    def transfer_error(self, message: str) -> None:
        pass  # the attempt's exception carries the failure


class CachingShuffleReader:
    """Partitions the fetch list into local (catalog) and remote
    (transport) blocks (reference RapidsCachingReader.read:61-100);
    remote fetches run on a fetch thread while the task consumes."""

    def __init__(self, manager: TpuShuffleManager, shuffle_id: int,
                 partition: int, task_attempt_id: int, timeout: float,
                 metrics=None):
        self.manager = manager
        self.shuffle_id = shuffle_id
        self.partition = partition
        self.task_attempt_id = task_attempt_id
        self.timeout = timeout
        self.metrics = metrics
        #: wire bytes this reader's remote fetches pulled, compressed
        #: vs uncompressed, plus detected wire corruptions — charged to
        #: the exchange on read completion
        self.wire_stats = {"compressed": 0, "raw": 0, "corruptions": 0}
        # captured here (the consuming task's thread, session conf
        # installed) because the fetch worker is a raw thread with no
        # conf propagation
        self.conf = C.get_active_conf()

    def read(self) -> Iterator[tuple[int, ColumnarBatch]]:
        from spark_rapids_tpu.shuffle.recovery import PeerHealth
        health = PeerHealth.get()
        missing = MapOutputRegistry.missing_maps(self.shuffle_id)
        if missing:
            # invalidated-and-not-yet-recomputed outputs: reading the
            # survivors would return PARTIAL data — surface the
            # stage-retry signal instead (recovery recomputes, then the
            # retried read sees a complete set)
            raise FetchFailedError(
                "unregistered", None,
                f"shuffle {self.shuffle_id} is missing map outputs "
                f"{missing} (superseded by a recovery invalidation)")
        outputs = MapOutputRegistry.outputs_for(self.shuffle_id)
        hedging = bool(self.conf[C.SHUFFLE_HEDGE_ENABLED])
        local_bids: list[BufferId] = []
        # groups keyed (primary address, hedge replica address | None):
        # a hedged group's blocks must all share one replica peer so
        # the hedge attempt is a single fetch to a single server
        remote: dict[tuple, list[BlockIdMsg]] = {}
        for map_id, status in sorted(outputs.items()):
            if status.partition_sizes[self.partition] == 0 and \
                    not self._has_degenerate(status, map_id):
                continue
            if status.executor_id == self.manager.executor_id:
                local_bids.extend(
                    self.manager.shuffle_catalog.blocks_for_partition(
                        self.shuffle_id, self.partition, [map_id]))
            else:
                addr = status.reachable_address(self.manager.transport,
                                                health)
                hedge_addr = status.hedge_address(
                    self.manager.transport, health) if hedging else None
                if hedge_addr == addr:
                    hedge_addr = None
                remote.setdefault((addr, hedge_addr), []).append(
                    BlockIdMsg(self.shuffle_id, map_id, self.partition))
        # maps whose advertised size for THIS partition is nonzero MUST
        # deliver at least one batch: a peer answering "no such table"
        # for data the registry advertises (e.g. a replaced/rebuilt
        # server whose catalog never saw the shuffle) must surface as a
        # FetchFailed for recovery — never a clean-looking empty read
        # (silent partial data)
        expect_nonzero = {
            m: s for m, s in outputs.items()
            if s.partition_sizes[self.partition] > 0}
        delivered: set = set()
        try:
            # local blocks: straight catalog reads with the semaphore held
            sem = TpuSemaphore.get()
            for bid in local_bids:
                with self.manager.env.catalog.acquired(bid) as buf:
                    sem.acquire_if_necessary()
                    delivered.add(bid.map_id)
                    yield bid.map_id, buf.get_columnar_batch()
            # remote: issue fetches per peer, consume as they land
            for map_id, batch in self._fetch_remote(remote, sem):
                delivered.add(map_id)
                yield map_id, batch
            silent = sorted(set(expect_nonzero) - delivered)
            if silent:
                st = expect_nonzero[silent[0]]
                addr = st.reachable_address(self.manager.transport,
                                            health)
                raise FetchFailedError(
                    addr,
                    BlockIdMsg(self.shuffle_id, silent[0],
                               self.partition),
                    f"maps {silent} advertise data for partition "
                    f"{self.partition} but the fetch returned none "
                    f"(peer serving a catalog without this shuffle?)")
        finally:
            if self.metrics is not None:
                from spark_rapids_tpu.utils import metrics as M
                if self.wire_stats["compressed"]:
                    self.metrics.add(M.SHUFFLE_COMPRESSED_BYTES,
                                     self.wire_stats["compressed"])
                    self.metrics.add(M.SHUFFLE_RAW_BYTES,
                                     self.wire_stats["raw"])
                if self.wire_stats["corruptions"]:
                    self.metrics.add(M.NUM_WIRE_CORRUPTIONS,
                                     self.wire_stats["corruptions"])
            # received buffers live only for this task (reference
            # ShuffleReceivedBufferCatalog per-task cleanup)
            self.manager.received_catalog.release_task(
                self.task_attempt_id)

    def _has_degenerate(self, status: MapStatus, map_id: int) -> bool:
        # degenerate batches report size 0 but still must be fetched for
        # their row counts; local catalog lookup answers cheaply
        if status.executor_id != self.manager.executor_id:
            return True  # conservatively ask the peer
        return bool(self.manager.shuffle_catalog.blocks_for_partition(
            self.shuffle_id, self.partition, [map_id]))

    def _fetch_one(self, address: str, blocks, handler_,
                   attempt_id: int) -> None:
        """One fetch of `blocks` from `address` into `handler_` under
        the given receive-cleanup attempt id."""
        conn = self.manager.transport.make_client(address)
        client = ShuffleClient(
            conn, self.manager.transport,
            self.manager.received_catalog,
            self.manager.env.host_store, address, conf=self.conf)
        try:
            client.fetch_blocks(blocks, attempt_id, handler_)
        finally:
            # the client may have swapped in a fresh connection on a
            # retry: close whatever it currently holds, not the
            # original handle
            client.connection.close()

    def _fetch_remote(self, remote: dict[tuple, list[BlockIdMsg]],
                      sem) -> Iterator[ColumnarBatch]:
        if not remote:
            return
        from spark_rapids_tpu.shuffle.recovery import PeerHealth
        from spark_rapids_tpu.utils import profile as P
        health = PeerHealth.get()
        q: "queue.Queue" = queue.Queue()
        current = {"addr": next(iter(remote))[0]}
        handler = _IteratorHandler(q, current, self.wire_stats)
        errors: list[BaseException] = []
        done = threading.Event()
        # captured on the consuming thread: the fetch worker's spans
        # (ShuffleClient fetch ranges) parent under this reader's scope
        # and its conf / cancellation / events reach the RIGHT query
        from spark_rapids_tpu.exec import scheduler as S
        span_ref = P.current_ref()
        qc = S.current()

        def fetch_all():
            try:
                # raw worker thread: install the consuming task's conf
                # so watchdog deadlines / fault injection resolve to
                # the session's values, not registry defaults
                with S.scoped(qc), C.session(self.conf), \
                        P.attach(span_ref):
                    for (address, hedge_addr), blocks in remote.items():
                        current["addr"] = address
                        if hedge_addr is not None:
                            self._hedged_group(address, hedge_addr,
                                               blocks, handler, health)
                        else:
                            self._fetch_one(address, blocks, handler,
                                            self.task_attempt_id)
                            health.record_success(address)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                q.put(("fatal", (current.get("addr"), str(e))))
            finally:
                done.set()
                q.put(("done", None))

        def _first_block(addr):
            for (a, _h), blocks in remote.items():
                if a == addr and blocks:
                    return blocks[0]
            return None

        t = threading.Thread(target=fetch_all, daemon=True,
                             name="tpu-shuffle-fetch")
        t.start()
        from spark_rapids_tpu.utils import watchdog as W
        hb = W.heartbeat(f"shuffle-read:s{self.shuffle_id}"
                         f"p{self.partition}", kind="task",
                         conf=self.conf)
        try:
            yield from self._consume(q, current, errors, done,
                                     _first_block, hb, sem)
        finally:
            hb.close()

    def _hedged_group(self, address: str, hedge_addr: str, blocks,
                      handler, health) -> None:
        """First-wins hedged fetch of one block group (runs on the
        fetch worker thread): the primary attempt stages its results;
        past the hedge delay (quantile of observed fetch latencies,
        floored by shuffle.hedge.delayMs) — or on early primary
        failure — the same blocks are requested from the replica peer.
        The first complete, uncorrupted attempt's buffers are adopted
        under the reader's attempt id; the loser is cancelled via its
        AttemptToken, its staged buffers freed, and its wire bytes
        reclassified to the ledger's wire:wasted site."""
        from spark_rapids_tpu.exec import scheduler as S
        from spark_rapids_tpu.shuffle.client_server import hedge_delay_s
        from spark_rapids_tpu.utils import metrics as M
        from spark_rapids_tpu.utils import movement as MV
        from spark_rapids_tpu.utils import profile as P
        from spark_rapids_tpu.utils import watchdog as W
        addrs = {"primary": address, "hedge": hedge_addr}
        staging = {n: _StagingHandler() for n in addrs}
        attempt_ids = {n: next(TpuShuffleManager._attempt_ids)
                       for n in addrs}
        parent_tok = W.current_token()
        tokens = {n: W.AttemptToken(parent=parent_tok) for n in addrs}
        done = {n: threading.Event() for n in addrs}
        results: dict = {}
        threads: dict = {}
        qc = S.current()
        span_ref = P.current_ref()

        def run(name):
            try:
                with S.scoped(qc), C.session(self.conf), \
                        P.attach(span_ref), \
                        W.attempt_scope(tokens[name]):
                    self._fetch_one(addrs[name], blocks,
                                    staging[name], attempt_ids[name])
                results[name] = None
            except BaseException as e:  # noqa: BLE001
                results[name] = e
            finally:
                done[name].set()

        def start(name):
            t = threading.Thread(target=run, args=(name,), daemon=True,
                                 name=f"tpu-shuffle-hedge-{name}")
            threads[name] = t
            t.start()

        start("primary")
        delay = hedge_delay_s(self.conf)
        deadline = time.monotonic() + delay
        while not done["primary"].is_set():
            parent_tok.check()
            left = deadline - time.monotonic()
            if left <= 0:
                break
            done["primary"].wait(min(0.02, left))
        if not (done["primary"].is_set()
                and results.get("primary") is None):
            # primary straggling past the hedge delay (or already
            # failed): race the replica for the same blocks
            if self.metrics is not None:
                self.metrics.add(M.NUM_HEDGED_FETCHES, 1)
            P.event(P.EV_HEDGE_FIRED, address=address, replica=hedge_addr,
                    blocks=len(blocks), delay_ms=round(delay * 1e3, 1))
            start("hedge")
        # first complete, uncorrupted response wins
        winner = None
        while winner is None:
            parent_tok.check()
            settled = [n for n in threads if done[n].is_set()]
            ok = [n for n in settled if results.get(n) is None]
            if ok:
                # deterministic preference when both landed between
                # polls: the primary's payload (they are identical
                # serialized bytes, but the tie-break keeps hedge-win
                # counts meaningful)
                winner = "primary" if "primary" in ok else ok[0]
                break
            if len(settled) == len(threads):
                raise results.get("primary") or results.get("hedge")
            time.sleep(0.01)
        loser = next((n for n in threads if n != winner), None)
        if winner == "hedge" and self.metrics is not None:
            self.metrics.add(M.NUM_HEDGED_WINS, 1)
        if loser is not None:
            tokens[loser].cancel_race_lost(
                f"hedged fetch: {addrs[winner]} answered first")
        # adopt the winner's staged buffers under the reader's attempt
        # id (its release_task owns their cleanup now)
        st = staging[winner]
        for bid in self.manager.received_catalog.take_task(
                attempt_ids[winner]):
            self.manager.received_catalog.add_received(
                self.task_attempt_id, bid)
        if st.wire:
            handler.buffer_received(st.wire, st.raw)
        for _ in range(st.corruptions):
            handler.corruption_detected()
        for bid in st.bids:
            handler.batch_received(bid)
        health.record_success(addrs[winner])
        if loser is not None:
            # reap the loser: its waits are cancellable (bounded polls
            # + token checks), so the join is prompt
            threads[loser].join(timeout=10.0)
            if threads[loser].is_alive():
                import logging
                logging.getLogger("spark_rapids_tpu.shuffle").warning(
                    "hedged-fetch loser (%s) did not exit after "
                    "cancellation; skipping its buffer cleanup",
                    addrs[loser])
            else:
                lst = staging[loser]
                self.manager.received_catalog.release_task(
                    attempt_ids[loser])
                if lst.wire and MV.ledger() is not None:
                    site = ("send:loop"
                            if addrs[loser].startswith("loop://")
                            else "send:dcn")
                    MV.move(MV.EDGE_WIRE, lst.wire, site,
                            MV.SITE_WASTED, raw_bytes=lst.raw)

    def _consume(self, q, current, errors, done, _first_block, hb,
                 sem) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.utils import watchdog as W
        received = 0
        finished = False
        while True:
            # bounded-poll the fetch queue in small slices so a
            # watchdog cancellation is honored promptly; the overall
            # per-get timeout still FetchFails like before
            deadline = time.monotonic() + self.timeout
            while True:
                W.check_cancelled()
                try:
                    kind, payload = q.get(
                        timeout=min(0.1, max(0.0, deadline
                                             - time.monotonic())))
                    break
                except queue.Empty:
                    if time.monotonic() >= deadline:
                        addr = current.get("addr") or "remote"
                        raise FetchFailedError(
                            addr, _first_block(addr),
                            f"shuffle fetch timed out after "
                            f"{self.timeout}s") from None
            if kind == "batch":
                received += 1
                hb.beat()
                with self.manager.env.catalog.acquired(payload) as buf:
                    sem.acquire_if_necessary()
                    yield payload.map_id, buf.get_columnar_batch()
            elif kind == "error":
                addr, msg = payload
                addr = addr or "remote"
                raise FetchFailedError(addr, _first_block(addr), msg)
            elif kind == "fatal":
                addr, msg = payload
                addr = addr or "remote"
                err = errors[0] if errors else None
                if isinstance(err, FetchFailedError):
                    raise err
                if isinstance(err, (OSError, ConnectionError, EOFError)):
                    # a dead/unreachable server is a FetchFailed (stage
                    # retry), never a raw socket error (reference
                    # RapidsShuffleIterator error path -> Spark
                    # FetchFailedException)
                    raise FetchFailedError(
                        addr, _first_block(addr),
                        f"shuffle server unreachable: {err}") from err
                raise err if err is not None else FetchFailedError(
                    addr, _first_block(addr), msg)
            elif kind == "done":
                finished = True
            if finished and q.empty() and done.is_set():
                break
