"""Accelerated shuffle manager: caching writer/reader over the spillable
catalog + transport.

Reference: `RapidsShuffleInternalManager.scala` — `RapidsCachingWriter`
(map output stays in the device store, spillable; MapStatus advertises the
transport address), `RapidsCachingReader` (local partitions read straight
from the catalog; remote ones fetched via the transport), and
`RapidsShuffleIterator` (fetch orchestration, semaphore on materialize,
timeout -> FetchFailed).

The driver-side MapOutputRegistry plays Spark's MapOutputTracker: map
task -> (executor, per-partition sizes).  Executor environments register
here so local mode and tests can run many "executors" in one process —
multi-executor behavior without a cluster, like the reference's
mocked-transport suites.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Iterator, Optional, Sequence

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.buffer import (
    BufferId, DegenerateBuffer, degenerate_meta)
from spark_rapids_tpu.memory.env import ResourceEnv
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.spill_priorities import (
    OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
from spark_rapids_tpu.shuffle.catalog import (
    ShuffleBufferCatalog, ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client_server import (
    FetchFailedError, ShuffleClient, ShuffleReceiveHandler, ShuffleServer)
from spark_rapids_tpu.shuffle.transport import BlockIdMsg, make_transport


class MapStatus:
    """Map-task completion record (reference MapStatus with the transport
    address in BlockManagerId.topologyInfo).  Carries BOTH the loopback
    and the wire (TCP) address: in-process readers take the loop lane,
    readers in another process fall back to the wire — how the reference
    serves local vs UCX-remote blocks from one MapStatus."""

    def __init__(self, executor_id: str, address: str,
                 partition_sizes: list[int],
                 tcp_address: str | None = None):
        self.executor_id = executor_id
        self.address = address
        self.partition_sizes = partition_sizes
        self.tcp_address = tcp_address
        #: registry epoch this status was registered under (stamped by
        #: MapOutputRegistry.register; stale re-registrations from a
        #: superseded map run are rejected)
        self.epoch = 0

    def addresses(self) -> list[str]:
        return [a for a in (self.address, self.tcp_address) if a]

    def reachable_address(self, transport, health=None) -> str:
        """Pick the lane to fetch from: loopback when it resolves in
        this process, the wire otherwise — and when a PeerHealth
        tracker is supplied, route around blacklisted addresses before
        wasting their full timeout (the flapping-peer diet)."""
        cands = self.addresses()
        reach = [a for a in cands if transport.can_reach(a)] or cands
        if health is not None:
            ok = [a for a in reach if not health.is_blacklisted(a)]
            if ok:
                reach = ok
        return reach[0]


class StaleMapStatusError(Exception):
    """A MapStatus registration carried a superseded epoch: the shuffle's
    outputs were invalidated (peer loss) after the producing map run
    started, so its result must not be served to reducers."""


class MapOutputRegistry:
    """Driver-side map output tracker (process-global).  Plays Spark's
    MapOutputTracker INCLUDING the fault-recovery surface: per-shuffle
    epochs (bumped on every invalidation, so stale registrations are
    rejected), executor/address invalidation (the FetchFailed ->
    unregisterMapOutput path), and an expected-map-count so a reduce
    read over an incomplete output set fails loudly instead of
    returning partial data."""

    _lock = threading.Lock()
    _outputs: dict[int, dict[int, MapStatus]] = {}
    _epochs: dict[int, int] = {}
    _expected: dict[int, int] = {}

    @classmethod
    def register(cls, shuffle_id: int, map_id: int,
                 status: MapStatus, epoch: Optional[int] = None) -> None:
        with cls._lock:
            cur = cls._epochs.get(shuffle_id, 0)
            if epoch is not None and epoch != cur:
                raise StaleMapStatusError(
                    f"map output {shuffle_id}/{map_id} registered at "
                    f"epoch {epoch} but the shuffle is at epoch {cur}: "
                    f"the producing map run was superseded by a "
                    f"recovery invalidation")
            status.epoch = cur
            cls._outputs.setdefault(shuffle_id, {})[map_id] = status

    @classmethod
    def outputs_for(cls, shuffle_id: int) -> dict[int, MapStatus]:
        with cls._lock:
            return dict(cls._outputs.get(shuffle_id, {}))

    @classmethod
    def epoch(cls, shuffle_id: int) -> int:
        with cls._lock:
            return cls._epochs.get(shuffle_id, 0)

    @classmethod
    def set_expected_maps(cls, shuffle_id: int, num_maps: int) -> None:
        """Record how many map tasks the shuffle has, arming the
        missing-output guard in `missing_maps`."""
        with cls._lock:
            cls._expected[shuffle_id] = num_maps

    @classmethod
    def missing_maps(cls, shuffle_id: int) -> list[int]:
        """Map ids whose outputs are invalidated-and-not-yet-recomputed
        (empty when the expected count was never declared)."""
        with cls._lock:
            n = cls._expected.get(shuffle_id)
            if n is None:
                return []
            outs = cls._outputs.get(shuffle_id, {})
            return [m for m in range(n) if m not in outs]

    @classmethod
    def invalidate_address(cls, shuffle_id: int, address: str
                           ) -> dict[int, MapStatus]:
        """Drop every map output owned by the executor(s) advertising
        `address` and bump the shuffle's epoch.  Returns the removed
        {map_id: MapStatus} so recovery can recompute exactly those."""
        with cls._lock:
            outs = cls._outputs.get(shuffle_id, {})
            execs = {s.executor_id for s in outs.values()
                     if address in (s.address, s.tcp_address)}
            lost = {m: s for m, s in outs.items()
                    if s.executor_id in execs}
            for m in lost:
                del outs[m]
            if lost:
                cls._epochs[shuffle_id] = \
                    cls._epochs.get(shuffle_id, 0) + 1
            return lost

    @classmethod
    def invalidate_others(cls, shuffle_id: int, keep_executor_id: str
                          ) -> dict[int, MapStatus]:
        """Unattributable failure fallback: drop every map output NOT
        owned by `keep_executor_id` (the reducing executor itself) and
        bump the epoch — a conservative whole-stage invalidation."""
        with cls._lock:
            outs = cls._outputs.get(shuffle_id, {})
            lost = {m: s for m, s in outs.items()
                    if s.executor_id != keep_executor_id}
            for m in lost:
                del outs[m]
            if lost:
                cls._epochs[shuffle_id] = \
                    cls._epochs.get(shuffle_id, 0) + 1
            return lost

    @classmethod
    def unregister_shuffle(cls, shuffle_id: int) -> None:
        with cls._lock:
            cls._outputs.pop(shuffle_id, None)
            cls._epochs.pop(shuffle_id, None)
            cls._expected.pop(shuffle_id, None)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._outputs.clear()
            cls._epochs.clear()
            cls._expected.clear()


class TpuShuffleManager:
    """Executor-side shuffle environment (reference GpuShuffleEnv +
    RapidsShuffleInternalManagerBase)."""

    _registry_lock = threading.Lock()
    _managers: dict[str, "TpuShuffleManager"] = {}

    def __init__(self, executor_id: str,
                 env: Optional[ResourceEnv] = None,
                 conf: Optional[C.RapidsConf] = None):
        self.executor_id = executor_id
        self.conf = conf or C.get_active_conf()
        self.env = env or ResourceEnv.get()
        self.shuffle_catalog = ShuffleBufferCatalog(self.env.catalog)
        self.received_catalog = ShuffleReceivedBufferCatalog(
            self.env.catalog)
        self.transport = make_transport(self.conf)
        from spark_rapids_tpu.shuffle.compression import codec_from_conf
        self.server = ShuffleServer(self.shuffle_catalog, self.transport,
                                    codec=codec_from_conf(self.conf))
        handle = self.transport.make_server(executor_id, self.server)
        self.loop_address = handle.loop_address
        self.tcp_address = handle.tcp_address
        with TpuShuffleManager._registry_lock:
            TpuShuffleManager._managers[executor_id] = self

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def get(cls, executor_id: str) -> Optional["TpuShuffleManager"]:
        with cls._registry_lock:
            return cls._managers.get(executor_id)

    def close(self) -> None:
        self.transport.shutdown()
        with TpuShuffleManager._registry_lock:
            TpuShuffleManager._managers.pop(self.executor_id, None)

    def register_shuffle(self, shuffle_id: int) -> None:
        self.shuffle_catalog.register_shuffle(shuffle_id)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        self.shuffle_catalog.unregister_shuffle(shuffle_id)
        MapOutputRegistry.unregister_shuffle(shuffle_id)

    # -- write side ----------------------------------------------------------
    def get_writer(self, shuffle_id: int, map_id: int
                   ) -> "CachingShuffleWriter":
        return CachingShuffleWriter(self, shuffle_id, map_id)

    # -- read side -----------------------------------------------------------
    _attempt_ids = itertools.count(1)

    def get_reader(self, shuffle_id: int, partition: int,
                   task_attempt_id: Optional[int] = None,
                   timeout: float = 30.0,
                   with_map_ids: bool = False,
                   metrics=None) -> Iterator:
        """Iterate one reduce partition's batches.  `with_map_ids`
        yields (map_id, batch) tuples instead, so a recovery-aware
        consumer can re-establish deterministic map order after a
        recompute moved outputs between executors.  `metrics` (the
        owning exchange's MetricSet) is charged the wire
        compressed/uncompressed byte counters so codec choice shows in
        EXPLAIN-with-metrics."""
        if task_attempt_id is None:
            # unique per reader so per-task receive cleanup cannot free a
            # concurrent reader's buffers
            task_attempt_id = next(TpuShuffleManager._attempt_ids)
        it = CachingShuffleReader(
            self, shuffle_id, partition, task_attempt_id, timeout,
            metrics=metrics).read()
        if with_map_ids:
            return it
        return (b for _, b in it)


class CachingShuffleWriter:
    """Stores each partition's batch in the device store via the shuffle
    catalog; degenerate (rows-only) batches store metadata alone
    (reference RapidsCachingWriter.write :74-191)."""

    def __init__(self, manager: TpuShuffleManager, shuffle_id: int,
                 map_id: int):
        self.manager = manager
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self._sizes: dict[int, int] = {}

    def write_partition(self, partition: int, batch: ColumnarBatch) -> None:
        cat = self.manager.shuffle_catalog
        bid = cat.next_shuffle_buffer_id(self.shuffle_id, self.map_id,
                                         partition)
        if batch.num_columns == 0:
            buf = DegenerateBuffer(
                bid, degenerate_meta(batch.schema, batch.num_rows))
            cat.catalog.register(buf)
            self._sizes[partition] = 0
            return
        buf = self.manager.env.device_store.add_batch(
            bid, batch, OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
        self._sizes[partition] = self._sizes.get(partition, 0) + \
            buf.size_bytes

    def commit(self, num_partitions: int,
               epoch: Optional[int] = None) -> MapStatus:
        """Register the map output.  `epoch` (recovery recomputes only)
        pins the registration to the registry epoch the recompute was
        planned under: if another invalidation raced in, the commit is
        rejected (StaleMapStatusError) and the written buffers freed —
        a superseded map run must never serve reducers."""
        status = MapStatus(
            self.manager.executor_id, self.manager.loop_address,
            [self._sizes.get(p, 0) for p in range(num_partitions)],
            tcp_address=self.manager.tcp_address)
        try:
            MapOutputRegistry.register(self.shuffle_id, self.map_id,
                                       status, epoch=epoch)
        except StaleMapStatusError:
            self.abort()
            raise
        return status

    def abort(self) -> None:
        """Failed-task cleanup (reference :159-167)."""
        self.manager.shuffle_catalog.remove_task_buffers(
            self.shuffle_id, self.map_id)


class _IteratorHandler(ShuffleReceiveHandler):
    def __init__(self, q: "queue.Queue", current: dict,
                 wire_stats: Optional[dict] = None):
        self.q = q
        #: mutable cell the fetch loop updates with the peer address it
        #: is currently draining, so errors carry the REAL peer (the
        #: old literal "remote" hid which executor to invalidate)
        self.current = current
        #: {"compressed": n, "raw": n} accumulator the owning reader
        #: charges to the exchange's compression metrics
        self.wire_stats = wire_stats
        self.expected = 0

    def start(self, expected_batches: int) -> None:
        self.expected = expected_batches

    def batch_received(self, bid: BufferId) -> None:
        self.q.put(("batch", bid))

    def buffer_received(self, wire_bytes: int, raw_bytes: int) -> None:
        if self.wire_stats is not None:
            self.wire_stats["compressed"] += wire_bytes
            self.wire_stats["raw"] += raw_bytes

    def transfer_error(self, message: str) -> None:
        self.q.put(("error", (self.current.get("addr"), message)))


class CachingShuffleReader:
    """Partitions the fetch list into local (catalog) and remote
    (transport) blocks (reference RapidsCachingReader.read:61-100);
    remote fetches run on a fetch thread while the task consumes."""

    def __init__(self, manager: TpuShuffleManager, shuffle_id: int,
                 partition: int, task_attempt_id: int, timeout: float,
                 metrics=None):
        self.manager = manager
        self.shuffle_id = shuffle_id
        self.partition = partition
        self.task_attempt_id = task_attempt_id
        self.timeout = timeout
        self.metrics = metrics
        #: wire bytes this reader's remote fetches pulled, compressed
        #: vs uncompressed — charged to the exchange on read completion
        self.wire_stats = {"compressed": 0, "raw": 0}
        # captured here (the consuming task's thread, session conf
        # installed) because the fetch worker is a raw thread with no
        # conf propagation
        self.conf = C.get_active_conf()

    def read(self) -> Iterator[tuple[int, ColumnarBatch]]:
        from spark_rapids_tpu.shuffle.recovery import PeerHealth
        health = PeerHealth.get()
        missing = MapOutputRegistry.missing_maps(self.shuffle_id)
        if missing:
            # invalidated-and-not-yet-recomputed outputs: reading the
            # survivors would return PARTIAL data — surface the
            # stage-retry signal instead (recovery recomputes, then the
            # retried read sees a complete set)
            raise FetchFailedError(
                "unregistered", None,
                f"shuffle {self.shuffle_id} is missing map outputs "
                f"{missing} (superseded by a recovery invalidation)")
        outputs = MapOutputRegistry.outputs_for(self.shuffle_id)
        local_bids: list[BufferId] = []
        remote: dict[str, list[BlockIdMsg]] = {}
        for map_id, status in sorted(outputs.items()):
            if status.partition_sizes[self.partition] == 0 and \
                    not self._has_degenerate(status, map_id):
                continue
            if status.executor_id == self.manager.executor_id:
                local_bids.extend(
                    self.manager.shuffle_catalog.blocks_for_partition(
                        self.shuffle_id, self.partition, [map_id]))
            else:
                addr = status.reachable_address(self.manager.transport,
                                                health)
                remote.setdefault(addr, []).append(
                    BlockIdMsg(self.shuffle_id, map_id, self.partition))
        try:
            # local blocks: straight catalog reads with the semaphore held
            sem = TpuSemaphore.get()
            for bid in local_bids:
                with self.manager.env.catalog.acquired(bid) as buf:
                    sem.acquire_if_necessary()
                    yield bid.map_id, buf.get_columnar_batch()
            # remote: issue fetches per peer, consume as they land
            yield from self._fetch_remote(remote, sem)
        finally:
            if self.metrics is not None and \
                    self.wire_stats["compressed"]:
                from spark_rapids_tpu.utils import metrics as M
                self.metrics.add(M.SHUFFLE_COMPRESSED_BYTES,
                                 self.wire_stats["compressed"])
                self.metrics.add(M.SHUFFLE_RAW_BYTES,
                                 self.wire_stats["raw"])
            # received buffers live only for this task (reference
            # ShuffleReceivedBufferCatalog per-task cleanup)
            self.manager.received_catalog.release_task(
                self.task_attempt_id)

    def _has_degenerate(self, status: MapStatus, map_id: int) -> bool:
        # degenerate batches report size 0 but still must be fetched for
        # their row counts; local catalog lookup answers cheaply
        if status.executor_id != self.manager.executor_id:
            return True  # conservatively ask the peer
        return bool(self.manager.shuffle_catalog.blocks_for_partition(
            self.shuffle_id, self.partition, [map_id]))

    def _fetch_remote(self, remote: dict[str, list[BlockIdMsg]],
                      sem) -> Iterator[ColumnarBatch]:
        if not remote:
            return
        from spark_rapids_tpu.shuffle.recovery import PeerHealth
        from spark_rapids_tpu.utils import profile as P
        health = PeerHealth.get()
        q: "queue.Queue" = queue.Queue()
        current = {"addr": next(iter(remote))}
        handler = _IteratorHandler(q, current, self.wire_stats)
        errors: list[BaseException] = []
        done = threading.Event()
        # captured on the consuming thread: the fetch worker's spans
        # (ShuffleClient fetch ranges) parent under this reader's scope
        # and its conf / cancellation / events reach the RIGHT query
        from spark_rapids_tpu.exec import scheduler as S
        span_ref = P.current_ref()
        qc = S.current()

        def fetch_all():
            try:
                # raw worker thread: install the consuming task's conf
                # so watchdog deadlines / fault injection resolve to
                # the session's values, not registry defaults
                with S.scoped(qc), C.session(self.conf), \
                        P.attach(span_ref):
                    for address, blocks in remote.items():
                        current["addr"] = address
                        conn = self.manager.transport.make_client(
                            address)
                        client = ShuffleClient(
                            conn, self.manager.transport,
                            self.manager.received_catalog,
                            self.manager.env.host_store, address,
                            conf=self.conf)
                        try:
                            client.fetch_blocks(blocks,
                                                self.task_attempt_id,
                                                handler)
                        finally:
                            # the client may have swapped in a fresh
                            # connection on a retry: close whatever it
                            # currently holds, not the original handle
                            client.connection.close()
                        health.record_success(address)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                q.put(("fatal", (current.get("addr"), str(e))))
            finally:
                done.set()
                q.put(("done", None))

        def _first_block(addr):
            blocks = remote.get(addr) or []
            return blocks[0] if blocks else None

        t = threading.Thread(target=fetch_all, daemon=True,
                             name="tpu-shuffle-fetch")
        t.start()
        from spark_rapids_tpu.utils import watchdog as W
        hb = W.heartbeat(f"shuffle-read:s{self.shuffle_id}"
                         f"p{self.partition}", kind="task",
                         conf=self.conf)
        try:
            yield from self._consume(q, current, errors, done,
                                     _first_block, hb, sem)
        finally:
            hb.close()

    def _consume(self, q, current, errors, done, _first_block, hb,
                 sem) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.utils import watchdog as W
        received = 0
        finished = False
        while True:
            # bounded-poll the fetch queue in small slices so a
            # watchdog cancellation is honored promptly; the overall
            # per-get timeout still FetchFails like before
            deadline = time.monotonic() + self.timeout
            while True:
                W.check_cancelled()
                try:
                    kind, payload = q.get(
                        timeout=min(0.1, max(0.0, deadline
                                             - time.monotonic())))
                    break
                except queue.Empty:
                    if time.monotonic() >= deadline:
                        addr = current.get("addr") or "remote"
                        raise FetchFailedError(
                            addr, _first_block(addr),
                            f"shuffle fetch timed out after "
                            f"{self.timeout}s") from None
            if kind == "batch":
                received += 1
                hb.beat()
                with self.manager.env.catalog.acquired(payload) as buf:
                    sem.acquire_if_necessary()
                    yield payload.map_id, buf.get_columnar_batch()
            elif kind == "error":
                addr, msg = payload
                addr = addr or "remote"
                raise FetchFailedError(addr, _first_block(addr), msg)
            elif kind == "fatal":
                addr, msg = payload
                addr = addr or "remote"
                err = errors[0] if errors else None
                if isinstance(err, FetchFailedError):
                    raise err
                if isinstance(err, (OSError, ConnectionError, EOFError)):
                    # a dead/unreachable server is a FetchFailed (stage
                    # retry), never a raw socket error (reference
                    # RapidsShuffleIterator error path -> Spark
                    # FetchFailedException)
                    raise FetchFailedError(
                        addr, _first_block(addr),
                        f"shuffle server unreachable: {err}") from err
                raise err if err is not None else FetchFailedError(
                    addr, _first_block(addr), msg)
            elif kind == "done":
                finished = True
            if finished and q.empty() and done.is_set():
                break
