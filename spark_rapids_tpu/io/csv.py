"""CSV scan (reference `GpuCSVScan`, `GpuBatchScanExec.scala:87-235`).

The reference splits files at byte boundaries, extends each split to the
next line boundary on the host, and hands the buffered lines to cuDF's CSV
parser.  Same shape here: byte-range read + line-boundary fixup on the
host, parsed by pyarrow's CSV reader with an explicit schema (no inference
drift between splits), then uploaded as one batch.

Unsupported options mirror the reference's guards (multi-char separators,
comments, custom line terminators, permissive corrupt-record columns all
fall back to CPU at tag time — see io/exec.py tagging).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.scan import FileSplit, FormatReader


@dataclasses.dataclass(frozen=True)
class CsvOptions:
    sep: str = ","
    header: bool = False
    null_value: str = ""
    quote: str = '"'
    comment: str = ""           # unsupported when set (reference guard)
    line_sep: str = "\n"        # only \n supported (reference guard)
    date_format: str = ""       # non-default formats unsupported

    def tag_unsupported(self) -> list[str]:
        reasons = []
        if len(self.sep) != 1:
            reasons.append("multi-character separators are not supported")
        if self.comment:
            reasons.append("comment skipping is not supported")
        if self.line_sep != "\n":
            reasons.append("custom line separators are not supported")
        if self.date_format:
            reasons.append("custom date formats are not supported")
        return reasons


def _read_split_lines(split: FileSplit) -> bytes:
    """Read [start, start+length), snapped to line boundaries: skip the
    first partial line unless at file start; extend past the end to finish
    the last line."""
    with open(split.path, "rb") as f:
        f.seek(split.start)
        data = f.read(split.length)
        if split.start > 0:
            nl = data.find(b"\n")
            data = data[nl + 1:] if nl >= 0 else b""
        if split.start + split.length < split.file_size and data:
            tail = b""
            while True:
                chunk = f.read(65536)
                if not chunk:
                    break
                nl = chunk.find(b"\n")
                if nl >= 0:
                    tail += chunk[: nl + 1]
                    break
                tail += chunk
            data += tail
    return data


class CsvFormat(FormatReader):
    extension = ".csv"

    def __init__(self, schema: T.Schema, options: Optional[CsvOptions] = None):
        # CSV requires a user schema (the reference falls back when schema
        # inference would be needed per-split)
        self.schema = schema
        self.options = options or CsvOptions()

    def file_schema(self, path: str) -> T.Schema:
        return self.schema

    def read_split(self, split: FileSplit, read_schema: T.Schema,
                   filter_expr) -> Optional["object"]:
        import io

        import pyarrow as pa
        import pyarrow.csv as pacsv
        data = _read_split_lines(split)
        opts = self.options
        if split.start == 0 and opts.header and data:
            nl = data.find(b"\n")
            data = data[nl + 1:] if nl >= 0 else b""
        if not data:
            return None
        column_types = {f.name: T.to_arrow(f.dtype)
                        for f in self.schema.fields}
        table = pacsv.read_csv(
            io.BytesIO(data),
            read_options=pacsv.ReadOptions(
                column_names=list(self.schema.names), use_threads=False),
            parse_options=pacsv.ParseOptions(delimiter=opts.sep,
                                             quote_char=opts.quote),
            convert_options=pacsv.ConvertOptions(
                column_types=column_types,
                null_values=[opts.null_value],
                strings_can_be_null=True,
                include_columns=[n for n in read_schema.names
                                 if n in self.schema.names]))
        return table
