"""File write path: commit protocol, single-directory and dynamic-partition
writers, write statistics.

Reference: `GpuFileFormatWriter.scala` (job setup/commit),
`GpuFileFormatDataWriter.scala` (SingleDirectoryDataWriter /
DynamicPartitionDataWriter — sort-based single-writer), and
`BasicColumnarWriteStatsTracker`.  The commit protocol is Hadoop's
FileOutputCommitter v1 shape: tasks write under
`_temporary/<attempt>/`, task commit renames into the job staging dir,
job commit moves everything to the final location and writes `_SUCCESS`.

Dynamic partitioning is sort-based like the reference: the batch is sorted
by partition expressions on device, sliced per distinct value on the host,
and streamed through one open writer at a time.
"""
from __future__ import annotations

import dataclasses
import os
import shutil
import uuid
from typing import Iterator, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch


@dataclasses.dataclass
class WriteStats:
    """Reference BasicColumnarWriteStatsTracker output."""
    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    partitions: list = dataclasses.field(default_factory=list)

    def merge(self, other: "WriteStats") -> "WriteStats":
        return WriteStats(self.num_files + other.num_files,
                          self.num_rows + other.num_rows,
                          self.num_bytes + other.num_bytes,
                          self.partitions + other.partitions)


def _writer_factory(file_format: str, options):
    if file_format == "parquet":
        from spark_rapids_tpu.io.parquet import (
            ParquetColumnarWriter, ParquetWriterOptions)
        return (ParquetColumnarWriter, options or ParquetWriterOptions(),
                ".parquet")
    if file_format == "orc":
        from spark_rapids_tpu.io.orc import OrcColumnarWriter, OrcWriterOptions
        return OrcColumnarWriter, options or OrcWriterOptions(), ".orc"
    raise ValueError(f"unsupported write format {file_format}")


class WriteJob:
    """Job-level commit protocol (reference GpuFileFormatWriter.write)."""

    def __init__(self, output_path: str, file_format: str,
                 schema: T.Schema, partition_by: Sequence[str] = (),
                 mode: str = "error", options=None):
        self.output_path = output_path
        self.file_format = file_format
        self.schema = schema
        self.partition_by = list(partition_by)
        self.mode = mode
        self.options = options
        # validate the format BEFORE setup() can destroy existing output
        self._writer_cls, self._writer_opts, self._ext = _writer_factory(
            file_format, options)
        self.job_id = uuid.uuid4().hex[:12]
        self.staging = os.path.join(output_path, "_temporary", self.job_id)

    def setup(self) -> None:
        if os.path.exists(self.output_path) and self.mode == "error" and \
                any(not n.startswith("_") for n in os.listdir(
                    self.output_path)):
            raise FileExistsError(
                f"path {self.output_path} already exists (mode=error)")
        if self.mode == "overwrite" and os.path.exists(self.output_path):
            shutil.rmtree(self.output_path)
        os.makedirs(self.staging, exist_ok=True)

    def task_writer(self, task_id: int) -> "DataWriter":
        data_schema = T.Schema(tuple(
            f for f in self.schema.fields if f.name not in self.partition_by))
        cls = (DynamicPartitionDataWriter if self.partition_by
               else SingleDirectoryDataWriter)
        return cls(self, task_id, data_schema, self._writer_cls,
                   self._writer_opts, self._ext)

    def commit(self, task_stats: Sequence[WriteStats]) -> WriteStats:
        """Move committed task output from staging to the final dir."""
        for root, _, names in os.walk(self.staging):
            rel = os.path.relpath(root, self.staging)
            dest_dir = (self.output_path if rel == "."
                        else os.path.join(self.output_path, rel))
            os.makedirs(dest_dir, exist_ok=True)
            for n in names:
                os.replace(os.path.join(root, n), os.path.join(dest_dir, n))
        shutil.rmtree(os.path.join(self.output_path, "_temporary"),
                      ignore_errors=True)
        with open(os.path.join(self.output_path, "_SUCCESS"), "w"):
            pass
        total = WriteStats()
        for s in task_stats:
            total = total.merge(s)
        return total

    def abort(self) -> None:
        shutil.rmtree(os.path.join(self.output_path, "_temporary"),
                      ignore_errors=True)


class DataWriter:
    """Task-level writer (reference GpuFileFormatDataWriter)."""

    def __init__(self, job: WriteJob, task_id: int, data_schema: T.Schema,
                 writer_cls, writer_opts, ext: str):
        self.job = job
        self.task_id = task_id
        self.data_schema = data_schema
        self.writer_cls = writer_cls
        self.writer_opts = writer_opts
        self.ext = ext
        self.stats = WriteStats()
        self._seq = 0

    def _new_file(self, subdir: str = "") -> str:
        name = (f"part-{self.task_id:05d}-{self.job.job_id}"
                f"-{self._seq:03d}{self.ext}")
        self._seq += 1
        d = os.path.join(self.job.staging, subdir)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def write(self, batch: ColumnarBatch) -> None:
        raise NotImplementedError

    def commit(self) -> WriteStats:
        raise NotImplementedError

    def abort(self) -> None:
        pass


class SingleDirectoryDataWriter(DataWriter):
    def __init__(self, *a):
        super().__init__(*a)
        self._writer = None

    def write(self, batch: ColumnarBatch) -> None:
        batch = batch.dense()
        if batch.num_rows == 0:
            return
        if self._writer is None:
            self._writer = self.writer_cls(
                self._new_file(), self.data_schema, self.writer_opts)
        self._writer.write_batch(batch.select(self.data_schema.names))

    def commit(self) -> WriteStats:
        if self._writer is not None:
            self._writer.close()
            self.stats.num_files += 1
            self.stats.num_rows += self._writer.rows_written
            self.stats.num_bytes += self._writer.bytes_written
        return self.stats


def _escape_path_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    out = []
    for ch in s:
        out.append(f"%{ord(ch):02X}" if ch in '/\\:*?"<>|%' else ch)
    return "".join(out)


class DynamicPartitionDataWriter(DataWriter):
    """Sort-based single-open-writer dynamic partitioning (reference
    `GpuFileFormatDataWriter.scala` DynamicPartitionDataWriter: requires
    input sorted by partition columns; we sort each batch and keep one
    writer open per run of equal values)."""

    def __init__(self, *a):
        super().__init__(*a)
        self._writer = None
        self._current_key: Optional[tuple] = None

    def write(self, batch: ColumnarBatch) -> None:
        batch = batch.dense()
        if batch.num_rows == 0:
            return
        n = batch.num_rows
        # vectorized host-side key sort: np.lexsort over (null-rank, value)
        # per partition column, most-significant column last in the key
        # list (lexsort convention); runs of equal keys are found with one
        # adjacent-compare pass
        cols = []  # (values, validity) in partition_by order
        sort_keys = []
        for name in self.job.partition_by:
            vals, validity = batch.column(name).to_numpy(n)
            if vals.dtype == object:
                sortable = np.array(
                    ["" if v is None else str(v) for v in vals])
            else:
                sortable = vals
            cols.append((vals, validity))
            sort_keys.append((sortable, ~validity))
        lex = []
        for sortable, null_rank in reversed(sort_keys):
            lex.append(sortable)
            lex.append(null_rank)  # more significant than the value
        order = np.lexsort(lex)
        changed = np.zeros(n, bool)
        changed[0] = True
        for sortable, null_rank in sort_keys:
            sv, nr = sortable[order], null_rank[order]
            changed[1:] |= (sv[1:] != sv[:-1]) | (nr[1:] != nr[:-1])
        starts = np.flatnonzero(changed)
        ends = np.append(starts[1:], n)
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.vector import bucket_capacity
        for s, e in zip(starts, ends):
            first = order[s]
            key = tuple(
                None if not validity[first] else
                (vals[first] if isinstance(vals[first], str)
                 else vals[first].item() if hasattr(vals[first], "item")
                 else vals[first])
                for vals, validity in cols)
            if key != self._current_key:
                self._roll(key)
            rows = order[s:e]
            cap = bucket_capacity(len(rows))
            idx = np.zeros(cap, np.int64)
            idx[: len(rows)] = rows
            valid = jnp.arange(cap) < len(rows)
            sub = batch.gather(jnp.asarray(idx), valid, len(rows))
            self._writer.write_batch(sub.select(self.data_schema.names))

    def _roll(self, key: tuple) -> None:
        self._close_current()
        subdir = os.path.join(*[
            f"{name}={_escape_path_value(v)}"
            for name, v in zip(self.job.partition_by, key)])
        self._writer = self.writer_cls(
            self._new_file(subdir), self.data_schema, self.writer_opts)
        self._current_key = key
        self.stats.partitions.append(subdir)

    def _close_current(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self.stats.num_files += 1
            self.stats.num_rows += self._writer.rows_written
            self.stats.num_bytes += self._writer.bytes_written
            self._writer = None

    def commit(self) -> WriteStats:
        self._close_current()
        return self.stats


def write_batches(batches: Iterator[ColumnarBatch], output_path: str,
                  file_format: str, schema: T.Schema,
                  partition_by: Sequence[str] = (), mode: str = "error",
                  options=None) -> WriteStats:
    """Single-task convenience driver for the full job protocol."""
    job = WriteJob(output_path, file_format, schema, partition_by, mode,
                   options)
    job.setup()
    writer = job.task_writer(0)
    try:
        for b in batches:
            writer.write(b)
        stats = writer.commit()
    except BaseException:
        writer.abort()
        job.abort()
        raise
    return job.commit([stats])
