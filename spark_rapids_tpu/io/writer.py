"""File write path: commit protocol, single-directory and dynamic-partition
writers, write statistics.

Reference: `GpuFileFormatWriter.scala` (job setup/commit),
`GpuFileFormatDataWriter.scala` (SingleDirectoryDataWriter /
DynamicPartitionDataWriter — sort-based single-writer), and
`BasicColumnarWriteStatsTracker`.  The commit protocol is Hadoop's
FileOutputCommitter v1 shape: tasks write under
`_temporary/<attempt>/`, task commit renames into the job staging dir,
job commit moves everything to the final location and writes `_SUCCESS`.

Dynamic partitioning is sort-based like the reference: the batch is sorted
by partition expressions on device, sliced per distinct value on the host,
and streamed through one open writer at a time.
"""
from __future__ import annotations

import dataclasses
import os
import shutil
import uuid
from typing import Iterator, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch


@dataclasses.dataclass
class WriteStats:
    """Reference BasicColumnarWriteStatsTracker output."""
    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    partitions: list = dataclasses.field(default_factory=list)

    def merge(self, other: "WriteStats") -> "WriteStats":
        return WriteStats(self.num_files + other.num_files,
                          self.num_rows + other.num_rows,
                          self.num_bytes + other.num_bytes,
                          self.partitions + other.partitions)


#: dropped (never moved to the output) at job commit
_COMMIT_MARKER = "_COMMITTED"


def _writer_factory(file_format: str, options):
    if file_format == "parquet":
        from spark_rapids_tpu.io.parquet import (
            ParquetColumnarWriter, ParquetWriterOptions)
        return (ParquetColumnarWriter, options or ParquetWriterOptions(),
                ".parquet")
    if file_format == "orc":
        from spark_rapids_tpu.io.orc import OrcColumnarWriter, OrcWriterOptions
        return OrcColumnarWriter, options or OrcWriterOptions(), ".orc"
    raise ValueError(f"unsupported write format {file_format}")


class WriteJob:
    """Job-level commit protocol (reference GpuFileFormatWriter.write +
    GpuInsertIntoHadoopFsRelationCommand).  FileOutputCommitter-v1
    shape, with a real TASK-attempt level (VERDICT r4 missing #2):

      task attempt writes under  _temporary/<job>/_attempt_<task>_<uuid>/
      task commit                one atomic rename -> _temporary/<job>/task_<task>/
      job commit                 move every committed task's files to the
                                 final dirs, then _SUCCESS

    The atomic task-commit rename makes duplicate/speculative attempts
    safe: exactly one attempt's rename can succeed for a task id; the
    loser deletes its own attempt dir and contributes no files or
    stats.  Task abort removes only that attempt's dir — committed
    output and other in-flight attempts are untouched.

    Modes: error | append | overwrite | dynamic_overwrite.
    dynamic_overwrite is Spark's INSERT OVERWRITE with
    spark.sql.sources.partitionOverwriteMode=dynamic: only partitions
    actually present in the new data are replaced at job commit;
    untouched partitions survive (the reference command's
    dynamicPartitionOverwrite branch)."""

    def __init__(self, output_path: str, file_format: str,
                 schema: T.Schema, partition_by: Sequence[str] = (),
                 mode: str = "error", options=None):
        self.output_path = output_path
        self.file_format = file_format
        self.schema = schema
        self.partition_by = list(partition_by)
        self.mode = mode
        self.options = options
        if mode == "dynamic_overwrite" and not self.partition_by:
            raise ValueError(
                "dynamic_overwrite requires partition_by columns")
        # validate the format BEFORE setup() can destroy existing output
        self._writer_cls, self._writer_opts, self._ext = _writer_factory(
            file_format, options)
        self.job_id = uuid.uuid4().hex[:12]
        self.staging = os.path.join(output_path, "_temporary", self.job_id)

    def setup(self) -> None:
        if os.path.exists(self.output_path) and self.mode == "error" and \
                any(not n.startswith("_") for n in os.listdir(
                    self.output_path)):
            raise FileExistsError(
                f"path {self.output_path} already exists (mode=error)")
        if self.mode == "overwrite" and os.path.exists(self.output_path):
            shutil.rmtree(self.output_path)
        os.makedirs(self.staging, exist_ok=True)

    def task_writer(self, task_id: int) -> "DataWriter":
        data_schema = T.Schema(tuple(
            f for f in self.schema.fields if f.name not in self.partition_by))
        cls = (DynamicPartitionDataWriter if self.partition_by
               else SingleDirectoryDataWriter)
        return cls(self, task_id, data_schema, self._writer_cls,
                   self._writer_opts, self._ext)

    def _committed_task_dirs(self) -> list:
        if not os.path.isdir(self.staging):
            return []
        return sorted(os.path.join(self.staging, n)
                      for n in os.listdir(self.staging)
                      if n.startswith("task_"))

    def commit(self, task_stats: Sequence[WriteStats]) -> WriteStats:
        """Move committed task output from staging to the final dir.
        Only `task_<id>` dirs (atomically renamed by task commit) are
        moved — files from uncommitted/aborted attempts never reach
        the output."""
        task_dirs = self._committed_task_dirs()
        if self.mode == "dynamic_overwrite":
            # replace exactly the partitions present in the new data
            touched = set()
            for td in task_dirs:
                for root, _dirs, names in os.walk(td):
                    rel = os.path.relpath(root, td)
                    if names and rel != ".":
                        touched.add(rel)
            for rel in sorted(touched):
                dest = os.path.join(self.output_path, rel)
                if os.path.isdir(dest):
                    shutil.rmtree(dest)
        for td in task_dirs:
            for root, _dirs, names in os.walk(td):
                rel = os.path.relpath(root, td)
                dest_dir = (self.output_path if rel == "."
                            else os.path.join(self.output_path, rel))
                os.makedirs(dest_dir, exist_ok=True)
                for n in names:
                    if n == _COMMIT_MARKER:
                        continue
                    os.replace(os.path.join(root, n),
                               os.path.join(dest_dir, n))
        shutil.rmtree(os.path.join(self.output_path, "_temporary"),
                      ignore_errors=True)
        with open(os.path.join(self.output_path, "_SUCCESS"), "w"):
            pass
        total = WriteStats()
        for s in task_stats:
            total = total.merge(s)
        return total

    def abort(self) -> None:
        shutil.rmtree(os.path.join(self.output_path, "_temporary"),
                      ignore_errors=True)


class DataWriter:
    """Task-ATTEMPT writer (reference GpuFileFormatDataWriter).  All
    files land in this attempt's private dir; `commit()` publishes
    them with one atomic rename to the task's committed dir, and
    `abort()` removes the attempt dir without touching anything
    published.  Safe under duplicate/speculative attempts for the
    same task id: the rename can succeed for exactly one attempt."""

    def __init__(self, job: WriteJob, task_id: int, data_schema: T.Schema,
                 writer_cls, writer_opts, ext: str):
        self.job = job
        self.task_id = task_id
        self.data_schema = data_schema
        self.writer_cls = writer_cls
        self.writer_opts = writer_opts
        self.ext = ext
        self.stats = WriteStats()
        self._seq = 0
        self.attempt_id = uuid.uuid4().hex[:8]
        self.attempt_dir = os.path.join(
            job.staging, f"_attempt_{task_id:05d}_{self.attempt_id}")

    def _new_file(self, subdir: str = "") -> str:
        name = (f"part-{self.task_id:05d}-{self.job.job_id}"
                f"-{self._seq:03d}{self.ext}")
        self._seq += 1
        d = os.path.join(self.attempt_dir, subdir)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def write(self, batch: ColumnarBatch) -> None:
        raise NotImplementedError

    def _close_writers(self) -> None:
        pass

    def commit(self) -> WriteStats:
        """Close files, then publish the attempt with ONE atomic
        rename.  A lost speculative race (committed dir already
        exists) discards this attempt's files and stats — the winner's
        output is what the job sees; duplicates can't double-count."""
        self._close_writers()
        committed = os.path.join(self.job.staging,
                                 f"task_{self.task_id:05d}")
        os.makedirs(self.attempt_dir, exist_ok=True)
        # marker guarantees the committed dir is never EMPTY: POSIX
        # rename silently REPLACES an empty destination directory,
        # which would let a late speculative attempt overwrite an
        # already-committed zero-output task; with the marker present
        # the loser's rename always fails ENOTEMPTY
        with open(os.path.join(self.attempt_dir, _COMMIT_MARKER), "w"):
            pass
        try:
            os.rename(self.attempt_dir, committed)
        except OSError:
            # another attempt already committed this task id
            shutil.rmtree(self.attempt_dir, ignore_errors=True)
            return WriteStats()
        return self.stats

    def abort(self) -> None:
        self._close_writers()
        shutil.rmtree(self.attempt_dir, ignore_errors=True)


class SingleDirectoryDataWriter(DataWriter):
    def __init__(self, *a):
        super().__init__(*a)
        self._writer = None

    def write(self, batch: ColumnarBatch) -> None:
        batch = batch.dense()
        if batch.num_rows == 0:
            return
        if self._writer is None:
            self._writer = self.writer_cls(
                self._new_file(), self.data_schema, self.writer_opts)
        self._writer.write_batch(batch.select(self.data_schema.names))

    def _close_writers(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self.stats.num_files += 1
            self.stats.num_rows += self._writer.rows_written
            self.stats.num_bytes += self._writer.bytes_written
            self._writer = None


def _escape_path_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    out = []
    for ch in s:
        out.append(f"%{ord(ch):02X}" if ch in '/\\:*?"<>|%' else ch)
    return "".join(out)


class DynamicPartitionDataWriter(DataWriter):
    """Sort-based single-open-writer dynamic partitioning (reference
    `GpuFileFormatDataWriter.scala` DynamicPartitionDataWriter: requires
    input sorted by partition columns; we sort each batch and keep one
    writer open per run of equal values)."""

    def __init__(self, *a):
        super().__init__(*a)
        self._writer = None
        self._current_key: Optional[tuple] = None

    def write(self, batch: ColumnarBatch) -> None:
        batch = batch.dense()
        if batch.num_rows == 0:
            return
        n = batch.num_rows
        # vectorized host-side key sort: np.lexsort over (null-rank, value)
        # per partition column, most-significant column last in the key
        # list (lexsort convention); runs of equal keys are found with one
        # adjacent-compare pass
        cols = []  # (values, validity) in partition_by order
        sort_keys = []
        for name in self.job.partition_by:
            vals, validity = batch.column(name).to_numpy(n)
            if vals.dtype == object:
                sortable = np.array(
                    ["" if v is None else str(v) for v in vals])
            else:
                sortable = vals
            cols.append((vals, validity))
            sort_keys.append((sortable, ~validity))
        lex = []
        for sortable, null_rank in reversed(sort_keys):
            lex.append(sortable)
            lex.append(null_rank)  # more significant than the value
        order = np.lexsort(lex)
        changed = np.zeros(n, bool)
        changed[0] = True
        for sortable, null_rank in sort_keys:
            sv, nr = sortable[order], null_rank[order]
            changed[1:] |= (sv[1:] != sv[:-1]) | (nr[1:] != nr[:-1])
        starts = np.flatnonzero(changed)
        ends = np.append(starts[1:], n)
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.vector import bucket_capacity
        for s, e in zip(starts, ends):
            first = order[s]
            key = tuple(
                None if not validity[first] else
                (vals[first] if isinstance(vals[first], str)
                 else vals[first].item() if hasattr(vals[first], "item")
                 else vals[first])
                for vals, validity in cols)
            if key != self._current_key:
                self._roll(key)
            rows = order[s:e]
            cap = bucket_capacity(len(rows))
            idx = np.zeros(cap, np.int64)
            idx[: len(rows)] = rows
            valid = jnp.arange(cap) < len(rows)
            sub = batch.gather(jnp.asarray(idx), valid, len(rows))
            self._writer.write_batch(sub.select(self.data_schema.names))

    def _roll(self, key: tuple) -> None:
        self._close_current()
        subdir = os.path.join(*[
            f"{name}={_escape_path_value(v)}"
            for name, v in zip(self.job.partition_by, key)])
        self._writer = self.writer_cls(
            self._new_file(subdir), self.data_schema, self.writer_opts)
        self._current_key = key
        self.stats.partitions.append(subdir)

    def _close_current(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self.stats.num_files += 1
            self.stats.num_rows += self._writer.rows_written
            self.stats.num_bytes += self._writer.bytes_written
            self._writer = None

    def _close_writers(self) -> None:
        self._close_current()


def write_batches(batches: Iterator[ColumnarBatch], output_path: str,
                  file_format: str, schema: T.Schema,
                  partition_by: Sequence[str] = (), mode: str = "error",
                  options=None) -> WriteStats:
    """Single-task convenience driver for the full job protocol."""
    job = WriteJob(output_path, file_format, schema, partition_by, mode,
                   options)
    job.setup()
    writer = job.task_writer(0)
    try:
        for b in batches:
            writer.write(b)
        stats = writer.commit()
    except BaseException:
        writer.abort()
        job.abort()
        raise
    return job.commit([stats])
