"""Parquet scan + write.

Reference: `GpuParquetScan.scala` — footer parse, predicate-pushdown
row-group filtering (`filterBlocks:228`), schema clipping, host re-assembly
of the needed column chunks, then device decode; and
`GpuParquetFileFormat.scala` for the write side.

TPU design: pyarrow owns the host-side footer parse and column-chunk
decode (the role parquet-mr + cuDF's parquet reader share in the
reference).  Row-group pruning happens on footer statistics *before* any
data pages are read, so a selective filter touches only the matching
byte ranges; decoded Arrow tables upload to HBM as one padded batch.
Chunk selection follows Spark's convention: a row group belongs to the
split containing its byte midpoint, so concurrent splits of one file
never double-read a row group.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io import pushdown as PD
from spark_rapids_tpu.io import rebase as RB
from spark_rapids_tpu.io.scan import FileSplit, FormatReader


def _rg_midpoint(rg) -> int:
    """Midpoint of the row group's COMPRESSED byte range (parquet-mr's
    split-assignment rule): rg.total_byte_size is uncompressed and can
    point past EOF, which would assign the row group to no split."""
    first_col = rg.column(0)
    start = first_col.dictionary_page_offset
    if start is None:
        start = first_col.data_page_offset
    total = sum(rg.column(i).total_compressed_size
                for i in range(rg.num_columns))
    return start + total // 2


def _stats_of_row_group(rg, names: list[str]) -> dict[str, PD.ColumnStats]:
    stats: dict[str, PD.ColumnStats] = {}
    for i in range(rg.num_columns):
        col = rg.column(i)
        name = col.path_in_schema.split(".")[0]
        if name not in names:
            continue
        s = col.statistics
        if s is None:
            stats[name] = PD.ColumnStats(num_values=rg.num_rows)
            continue
        stats[name] = PD.ColumnStats(
            min=s.min if s.has_min_max else None,
            max=s.max if s.has_min_max else None,
            null_count=s.null_count if s.has_null_count else None,
            num_values=rg.num_rows)
    return stats


class ParquetFormat(FormatReader):
    extension = ".parquet"

    def __init__(self, rebase_mode: Optional[str] = None):
        # None = resolve from the active session conf at read time (the
        # conf collect() installs), via the shim-variant key
        self._explicit_rebase_mode = (
            None if rebase_mode is None else RB.normalize_mode(rebase_mode))

    @property
    def rebase_mode(self) -> str:
        if self._explicit_rebase_mode is not None:
            return self._explicit_rebase_mode
        from spark_rapids_tpu import config as C
        return self._mode_from_conf(C.get_active_conf())

    @staticmethod
    def _mode_from_conf(conf) -> str:
        from spark_rapids_tpu.shims import current_shims
        return current_shims(conf).parquet_rebase_read_mode(conf)

    def resolve_session(self, conf) -> "ParquetFormat":
        if self._explicit_rebase_mode is not None:
            return self
        return ParquetFormat(self._mode_from_conf(conf))

    def file_schema(self, path: str) -> T.Schema:
        import pyarrow.parquet as pq
        sch = pq.read_schema(path)
        return T.Schema(tuple(
            T.Field(f.name, T.from_arrow(f.type)) for f in sch))

    def read_split(self, split: FileSplit, read_schema: T.Schema,
                   filter_expr) -> Optional["object"]:
        import pyarrow.parquet as pq
        f = pq.ParquetFile(split.path)
        md = f.metadata
        names = [n for n in read_schema.names
                 if n in set(md.schema.to_arrow_schema().names)]
        if filter_expr is not None and \
                self.rebase_mode == "LEGACY" and \
                not RB.is_corrected_file(md.metadata, False):
            # legacy files store Julian-hybrid day numbers: row-group
            # stats cannot be compared against proleptic-Gregorian
            # filter literals — skip pruning, keep exactness.
            # EXCEPTION mode keeps pruning on purpose: the rebase check
            # runs over DECODED values only, exactly like the reference
            # (GpuParquetScan decodes the post-pruning blocks and only
            # then checks isDateTimeRebaseNeededRead), so a pruned
            # row group never raises there either.
            filter_expr = None
        keep: list[int] = []
        for rg_idx in range(md.num_row_groups):
            rg = md.row_group(rg_idx)
            if rg.num_rows == 0:
                continue
            mid = _rg_midpoint(rg)
            if not (split.start <= mid < split.start + split.length):
                continue
            if filter_expr is not None and PD.might_match(
                    filter_expr, _stats_of_row_group(rg, names)) is False:
                continue
            keep.append(rg_idx)
        if not keep:
            return None
        table = f.read_row_groups(keep, columns=names or None,
                                  use_threads=False)
        return RB.apply_read_rebase(table, md.metadata, self.rebase_mode,
                                    "Parquet")


# ---------------------------------------------------------------------------
# write side (reference GpuParquetFileFormat.scala / ColumnarOutputWriter)
_PA_COMPRESSION = {"none": "NONE", "uncompressed": "NONE", "snappy": "SNAPPY",
                   "gzip": "GZIP", "zstd": "ZSTD", "lz4": "LZ4"}


@dataclasses.dataclass
class ParquetWriterOptions:
    compression: str = "snappy"
    # None = resolve from the session conf via the shim-variant key
    # (spark.sql.legacy.parquet.datetimeRebaseModeInWrite and friends)
    rebase_mode: Optional[str] = None


# the version stamp makes readers' corrected-mode detection recognize our
# files (reference GpuParquetScan.scala:195-197; Spark stamps the same
# keys); it follows the emulated session version


class ParquetColumnarWriter:
    """Streams batches into one parquet file (reference
    `ColumnarOutputWriter.scala`: chunked device encode; here the encode is
    Arrow's parquet writer over the downloaded batch)."""

    def __init__(self, path: str, schema: T.Schema,
                 options: Optional[ParquetWriterOptions] = None):
        import pyarrow as pa
        import pyarrow.parquet as pq
        self.path = path
        self.schema = schema
        opts = options or ParquetWriterOptions()
        codec = _PA_COMPRESSION.get(opts.compression.lower())
        if codec is None:
            raise ValueError(
                f"unsupported parquet compression {opts.compression}")
        from spark_rapids_tpu import config as C
        conf = C.get_active_conf()
        mode = opts.rebase_mode
        if mode is None:
            from spark_rapids_tpu.shims import current_shims
            mode = current_shims(conf).parquet_rebase_write_mode(conf)
        self.rebase_mode = RB.normalize_mode(mode)
        if self.rebase_mode not in RB.READ_MODES:
            raise ValueError(
                f"unrecognized datetime rebase mode {mode}")
        meta = {RB.SPARK_VERSION_METADATA_KEY:
                str(conf[C.SPARK_VERSION]).encode("utf-8")}
        if self.rebase_mode == "LEGACY":
            meta[RB.SPARK_LEGACY_DATETIME_KEY] = b""
        self._arrow_schema = pa.schema(
            [pa.field(f.name, T.to_arrow(f.dtype)) for f in schema.fields])
        self._writer = pq.ParquetWriter(
            path, self._arrow_schema.with_metadata(meta),
            compression=codec.lower())
        self.rows_written = 0
        self.bytes_written = 0

    def write_batch(self, batch) -> None:
        RB.check_batch_write(batch, self.rebase_mode, "Parquet")
        table = batch.to_arrow().cast(self._arrow_schema)
        if self.rebase_mode == "LEGACY":
            table = RB.rebase_arrow_table_write(table)
        self._writer.write_table(table)
        self.rows_written += batch.num_rows

    def close(self) -> None:
        import os
        self._writer.close()
        self.bytes_written = os.path.getsize(self.path)
