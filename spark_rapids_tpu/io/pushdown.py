"""Statistics-based predicate pushdown for file scans.

Plays the role of the reference's row-group filtering (`filterBlocks`,
`GpuParquetScan.scala:228`, which delegates to parquet-mr's
`RowGroupFilter`) and ORC SearchArgument pushdown (`OrcFilters.scala`):
given per-chunk column statistics (min/max/null counts), decide whether a
row group / stripe *might* contain rows matching the scan filter.

Tri-state logic: `might_match` returns False only when the statistics
*prove* no row can match; anything unsupported or uncertain keeps the
chunk.  Filters are the same `Expression` AST the execs evaluate, so a
pushed-down filter is still re-applied post-scan for exactness.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from spark_rapids_tpu.exprs.base import (
    Alias, AttributeReference, Expression, Literal)
from spark_rapids_tpu.exprs import predicates as P


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Min/max are None when the writer recorded no stats (treat as
    unbounded).  `num_values` is the chunk row count."""
    min: Any = None
    max: Any = None
    null_count: Optional[int] = None
    num_values: Optional[int] = None

    @property
    def all_null(self) -> bool:
        return (self.null_count is not None and self.num_values is not None
                and self.null_count >= self.num_values)

    @property
    def has_nulls(self) -> bool:
        return self.null_count is None or self.null_count > 0


def might_match(filter_expr: Optional[Expression],
                stats: dict[str, ColumnStats]) -> bool:
    """True unless `stats` prove no row in the chunk satisfies the filter."""
    if filter_expr is None:
        return True
    return _may(filter_expr, stats)


def _col_of(e: Expression) -> Optional[str]:
    if isinstance(e, AttributeReference):
        return e.name
    if isinstance(e, Alias):
        return _col_of(e.child)
    return None


def _lit_of(e: Expression):
    if isinstance(e, Literal):
        return e.value
    return _MISSING


_MISSING = object()


def _cmp_args(e) -> Optional[tuple[str, Any, str]]:
    """Normalize `col OP lit` / `lit OP col` to (col, lit, op) with the
    comparison flipped when the literal is on the left."""
    op = type(e).__name__
    c, v = _col_of(e.left), _lit_of(e.right)
    if c is not None and v is not _MISSING:
        return c, v, op
    c, v = _col_of(e.right), _lit_of(e.left)
    if c is not None and v is not _MISSING:
        flip = {"LessThan": "GreaterThan", "GreaterThan": "LessThan",
                "LessThanOrEqual": "GreaterThanOrEqual",
                "GreaterThanOrEqual": "LessThanOrEqual",
                "EqualTo": "EqualTo"}
        return c, v, flip.get(op, op)
    return None


def _may(e: Expression, stats: dict[str, ColumnStats]) -> bool:
    if isinstance(e, P.And):
        return _may(e.left, stats) and _may(e.right, stats)
    if isinstance(e, P.Or):
        return _may(e.left, stats) or _may(e.right, stats)
    if isinstance(e, Literal):
        return e.value is not False and e.value is not None
    if isinstance(e, P.IsNull):
        c = _col_of(e.children()[0])
        if c is not None and c in stats:
            return stats[c].has_nulls
        return True
    if isinstance(e, P.IsNotNull):
        c = _col_of(e.children()[0])
        if c is not None and c in stats:
            return not stats[c].all_null
        return True
    if isinstance(e, P.InSet):
        c = _col_of(e.child)
        if c is None or c not in stats:
            return True
        return any(_range_may(stats[c], v, "EqualTo")
                   for v in e.values if v is not None)
    if isinstance(e, (P.EqualTo, P.LessThan, P.LessThanOrEqual,
                      P.GreaterThan, P.GreaterThanOrEqual)):
        norm = _cmp_args(e)
        if norm is None:
            return True
        col, val, op = norm
        if col not in stats or val is None:
            # comparison with null literal matches nothing, but stay
            # conservative for unknown columns
            return val is not None if col in stats else True
        return _range_may(stats[col], val, op)
    # Not / StartsWith / arbitrary expressions: keep the chunk
    return True


def _range_may(s: ColumnStats, val, op: str) -> bool:
    """Can any non-null value in [s.min, s.max] satisfy `value OP val`?"""
    if s.all_null:
        return False
    try:
        if op == "EqualTo":
            if s.min is not None and _lt(val, s.min):
                return False
            if s.max is not None and _lt(s.max, val):
                return False
        elif op == "LessThan":
            if s.min is not None and not _lt(s.min, val):
                return False
        elif op == "LessThanOrEqual":
            if s.min is not None and _lt(val, s.min):
                return False
        elif op == "GreaterThan":
            if s.max is not None and not _lt(val, s.max):
                return False
        elif op == "GreaterThanOrEqual":
            if s.max is not None and _lt(s.max, val):
                return False
    except TypeError:
        return True  # incomparable stat/literal types (e.g. after cast)
    return True


def _lt(a, b) -> bool:
    # date/timestamp stats may surface as datetime while literals are
    # int32 days / int64 micros; normalize via ordinal comparison
    import datetime
    import numpy as np
    if isinstance(a, (datetime.date, datetime.datetime, np.datetime64)):
        a = _to_epoch(a)
    if isinstance(b, (datetime.date, datetime.datetime, np.datetime64)):
        b = _to_epoch(b)
    return a < b


def _to_epoch(v):
    import datetime
    import numpy as np
    if isinstance(v, np.datetime64):
        return v.astype("datetime64[us]").astype(np.int64).item()
    if isinstance(v, datetime.datetime):
        return int(v.replace(tzinfo=datetime.timezone.utc).timestamp() * 1e6)
    return (v - datetime.date(1970, 1, 1)).days
