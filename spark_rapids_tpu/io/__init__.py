"""I/O layer: file scans (parquet/ORC/CSV) and writers (SURVEY.md §2.7).

Public helpers build planner-facing scan/write nodes; `accelerate()`
replaces them with the TPU execs.
"""
from __future__ import annotations

from typing import Optional, Sequence

from spark_rapids_tpu import types as T


def read_parquet(path: str, schema: Optional[T.Schema] = None):
    from spark_rapids_tpu.io.exec import CpuFileScan, ScanDescription
    return CpuFileScan(ScanDescription(path, "parquet", schema))


def read_orc(path: str, schema: Optional[T.Schema] = None):
    from spark_rapids_tpu.io.exec import CpuFileScan, ScanDescription
    return CpuFileScan(ScanDescription(path, "orc", schema))


def read_csv(path: str, schema: T.Schema, options=None):
    from spark_rapids_tpu.io.exec import CpuFileScan, ScanDescription
    return CpuFileScan(ScanDescription(path, "csv", schema, options))


def write(child, path: str, file_format: str,
          partition_by: Sequence[str] = (), mode: str = "error",
          options=None):
    from spark_rapids_tpu.io.exec import CpuWriteFiles
    return CpuWriteFiles(child, path, file_format, partition_by, mode,
                         options)
