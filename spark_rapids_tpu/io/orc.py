"""ORC scan + write (reference `GpuOrcScan.scala` /
`GpuOrcFileFormat.scala`).

The reference selects stripes by split range + SearchArgument pushdown and
re-encodes a minimal ORC file on the host for cuDF to decode.  Here
pyarrow's ORC reader owns the host decode; stripe selection follows the
same split convention (a stripe belongs to the split containing its byte
midpoint).  pyarrow exposes no per-stripe statistics, so pruning is
file-level only (schema-existence + split range); the filter is still
re-applied exactly by the downstream FilterExec.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.scan import FileSplit, FormatReader


class OrcFormat(FormatReader):
    extension = ".orc"

    def file_schema(self, path: str) -> T.Schema:
        from pyarrow import orc
        f = orc.ORCFile(path)
        return T.Schema(tuple(
            T.Field(fld.name, T.from_arrow(fld.type)) for fld in f.schema))

    def read_split(self, split: FileSplit, read_schema: T.Schema,
                   filter_expr) -> Optional["object"]:
        import pyarrow as pa
        from pyarrow import orc
        f = orc.ORCFile(split.path)
        names = [n for n in read_schema.names if n in f.schema.names]
        total = f.nstripes
        if total == 0:
            return None
        # pyarrow's ORCFile exposes no stripe byte offsets, so stripes map
        # onto splits by even byte apportionment of the file — deterministic
        # and non-overlapping across a file's splits, like the midpoint rule
        per = max(1, split.file_size // total)
        keep = [i for i in range(total)
                if split.start <= i * per + per // 2
                < split.start + split.length]
        if not keep:
            return None
        stripes = [f.read_stripe(i, columns=names or None) for i in keep]
        tbls = [pa.Table.from_batches([s]) if isinstance(s, pa.RecordBatch)
                else s for s in stripes]
        return pa.concat_tables(tbls)


# ---------------------------------------------------------------------------
@dataclasses.dataclass
class OrcWriterOptions:
    compression: str = "snappy"


_ORC_COMPRESSION = {"none": "UNCOMPRESSED", "uncompressed": "UNCOMPRESSED",
                    "snappy": "SNAPPY", "zlib": "ZLIB", "zstd": "ZSTD",
                    "lz4": "LZ4"}


class OrcColumnarWriter:
    """Streams batches into one ORC file (reference
    `GpuOrcFileFormat.scala`: cuDF chunked ORC encode)."""

    def __init__(self, path: str, schema: T.Schema,
                 options: Optional[OrcWriterOptions] = None):
        import pyarrow as pa
        from pyarrow import orc
        self.path = path
        self.schema = schema
        opts = options or OrcWriterOptions()
        codec = _ORC_COMPRESSION.get(opts.compression.lower())
        if codec is None:
            raise ValueError(f"unsupported ORC compression {opts.compression}")
        self._arrow_schema = pa.schema(
            [pa.field(f.name, T.to_arrow(f.dtype)) for f in schema.fields])
        self._writer = orc.ORCWriter(path, compression=codec)
        self.rows_written = 0
        self.bytes_written = 0

    def write_batch(self, batch) -> None:
        table = batch.to_arrow().cast(self._arrow_schema)
        self._writer.write(table)
        self.rows_written += batch.num_rows

    def close(self) -> None:
        import os
        self._writer.close()
        self.bytes_written = os.path.getsize(self.path)
