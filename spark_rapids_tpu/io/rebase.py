"""Hybrid-calendar (Julian <-> proleptic Gregorian) rebase detection.

Reference: `com/nvidia/spark/RebaseHelper.scala` (value-range checks on
read/write), the per-file corrected-mode resolution in
`GpuParquetScan.scala:194-210` (`isCorrectedRebaseMode` over the Spark
key-value footer metadata), and the write-side EXCEPTION check in
`GpuParquetFileFormat.scala:216-228`.

Spark 2.x / legacy Hive wrote dates and timestamps in the hybrid
Julian+Gregorian calendar; Spark 3.x uses the proleptic Gregorian
calendar.  Values at or after the Gregorian cutover (1582-10-15; in
non-UTC zones timestamp drift persists until 1900, but this engine is
UTC-only so the timestamp cutover is 1582-10-15 too) mean the same
instant in both calendars, so only values BEFORE the cutover are
ambiguous.  Like the
reference we never rebase on the accelerator: files/values that would
need it either raise the Spark 3.0 upgrade error (EXCEPTION / LEGACY
read modes) or are read verbatim (CORRECTED).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

# Days since unix epoch of 1582-10-15, the first proleptic-Gregorian day
# shared by both calendars (RebaseDateTime.lastSwitchJulianDay).
CUTOVER_DAY = -141427
# Timestamp ambiguity cutover for a UTC session: UTC has no pre-1900
# timezone-offset drift, so the switch instant is exactly the date
# cutover (RebaseDateTime.lastSwitchJulianTs for UTC).  The engine is
# UTC-only (same as the reference, GpuOverrides.scala:397-409); Spark's
# 1900-01-01 wording in the upgrade-error text covers non-UTC zones and
# stays in the messages only.
CUTOVER_MICROS = CUTOVER_DAY * 86400000000

# Spark's parquet footer key-value metadata keys
# (GpuParquetScan.scala:195-197).
SPARK_VERSION_METADATA_KEY = b"org.apache.spark.version"
SPARK_LEGACY_DATETIME_KEY = b"org.apache.spark.legacyDateTime"

READ_MODES = ("EXCEPTION", "CORRECTED", "LEGACY")


def _verify_utc_session() -> None:
    """CUTOVER_MICROS equals the date cutover ONLY for a UTC session
    (non-UTC zones drift pre-1900).  The engine is UTC-only (reference
    GpuOverrides.scala:397-409 tags timestamps off outside UTC); this
    guard keeps the constant from silently going stale if a session
    timezone conf is ever introduced (ADVICE r2)."""
    from spark_rapids_tpu import config as C
    tz = C.get_active_conf().get("spark.sql.session.timeZone", "UTC")
    if tz not in ("UTC", "Etc/UTC", "GMT", "+00:00", "Z"):
        raise AssertionError(
            f"legacy-timestamp rebase detection requires a UTC session; "
            f"got spark.sql.session.timeZone={tz!r}")


class SparkUpgradeError(RuntimeError):
    """Analog of Spark's SparkUpgradeException (SPARK-31404)."""


def normalize_mode(raw) -> str:
    """Map a conf value to a rebase mode: Spark 3.0.0's boolean-era keys
    use true/false, 3.0.1+ use mode names (shim layer picks the key)."""
    s = str(raw).upper()
    if s == "TRUE":
        return "LEGACY"
    if s == "FALSE":
        return "CORRECTED"
    return s


def new_rebase_exception_read(fmt: str = "Parquet") -> SparkUpgradeError:
    """Reference `RebaseHelper.newRebaseExceptionInRead`."""
    return SparkUpgradeError(
        f"You may get a different result due to the upgrading of Spark"
        f" 3.0: reading dates before 1582-10-15 or timestamps before"
        f" 1900-01-01T00:00:00Z from {fmt} files can be ambiguous, as the"
        f" files may be written by a legacy hybrid calendar. The"
        f" accelerator does not support reading these 'LEGACY' files;"
        f" set the datetime rebase mode to 'CORRECTED' to read the"
        f" values as-is (SPARK-31404).")


def new_rebase_exception_write(fmt: str = "Parquet") -> SparkUpgradeError:
    """Reference `DataSourceUtils.newRebaseExceptionInWrite` path used by
    `GpuParquetFileFormat.scala:224`."""
    return SparkUpgradeError(
        f"You may get a different result due to the upgrading of Spark"
        f" 3.0: writing dates before 1582-10-15 or timestamps before"
        f" 1900-01-01T00:00:00Z into {fmt} files can be dangerous, as the"
        f" files may be read by legacy systems that use the hybrid"
        f" calendar. Set the datetime rebase mode to 'CORRECTED' to"
        f" write the values as-is (SPARK-31404).")


def is_corrected_file(kv_meta: Optional[dict],
                      corrected_mode_conf: bool) -> bool:
    """Per-file resolution (reference `isCorrectedRebaseMode`
    `GpuParquetScan.scala:199-210`): files written by Spark >= 3.0.0
    WITHOUT the legacyDateTime marker are already proleptic Gregorian;
    files with no Spark version marker inherit the session mode."""
    if kv_meta:
        version = kv_meta.get(SPARK_VERSION_METADATA_KEY)
        if version is not None:
            if isinstance(version, bytes):
                version = version.decode("utf-8", "replace")
            return (_version_at_least(version, (3, 0, 0))
                    and kv_meta.get(SPARK_LEGACY_DATETIME_KEY) is None)
    return corrected_mode_conf


def _version_at_least(version: str, floor: tuple) -> bool:
    """Numeric component-wise compare ("10.0.0" > "3.0.0"; suffixes like
    "-SNAPSHOT" ignored)."""
    parts = []
    for tok in version.split(".")[:3]:
        digits = ""
        for ch in tok:
            if not ch.isdigit():
                break
            digits += ch
        if not digits:
            return False
        parts.append(int(digits))
    return tuple(parts) >= floor


def _arrow_col_needs_rebase(col) -> bool:
    _verify_utc_session()
    import pyarrow as pa
    import pyarrow.compute as pc
    t = col.type
    if pa.types.is_date32(t):
        lo, cut = pc.min(col.cast(pa.int32())).as_py(), CUTOVER_DAY
    elif pa.types.is_timestamp(t):
        lo, cut = pc.min(col.cast(pa.timestamp("us")).cast(
            pa.int64())).as_py(), CUTOVER_MICROS
    else:
        return False
    return lo is not None and lo < cut


def arrow_table_needs_rebase(table) -> bool:
    """Read-side value check (reference
    `RebaseHelper.isDateTimeRebaseNeededRead`): any date/timestamp value
    before the cutover."""
    return any(_arrow_col_needs_rebase(table.column(i))
               for i in range(table.num_columns))


def apply_read_rebase(table, kv_meta: Optional[dict], mode: str,
                      fmt: str = "Parquet"):
    """The whole read-side decision (reference
    `GpuParquetScan.scala:247-249` + RebaseHelper): CORRECTED reads
    verbatim; files already in the proleptic calendar skip checks; LEGACY
    (CPU fallback engine only — the planner keeps LEGACY scans off the
    accelerator) performs the Julian->Gregorian rebase; EXCEPTION raises
    when pre-cutover values are present.  Returns the (possibly rebased)
    table."""
    mode = normalize_mode(mode)
    if mode not in READ_MODES:
        raise ValueError(f"{mode} is not a supported datetime rebase "
                         "mode (EXCEPTION, CORRECTED, LEGACY)")
    if mode == "CORRECTED":
        return table
    if is_corrected_file(kv_meta, corrected_mode_conf=False):
        return table
    if mode == "LEGACY":
        return rebase_arrow_table_read(table)
    if arrow_table_needs_rebase(table):
        raise new_rebase_exception_read(fmt)
    return table


def batch_needs_rebase(batch) -> bool:
    _verify_utc_session()
    """Write-side value check over a device ColumnarBatch (reference
    `RebaseHelper.isDateTimeRebaseNeededWrite`)."""
    from spark_rapids_tpu import types as T
    n = batch.num_rows
    for name in batch.schema.names:
        vec = batch.column(name)
        if vec.dtype.id not in (T.TypeId.DATE32, T.TypeId.TIMESTAMP_US):
            continue
        vals = np.asarray(vec.data[:n])
        valid = np.asarray(vec.validity[:n])
        if not valid.any():
            continue
        lo = int(vals[valid].min())
        cut = (CUTOVER_DAY if vec.dtype.id == T.TypeId.DATE32
               else CUTOVER_MICROS)
        if lo < cut:
            return True
    return False


def check_batch_write(batch, mode: str, fmt: str = "Parquet") -> None:
    """EXCEPTION write mode raises on pre-cutover values
    (`GpuParquetFileFormat.scala:221-228`); CORRECTED writes verbatim;
    LEGACY never reaches the accelerator (tagged off at planning,
    `GpuParquetFileFormat.scala:83`)."""
    if normalize_mode(mode) != "EXCEPTION":
        return
    if batch_needs_rebase(batch):
        raise new_rebase_exception_write(fmt)


# ---------------------------------------------------------------------------
# Actual calendar rebasing, used by the CPU fallback engine under LEGACY
# mode (the role Spark's RebaseDateTime plays for CPU Spark; the
# accelerator itself never rebases, matching the reference).  All math is
# vectorized int64 Julian-Day-Number arithmetic; UTC sessions only (the
# engine is UTC-only like the reference, GpuOverrides.scala:397-409), so
# timestamp rebase reduces to the calendar-day shift.

_EPOCH_JDN = 2440588  # JDN of 1970-01-01 (proleptic Gregorian)
_MICROS_PER_DAY = 86400000000


def _jdn_from_ymd(y, m, d, julian: bool):
    a = (14 - m) // 12
    yy = y + 4800 - a
    mm = m + 12 * a - 3
    jdn = d + (153 * mm + 2) // 5 + 365 * yy + yy // 4
    if julian:
        return jdn - 32083
    return jdn - yy // 100 + yy // 400 - 32045


def _ymd_from_jdn(jdn, julian: bool):
    f = jdn + 1401
    if not julian:
        f = f + (((4 * jdn + 274277) // 146097) * 3) // 4 - 38
    e = 4 * f + 3
    g = (e % 1461) // 4
    h = 5 * g + 2
    d = (h % 153) // 5 + 1
    m = (h // 153 + 2) % 12 + 1
    y = e // 1461 - 4716 + (14 - m) // 12
    return y, m, d


def _rebase_days(days: np.ndarray, to_julian: bool) -> np.ndarray:
    """Re-label pre-cutover epoch days between calendars: decompose the
    day number into (y, m, d) under the source calendar, re-encode the
    same label under the target calendar."""
    days = np.asarray(days, np.int64)
    old = days < CUTOVER_DAY
    if not old.any():
        return days
    jdn = days + _EPOCH_JDN
    y, m, d = _ymd_from_jdn(jdn, julian=not to_julian)
    out = _jdn_from_ymd(y, m, d, julian=to_julian) - _EPOCH_JDN
    return np.where(old, out, days)


def rebase_julian_to_gregorian_days(days: np.ndarray) -> np.ndarray:
    """Read-side LEGACY rebase (RebaseDateTime.rebaseJulianToGregorianDays)."""
    return _rebase_days(days, to_julian=False)


def rebase_gregorian_to_julian_days(days: np.ndarray) -> np.ndarray:
    """Write-side LEGACY rebase (RebaseDateTime.rebaseGregorianToJulianDays).
    Labels inside the 1582-10-05..14 cutover gap do not exist in the
    hybrid calendar; like Spark we let them land on the Julian encoding
    of the same label (which aliases days after the gap)."""
    return _rebase_days(days, to_julian=True)


def _rebase_micros(micros: np.ndarray, to_julian: bool) -> np.ndarray:
    micros = np.asarray(micros, np.int64)
    days = micros // _MICROS_PER_DAY
    shifted = _rebase_days(days, to_julian)
    return micros + (shifted - days) * _MICROS_PER_DAY


def rebase_julian_to_gregorian_micros(micros: np.ndarray) -> np.ndarray:
    return _rebase_micros(micros, to_julian=False)


def rebase_gregorian_to_julian_micros(micros: np.ndarray) -> np.ndarray:
    return _rebase_micros(micros, to_julian=True)


def _rebase_arrow_table(table, to_julian: bool):
    import pyarrow as pa
    out = table
    for i, col in enumerate(table.columns):
        t = col.type
        if pa.types.is_date32(t):
            ints = col.cast(pa.int32()).combine_chunks().to_numpy(
                zero_copy_only=False)
            mask = np.asarray(col.is_null())
            rb = _rebase_days(np.where(mask, 0, ints),
                              to_julian).astype(np.int32)
            arr = pa.array(rb, mask=mask).cast(pa.date32())
        elif pa.types.is_timestamp(t):
            ints = col.cast(pa.timestamp("us")).cast(
                pa.int64()).combine_chunks().to_numpy(zero_copy_only=False)
            mask = np.asarray(col.is_null())
            rb = _rebase_micros(np.where(mask, 0, ints), to_julian)
            arr = pa.array(rb, mask=mask).cast(pa.timestamp("us")).cast(t)
        else:
            continue
        out = out.set_column(i, table.schema.field(i).name, arr)
    return out


def rebase_arrow_table_read(table):
    """Julian->Gregorian rebase of every date/timestamp column of a
    decoded Arrow table (LEGACY read of a legacy file on the CPU
    fallback engine)."""
    return _rebase_arrow_table(table, to_julian=False)


def rebase_arrow_table_write(table):
    """Gregorian->Julian rebase before encoding (LEGACY write on the CPU
    fallback engine)."""
    return _rebase_arrow_table(table, to_julian=True)
