"""Scan and write physical operators + their device-neutral plan nodes.

Reference execs: `GpuFileSourceScanExec.scala` (v1 scan),
`GpuBatchScanExec.scala` (v2 scan — same reader factories here),
`GpuDataWritingCommandExec.scala` / `GpuInsertIntoHadoopFsRelationCommand`.

The CpuFileScan / CpuWriteFiles nodes are the planner-facing inputs
(Spark's FileSourceScanExec / InsertIntoHadoopFsRelationCommand analogs);
override rules in plan/overrides.py convert them to the TPU execs, with
per-format enable confs and CSV option guards deciding fallback.
"""
from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np
import pandas as pd

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, empty_batch
from spark_rapids_tpu.exec.base import LeafExec, TpuExec, UnaryExecBase
from spark_rapids_tpu.exprs.base import Expression
from spark_rapids_tpu.io.csv import CsvFormat, CsvOptions
from spark_rapids_tpu.io.orc import OrcFormat
from spark_rapids_tpu.io.parquet import ParquetFormat
from spark_rapids_tpu.io.scan import (
    FilePartition, FormatReader, MultiFileCoalescingReader, discover_files)
from spark_rapids_tpu.io.writer import WriteJob, WriteStats
from spark_rapids_tpu.plan.nodes import CpuNode, normalize_df


def make_format(file_format: str, schema: Optional[T.Schema] = None,
                options=None) -> FormatReader:
    if file_format == "parquet":
        # the hybrid-calendar read mode is frozen from the session conf
        # by FormatReader.resolve_session at execution time (reference
        # GpuParquetScan.scala:225-226)
        return ParquetFormat()
    if file_format == "orc":
        return OrcFormat()
    if file_format == "csv":
        assert schema is not None, "CSV requires an explicit schema"
        return CsvFormat(schema, options or CsvOptions())
    raise ValueError(f"unsupported scan format {file_format}")


class ScanDescription:
    """Planned scan shared by the CPU node and the TPU exec: files
    discovered, splits packed, schemas resolved."""

    _EXTENSIONS = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv"}

    def __init__(self, path: str, file_format: str,
                 schema: Optional[T.Schema] = None, options=None,
                 conf: Optional[C.RapidsConf] = None):
        conf = conf or C.get_active_conf()
        self.path = path
        self.file_format = file_format
        self.options = options
        files, self.part_schema = discover_files(
            path, self._EXTENSIONS[file_format])
        # partition columns never live in the data files — strip them
        # BEFORE building the reader (the CSV parser needs the exact
        # per-file column list)
        if schema is not None:
            self.data_schema = T.Schema(tuple(
                f for f in schema.fields
                if f.name not in self.part_schema.names))
        else:
            if not files:
                raise FileNotFoundError(f"no {file_format} files in {path}")
            probe = make_format(file_format, None, options)
            self.data_schema = T.Schema(tuple(
                f for f in probe.file_schema(files[0].path).fields
                if f.name not in self.part_schema.names))
        self.reader = make_format(file_format, self.data_schema, options)
        #: multi-file coalescing reader toggle (reference
        #: supportsSmallFileOpt; flipped via
        #: shims.copy_scan_with_small_file_opt)
        self.small_file_opt = True
        from spark_rapids_tpu.shims import current_shims
        self.partitions = current_shims(conf).plan_file_partitions(
            files, conf[C.MAX_PARTITION_BYTES], conf[C.FILE_OPEN_COST],
            min_partitions=conf[C.MIN_PARTITION_NUM])
        self.output_schema = T.Schema(
            tuple(self.data_schema.fields) + tuple(self.part_schema.fields))

    def pruned(self, names: set) -> "ScanDescription":
        """Column-pruned copy (Catalyst schema-pruning analog): the reader
        only decodes the requested columns' chunks/stripes."""
        import copy
        sd = copy.copy(self)
        sd.data_schema = T.Schema(tuple(
            f for f in self.data_schema.fields if f.name in names))
        sd.part_schema = T.Schema(tuple(
            f for f in self.part_schema.fields if f.name in names))
        sd.reader = make_format(self.file_format, sd.data_schema,
                                self.options)
        sd.output_schema = T.Schema(
            tuple(sd.data_schema.fields) + tuple(sd.part_schema.fields))
        return sd


class CpuFileScan(CpuNode):
    """Planner-facing scan node; also the CPU fallback execution."""

    def __init__(self, scan: ScanDescription):
        super().__init__()
        self.scan = scan
        self.pushed_filter: Optional[Expression] = None

    def name(self) -> str:
        return f"CpuFileScan[{self.scan.file_format}]"

    def describe(self) -> str:
        return (f"CpuFileScan[{self.scan.file_format}]({self.scan.path}, "
                f"{len(self.scan.partitions)} partitions)")

    def output_schema(self) -> T.Schema:
        return self.scan.output_schema

    def output_partition_count(self) -> int:
        return max(1, len(self.scan.partitions))

    def execute(self) -> list[Iterator[pd.DataFrame]]:
        return [self._read_partition(p) for p in self.scan.partitions]

    def _read_partition(self, part: FilePartition
                        ) -> Iterator[pd.DataFrame]:
        scan = self.scan
        for split in part.splits:
            table = scan.reader.read_split(split, scan.data_schema,
                                           self.pushed_filter)
            if table is None or table.num_rows == 0:
                continue
            df = table.to_pandas()
            # storage model: dates as int32 days, timestamps int64 micros
            for f in scan.data_schema.fields:
                if f.name not in df.columns:
                    df[f.name] = pd.Series([pd.NA] * len(df))
                elif f.dtype.id == T.TypeId.DATE32 and \
                        df[f.name].dtype.kind == "O":
                    df[f.name] = pd.array(
                        [None if v is None else
                         (v - __import__("datetime").date(1970, 1, 1)).days
                         for v in df[f.name]], "Int32")
            pvals = dict(split.partition_values)
            for f in scan.part_schema.fields:
                df[f.name] = pvals.get(f.name)
            yield normalize_df(df[list(scan.output_schema.names)],
                               scan.output_schema)


class TpuFileSourceScanExec(LeafExec):
    """Columnar scan exec (reference `GpuFileSourceScanExec.scala:58`).
    One output partition per FilePartition; host buffering overlaps device
    work via the shared thread pool."""

    def __init__(self, scan: ScanDescription,
                 pushed_filter: Optional[Expression] = None,
                 conf: Optional[C.RapidsConf] = None):
        super().__init__()
        self.scan = scan
        self.pushed_filter = pushed_filter
        self.conf = conf or C.get_active_conf()

    def output_schema(self) -> T.Schema:
        return self.scan.output_schema

    def output_partition_count(self) -> int:
        return max(1, len(self.scan.partitions))

    def describe(self) -> str:
        pf = f", pushed={self.pushed_filter!r}" if self.pushed_filter else ""
        return (f"TpuFileSourceScanExec[{self.scan.file_format}]"
                f"({self.scan.path}{pf})")

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        for it in self.execute_partitions():
            yield from it

    def execute_partitions(self) -> list[Iterator[ColumnarBatch]]:
        # scan->compute pipeline break: a producer thread decodes and
        # uploads batch k+1 while the consumer's kernels chew batch k
        # (lazy-started, so partitions don't all begin at plan build).
        # Prefetch conf resolves at execution time (active session), not
        # from the plan-time self.conf snapshot.
        from spark_rapids_tpu.exec.pipeline import maybe_prefetch
        outs = []
        for p in self.scan.partitions:
            outs.append(maybe_prefetch(
                self._partition_iter(p), label="scan",
                metrics=self.metrics))
        return outs or [iter(())]

    def _partition_iter(self, part: FilePartition
                        ) -> Iterator[ColumnarBatch]:
        import dataclasses as _dc
        if getattr(self.scan, "small_file_opt", True):
            groups = [part]
        else:
            # coalescing disabled (reference
            # copyFileSourceScanExec(supportsSmallFileOpt=false)): each
            # split decodes through its own reader
            groups = [_dc.replace(part, splits=(s,)) for s in part.splits]
        for g in groups:
            reader = MultiFileCoalescingReader(
                self.scan.reader, g, self.scan.data_schema,
                self.scan.part_schema, self.pushed_filter, self.conf,
                metrics=self.metrics)
            for batch in reader:
                self.update_output_metrics(batch)
                yield batch


# ---------------------------------------------------------------------------
_WRITE_SCHEMA = T.Schema.of(("num_files", T.INT64), ("num_rows", T.INT64),
                            ("num_bytes", T.INT64))


class CpuWriteFiles(CpuNode):
    """InsertIntoHadoopFsRelationCommand analog; executes the write on
    whichever engine the child landed on.  Output: one summary row."""

    def __init__(self, child: CpuNode, path: str, file_format: str,
                 partition_by: Sequence[str] = (), mode: str = "error",
                 options=None):
        super().__init__(child)
        self.path = path
        self.file_format = file_format
        self.partition_by = list(partition_by)
        self.mode = mode
        self.options = options

    def name(self) -> str:
        return f"CpuWriteFiles[{self.file_format}]"

    def output_schema(self) -> T.Schema:
        return _WRITE_SCHEMA

    def output_partition_count(self) -> int:
        return 1

    def execute(self) -> list[Iterator[pd.DataFrame]]:
        schema = self.child.output_schema()
        job = WriteJob(self.path, self.file_format, schema,
                       self.partition_by, self.mode, self.options)
        job.setup()
        stats_list = []
        try:
            for task_id, it in enumerate(self.child.execute()):
                writer = job.task_writer(task_id)
                try:
                    for df in it:
                        writer.write(ColumnarBatch.from_numpy(
                            _df_data(df, schema), schema,
                            _df_validity(df, schema)))
                except BaseException:
                    writer.abort()  # this attempt only
                    raise
                stats_list.append(writer.commit())
        except BaseException:
            job.abort()
            raise
        total = job.commit(stats_list)
        return [iter([_stats_df(total)])]


def _df_data(df: pd.DataFrame, schema: T.Schema) -> dict:
    data = {}
    for f in schema.fields:
        s = df[f.name]
        if f.dtype.is_string:
            data[f.name] = np.array(
                [None if v is None or v is pd.NA else v for v in s],
                dtype=object)
        else:
            arr = s.to_numpy(dtype=f.dtype.storage_dtype, na_value=0)
            data[f.name] = arr
    return data


def _df_validity(df: pd.DataFrame, schema: T.Schema) -> dict:
    return {f.name: ~df[f.name].isna().to_numpy()
            for f in schema.fields}


def _stats_df(stats: WriteStats) -> pd.DataFrame:
    return pd.DataFrame({"num_files": pd.array([stats.num_files], "Int64"),
                         "num_rows": pd.array([stats.num_rows], "Int64"),
                         "num_bytes": pd.array([stats.num_bytes], "Int64")})


class TpuWriteFilesExec(UnaryExecBase):
    """Columnar write exec (reference `GpuDataWritingCommandExec.scala`).
    Tasks stream child batches straight from HBM into the host encoder."""

    def __init__(self, node: CpuWriteFiles, child: TpuExec):
        super().__init__(child)
        self.node = node

    def output_schema(self) -> T.Schema:
        return _WRITE_SCHEMA

    def output_partition_count(self) -> int:
        return 1

    def describe(self) -> str:
        return (f"TpuWriteFilesExec[{self.node.file_format}]"
                f"({self.node.path})")

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        node = self.node
        schema = self.child.output_schema()
        job = WriteJob(node.path, node.file_format, schema,
                       node.partition_by, node.mode, node.options)
        job.setup()
        stats_list = []
        try:
            for task_id, it in enumerate(self.child.execute_partitions()):
                writer = job.task_writer(task_id)
                try:
                    with self.metrics.timed():
                        for batch in it:
                            writer.write(batch)
                except BaseException:
                    writer.abort()  # this attempt only
                    raise
                stats_list.append(writer.commit())
        except BaseException:
            job.abort()
            raise
        total = job.commit(stats_list)
        out = ColumnarBatch.from_numpy(
            {"num_files": np.array([total.num_files], np.int64),
             "num_rows": np.array([total.num_rows], np.int64),
             "num_bytes": np.array([total.num_bytes], np.int64)},
            _WRITE_SCHEMA)
        self.update_output_metrics(out)
        yield out

    def execute_partitions(self):
        return [self.execute_columnar()]
