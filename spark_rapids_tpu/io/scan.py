"""Common file-scan infrastructure.

Mirrors the reference's scan plumbing (SURVEY.md §2.7):
  - `FileSplit`/`plan_file_partitions`: Spark's FilePartition bin-packing
    (maxSplitBytes formula) that `GpuFileSourceScanExec.scala` reuses.
  - `discover_files`: hive-style partition-value discovery (key=value dirs),
    the input Spark's catalog provides in the reference.
  - `append_partition_values`: per-batch partition-value columns
    (reference `ColumnarPartitionReaderWithPartitionValues`).
  - `MultiFileCoalescingReader`: thread-pool host-side buffering so file
    I/O overlaps device compute (reference `MultiFileThreadPoolFactory`,
    `GpuParquetScan.scala:647-698` small-file optimization).

TPU boundary discipline (reference `GpuParquetScan.scala:1102`): all host
parsing/decoding runs *before* the task acquires the TPU semaphore; only
the final host→HBM upload holds it.
"""
from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import os
import threading
from typing import Any, Iterator, Optional, Sequence

import numpy as np

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import ColumnVector


@dataclasses.dataclass(frozen=True)
class FileSplit:
    """A byte range of one file plus its hive partition values."""
    path: str
    start: int
    length: int
    file_size: int
    partition_values: tuple[tuple[str, Any], ...] = ()


@dataclasses.dataclass(frozen=True)
class FilePartition:
    """One task's worth of splits (Spark FilePartition)."""
    index: int
    splits: tuple[FileSplit, ...]


def plan_file_partitions(files: Sequence[FileSplit],
                         max_partition_bytes: int,
                         open_cost_bytes: int,
                         min_partitions: int = 1,
                         split_files: bool = True) -> list[FilePartition]:
    """Spark's split packing: split each file at maxSplitBytes, sort splits
    descending, first-fit into partitions of maxSplitBytes (each split
    costs its length + open cost).  `split_files=False` packs whole files
    only (the Databricks getPartitionSplitFiles drift — shim-routed)."""
    total = sum(f.length for f in files) + open_cost_bytes * len(files)
    bytes_per_core = max(1, total // max(1, min_partitions))
    max_split = min(max_partition_bytes, max(open_cost_bytes,
                                             bytes_per_core))
    splits: list[FileSplit] = []
    for f in files:
        if not split_files:
            splits.append(f)
            continue
        off = f.start
        remaining = f.length
        while remaining > 0:
            size = min(max_split, remaining)
            splits.append(dataclasses.replace(f, start=off, length=size))
            off += size
            remaining -= size
    splits.sort(key=lambda s: s.length, reverse=True)
    partitions: list[list[FileSplit]] = []
    sizes: list[int] = []
    cur: list[FileSplit] = []
    cur_size = 0
    for s in splits:
        # Spark's rule: close on length overflow, but account the open
        # cost in the accumulated size (FilePartition.getFilePartitions)
        if cur and cur_size + s.length > max_split:
            partitions.append(cur)
            sizes.append(cur_size)
            cur, cur_size = [], 0
        cur.append(s)
        cur_size += s.length + open_cost_bytes
    if cur:
        partitions.append(cur)
    if not partitions:
        partitions = [[]]
    return [FilePartition(i, tuple(p)) for i, p in enumerate(partitions)]


# ---------------------------------------------------------------------------
# hive-style partition discovery
def discover_files(path: str, extension: Optional[str] = None
                   ) -> tuple[list[FileSplit], T.Schema]:
    """Walk `path`; parse key=value directory components into partition
    values.  Returns (files, partition_schema).  Partition value types are
    inferred (int64 else string), matching Spark's default inference."""
    files: list[tuple[str, int, tuple[tuple[str, str], ...]]] = []
    if os.path.isfile(path):
        files.append((path, os.path.getsize(path), ()))
    else:
        for root, dirs, names in os.walk(path):
            dirs[:] = sorted(d for d in dirs if not d.startswith(("_", ".")))
            rel = os.path.relpath(root, path)
            pvals = []
            if rel != ".":
                for comp in rel.split(os.sep):
                    if "=" in comp:
                        k, v = comp.split("=", 1)
                        pvals.append((k, v))
            for name in sorted(names):
                if name.startswith(("_", ".")):
                    continue
                if extension and not name.endswith(extension):
                    continue
                full = os.path.join(root, name)
                files.append((full, os.path.getsize(full), tuple(pvals)))
    part_names: list[str] = []
    for _, _, pvals in files:
        for k, _ in pvals:
            if k not in part_names:
                part_names.append(k)
    part_fields = []
    typed_files = []
    inferred: dict[str, T.DataType] = {}
    for k in part_names:
        vals = [dict(pv).get(k) for _, _, pv in files]
        inferred[k] = _infer_partition_type([v for v in vals if v is not None])
        part_fields.append(T.Field(k, inferred[k]))
    for fpath, fsize, pvals in files:
        d = dict(pvals)
        typed = tuple((k, _convert_partition_value(d.get(k), inferred[k]))
                      for k in part_names)
        typed_files.append(FileSplit(fpath, 0, fsize, fsize, typed))
    return typed_files, T.Schema(tuple(part_fields))


def _infer_partition_type(raw: list[str]) -> T.DataType:
    try:
        for v in raw:
            int(v)
        return T.INT64
    except (TypeError, ValueError):
        return T.STRING


def _convert_partition_value(raw: Optional[str], dt: T.DataType):
    if raw is None or raw == "__HIVE_DEFAULT_PARTITION__":
        return None
    if dt == T.INT64:
        return int(raw)
    return raw


def append_partition_values(batch: ColumnarBatch,
                            part_schema: T.Schema,
                            values: tuple[tuple[str, Any], ...]
                            ) -> ColumnarBatch:
    """Widen a data batch with broadcast partition-value columns."""
    if not len(part_schema):
        return batch
    vals = dict(values)
    cols = list(batch.columns)
    fields = list(batch.schema.fields)
    for f in part_schema.fields:
        cols.append(ColumnVector.from_scalar(
            vals.get(f.name), f.dtype, batch.capacity, batch.num_rows))
        fields.append(f)
    return ColumnarBatch(T.Schema(tuple(fields)), cols, batch.num_rows)


# ---------------------------------------------------------------------------
class FormatReader:
    """Per-format host decode: split -> pyarrow Table (or None when the
    split prunes to nothing).  Implementations must be thread-safe; they
    run on the buffering pool."""

    #: file extension used by partition discovery
    extension: Optional[str] = None

    def read_split(self, split: FileSplit, read_schema: T.Schema,
                   filter_expr) -> Optional["object"]:
        raise NotImplementedError

    def file_schema(self, path: str) -> T.Schema:
        raise NotImplementedError

    def resolve_session(self, conf: C.RapidsConf) -> "FormatReader":
        """Freeze conf-dependent reader state before dispatch to the
        buffering pool (the active conf is thread-local and does not
        reach pool threads).  Default: nothing to freeze."""
        return self


_POOL_LOCK = threading.Lock()
_POOLS: dict[int, concurrent.futures.ThreadPoolExecutor] = {}


def _buffering_pool(num_threads: int):
    """Shared host-read pool (reference MultiFileThreadPoolFactory:647 —
    one pool per executor, sized by conf).  Pools are keyed by size and
    never shut down while readers may hold them (distinct sizes are rare:
    one per configured numThreads value)."""
    with _POOL_LOCK:
        pool = _POOLS.get(num_threads)
        if pool is None:
            pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=num_threads,
                thread_name_prefix="tpu-file-buffer")
            _POOLS[num_threads] = pool
        return pool


class MultiFileCoalescingReader:
    """Reads a partition's splits on the buffering pool, coalescing the
    decoded host tables into device batches capped by the reader batch
    limits.  The semaphore is taken only around host→HBM upload."""

    def __init__(self, reader: FormatReader, partition: FilePartition,
                 read_schema: T.Schema, part_schema: T.Schema,
                 filter_expr, conf: C.RapidsConf, metrics=None):
        self.reader = reader.resolve_session(conf)
        self.partition = partition
        self.read_schema = read_schema
        self.part_schema = part_schema
        self.filter_expr = filter_expr
        self.conf = conf
        self.metrics = metrics

    def __iter__(self) -> Iterator[ColumnarBatch]:
        import time
        num_threads = self.conf[C.MULTITHREAD_READ_NUM_THREADS]
        max_rows = min(self.conf[C.MAX_READER_BATCH_ROWS],
                       self.conf[C.MAX_BATCH_ROWS])
        max_bytes = self.conf[C.MAX_READER_BATCH_BYTES]
        pool = _buffering_pool(num_threads)
        t0 = time.monotonic()
        # bounded in-flight window: decoded host tables are consumed in
        # split order, so only ~2x the pool's width is buffered at once
        # (the reference throttles with a bounded buffer pool likewise)
        window = max(2, num_threads * 2)
        splits = list(self.partition.splits)
        futures: collections.deque = collections.deque()
        next_submit = 0

        def _top_up():
            nonlocal next_submit
            while next_submit < len(splits) and len(futures) < window:
                futures.append(pool.submit(
                    self.reader.read_split, splits[next_submit],
                    self.read_schema, self.filter_expr))
                next_submit += 1

        _top_up()
        # accumulate host tables per partition-value group; flush when the
        # next table would exceed the reader batch limits
        pending: list = []
        pending_rows = 0
        pending_bytes = 0
        pending_pvals: Optional[tuple] = None
        for split in splits:
            fut = futures.popleft()
            table = fut.result()
            _top_up()
            if table is None or table.num_rows == 0:
                continue
            if (pending and
                    (pending_pvals != split.partition_values or
                     pending_rows + table.num_rows > max_rows or
                     pending_bytes + table.nbytes > max_bytes)):
                yield self._upload(pending, pending_pvals, t0)
                t0 = time.monotonic()
                pending, pending_rows, pending_bytes = [], 0, 0
            pending.append(table)
            pending_pvals = split.partition_values
            pending_rows += table.num_rows
            pending_bytes += table.nbytes
        if pending:
            yield self._upload(pending, pending_pvals, t0)

    def _upload(self, tables: list, pvals, t0) -> ColumnarBatch:
        import time

        import pyarrow as pa

        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        from spark_rapids_tpu.utils import metrics as M
        table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        buffer_time = time.monotonic() - t0
        TpuSemaphore.get().acquire_if_necessary()
        t1 = time.monotonic()
        batch = ColumnarBatch.from_arrow(table)
        batch = _conform(batch, self.read_schema)
        batch = append_partition_values(batch, self.part_schema, pvals or ())
        if self.metrics is not None:
            self.metrics.add(M.BUFFER_TIME, buffer_time)
            self.metrics.add(M.DECODE_TIME, time.monotonic() - t1)
            # per-node movement attribution: host->HBM bytes this scan
            # shipped (EXPLAIN-with-metrics renders it; the query-wide
            # total lives on the ledger's upload edge)
            self.metrics.add(M.UPLOAD_BYTES, batch.device_size_bytes())
        return batch


def _conform(batch: ColumnarBatch, schema: T.Schema) -> ColumnarBatch:
    """Schema evolution (reference `evolveSchemaIfNeededAndClose`
    `GpuParquetScan.scala:529`): reorder to the read schema, add missing
    columns as null, cast widened types."""
    cols = []
    for f in schema.fields:
        try:
            idx = batch.schema.index(f.name)
        except KeyError:
            cols.append(ColumnVector.from_scalar(
                None, f.dtype, batch.capacity, batch.num_rows))
            continue
        c = batch.columns[idx]
        if c.dtype != f.dtype:
            from spark_rapids_tpu.exec.base import make_eval_context
            from spark_rapids_tpu.exprs.base import BoundReference
            from spark_rapids_tpu.exprs.cast import Cast
            ctx = make_eval_context([c], batch.capacity, batch.num_rows)
            c = Cast(BoundReference(0, c.dtype), f.dtype).eval(ctx)
        cols.append(c)
    return ColumnarBatch(schema, cols, batch.num_rows)
