"""Shim discovery (reference `ShimLoader.scala:26-61`).

The reference finds `SparkShimServiceProvider`s via Java's `ServiceLoader`
and picks the one whose `matchesVersion` accepts the running Spark version
(with a Databricks sniff, since Databricks misreports its base version).
Here providers self-register at import; resolution is by exact version
string, with the same Databricks detection hook.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.shims.base import SparkShims
from spark_rapids_tpu.shims.versions import ALL_SHIMS

log = logging.getLogger(__name__)

_PROVIDERS: list[type] = list(ALL_SHIMS)
_lock = threading.Lock()
_cache: dict[str, SparkShims] = {}


def register_provider(shim_class: type) -> None:
    """ServiceLoader analog: add an externally-defined shim provider.
    Prepended so an external provider can override a built-in version."""
    with _lock:
        _PROVIDERS.insert(0, shim_class)
        _cache.clear()


def _has_provider(version: str) -> bool:
    with _lock:
        return any(version in p.VERSION_NAMES for p in _PROVIDERS)


def detect_version(conf: Optional[C.RapidsConf] = None) -> str:
    """The session's Spark version.  Databricks detection mirrors
    `ShimLoader.scala`: the cluster-tag conf marks a Databricks runtime
    regardless of the reported base version — but only when a Databricks
    shim for that base version exists, so an unexpected runtime degrades
    to the upstream shim instead of failing every plan rewrite."""
    conf = conf or C.get_active_conf()
    version = str(conf[C.SPARK_VERSION])
    if conf.get("spark.databricks.clusterUsageTags.clusterId") \
            and "databricks" not in version:
        db = f"{version}-databricks"
        if _has_provider(db):
            return db
        log.warning(
            "Databricks runtime detected but no %s shim exists; "
            "using the upstream %s shim", db, version)
    return version


def get_spark_shims(version: Optional[str] = None,
                    conf: Optional[C.RapidsConf] = None) -> SparkShims:
    version = version or detect_version(conf)
    with _lock:
        hit = _cache.get(version)
        if hit is not None:
            return hit
        for provider in _PROVIDERS:
            if version in provider.VERSION_NAMES:
                shims = provider()
                _cache[version] = shims
                log.info("Loaded shims for Spark %s via %s", version,
                         provider.__name__)
                return shims
    raise RuntimeError(
        f"Could not find a shim provider for Spark version {version!r}; "
        f"supported: {[v for p in _PROVIDERS for v in p.VERSION_NAMES]}")


def current_shims(conf: Optional[C.RapidsConf] = None) -> SparkShims:
    return get_spark_shims(conf=conf)
