"""Shim discovery (reference `ShimLoader.scala:26-61`).

The reference finds `SparkShimServiceProvider`s via Java's `ServiceLoader`
and picks the one whose `matchesVersion` accepts the running Spark version
(with a Databricks sniff, since Databricks misreports its base version).
Here providers self-register at import; resolution is by exact version
string, with the same Databricks detection hook.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.shims.base import SparkShims
from spark_rapids_tpu.shims.versions import ALL_SHIMS

log = logging.getLogger(__name__)

_PROVIDERS: list[type] = list(ALL_SHIMS)
_lock = threading.Lock()
_cache: dict[str, SparkShims] = {}


def register_provider(shim_class: type) -> None:
    """ServiceLoader analog: add an externally-defined shim provider.
    Prepended so an external provider can override a built-in version."""
    with _lock:
        _PROVIDERS.insert(0, shim_class)
        _cache.clear()


def _has_provider(version: str) -> bool:
    with _lock:
        return any(version in p.VERSION_NAMES for p in _PROVIDERS)


def detect_version(conf: Optional[C.RapidsConf] = None) -> str:
    """The session's Spark version.  Databricks detection mirrors
    `ShimLoader.scala`: the cluster-tag conf marks a Databricks runtime
    regardless of the reported base version — but only when a Databricks
    shim for that base version exists, so an unexpected runtime degrades
    to the upstream shim instead of failing every plan rewrite."""
    conf = conf or C.get_active_conf()
    version = str(conf[C.SPARK_VERSION])
    if conf.get("spark.databricks.clusterUsageTags.clusterId") \
            and "databricks" not in version:
        db = f"{version}-databricks"
        if _has_provider(db):
            return db
        log.warning(
            "Databricks runtime detected but no %s shim exists; "
            "using the upstream %s shim", db, version)
    return version


def _nearest_minor(version: str) -> Optional[str]:
    """Highest known patch release within the same major.minor line
    (e.g. an unknown 3.0.9 -> 3.0.2).  Databricks-suffixed versions
    never cross-match — their drift is runtime-wide, not patch-level."""
    if "databricks" in version:
        return None
    parts = version.split(".")
    if len(parts) < 2:
        return None
    prefix = ".".join(parts[:2]) + "."
    with _lock:
        known = [v for p in _PROVIDERS for v in p.VERSION_NAMES
                 if v.startswith(prefix) and "databricks" not in v]
    if not known:
        return None
    # numeric ordering: lexicographic would rank 3.0.2 above 3.0.10
    import re
    return max(known,
               key=lambda v: [int(x) for x in re.findall(r"\d+", v)])


def get_spark_shims(version: Optional[str] = None,
                    conf: Optional[C.RapidsConf] = None) -> SparkShims:
    conf = conf or C.get_active_conf()
    version = version or detect_version(conf)
    with _lock:
        hit = _cache.get(version)
        if hit is None and conf[C.ALLOW_UNKNOWN_SPARK_VERSION]:
            # fallback results live under a gated key (see below)
            hit = _cache.get(version + "|fallback")
        if hit is not None:
            return hit
        for provider in _PROVIDERS:
            if version in provider.VERSION_NAMES:
                shims = provider()
                _cache[version] = shims
                log.info("Loaded shims for Spark %s via %s", version,
                         provider.__name__)
                return shims
    # unknown version: the reference ShimLoader throws here (a new
    # Spark release needs a new shim — silent use of a stale one can
    # miscompile plans).  Conf-gated escape hatch for operators who
    # accept that risk: fall back to the nearest same-minor shim with
    # a loud warning (VERDICT r4 weak #6 — the arrival of a new
    # version now has a defined, tested behavior either way).
    near = _nearest_minor(version)
    if near is not None and conf[C.ALLOW_UNKNOWN_SPARK_VERSION]:
        log.warning(
            "No shim provider for Spark %s; "
            "spark.rapids.tpu.allowUnknownSparkVersion is set — "
            "falling back to the %s shim. Version-sensitive "
            "behaviors (rebase defaults, First/Last API, AQE "
            "reader specs) follow %s, which may be WRONG for %s.",
            version, near, near, version)
        shims = get_spark_shims(near)
        # cached under a FALLBACK-ONLY key: a later session with the
        # gate unset must still get the documented RuntimeError, not a
        # silently cached fallback shim
        with _lock:
            _cache[version + "|fallback"] = shims
        return shims
    hint = (f" (set {C.ALLOW_UNKNOWN_SPARK_VERSION.key} to fall back "
            f"to the {near} shim at your own risk)"
            if near is not None
            and not conf[C.ALLOW_UNKNOWN_SPARK_VERSION] else "")
    raise RuntimeError(
        f"Could not find a shim provider for Spark version {version!r}; "
        f"supported: "
        f"{[v for p in _PROVIDERS for v in p.VERSION_NAMES]}{hint}")


def current_shims(conf: Optional[C.RapidsConf] = None) -> SparkShims:
    return get_spark_shims(conf=conf)
