"""Per-version package (reference `shims/spark302/.../spark302/RapidsShuffleManager.scala`):
the version-named shuffle-manager class users put in
`spark.shuffle.manager`."""
from spark_rapids_tpu.shuffle.manager import TpuShuffleManager


class RapidsShuffleManager(TpuShuffleManager):
    SPARK_VERSION = "spark302"
