"""Version shim layer (reference `sql-plugin/.../SparkShims.scala` +
`shims/spark30*` modules): everything that varies across supported Spark
versions routes through a `SparkShims` instance resolved by `ShimLoader`.
"""
from spark_rapids_tpu.shims.base import ShimVersion, SparkShims
from spark_rapids_tpu.shims.loader import (current_shims, detect_version,
                                           get_spark_shims,
                                           register_provider)
from spark_rapids_tpu.shims.versions import (ALL_SHIMS, Spark300dbShims,
                                             Spark300Shims, Spark301Shims,
                                             Spark302Shims, Spark310Shims)

__all__ = [
    "ShimVersion", "SparkShims", "current_shims", "detect_version",
    "get_spark_shims", "register_provider", "ALL_SHIMS",
    "Spark300Shims", "Spark300dbShims", "Spark301Shims", "Spark302Shims",
    "Spark310Shims",
]
