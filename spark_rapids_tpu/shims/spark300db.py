"""Per-version package (reference `shims/spark300db/.../spark300db/RapidsShuffleManager.scala`):
the version-named shuffle-manager class users put in
`spark.shuffle.manager`."""
from spark_rapids_tpu.shuffle.manager import TpuShuffleManager


class RapidsShuffleManager(TpuShuffleManager):
    SPARK_VERSION = "spark300db"
