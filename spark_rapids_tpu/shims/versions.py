"""Per-version shim implementations (reference `shims/spark300`,
`spark300db`, `spark301`, `spark302`, `spark310` modules).

Each class carries only what drifted from its parent, the same way the
reference's per-version source trees carry per-version copies of
version-sensitive classes.
"""
from __future__ import annotations

from spark_rapids_tpu.shims.base import SparkShims


class Spark300Shims(SparkShims):
    """Spark 3.0.0 — the base behavior set."""
    VERSION_NAMES = ("3.0.0",)


class Spark300dbShims(Spark300Shims):
    """Databricks 3.0.0 (reference `shims/spark300db`): forked AQE classes
    and its own shuffle-manager package."""
    VERSION_NAMES = ("3.0.0-databricks",)

    def aqe_shuffle_reader_name(self) -> str:
        # Databricks runtime forked AQE before upstream settled the name.
        return "DatabricksShuffleReaderExec"

    def make_query_stage_prep_rule(self, conf, factory):
        rule = factory(conf)

        def db_rule(plan):
            return rule(plan)
        db_rule.__name__ = "DatabricksQueryStagePrepRule"
        return db_rule

    def plan_file_partitions(self, files, max_bytes, open_cost,
                             min_partitions: int = 1):
        # Databricks' getPartitionSplitFiles packs WHOLE files (no
        # byte-range splitting)
        from spark_rapids_tpu.io.scan import plan_file_partitions
        return plan_file_partitions(files, max_bytes, open_cost,
                                    min_partitions=min_partitions,
                                    split_files=False)

    def shuffle_manager_class(self) -> str:
        return "spark_rapids_tpu.shims.spark300db.RapidsShuffleManager"


class Spark301Shims(Spark300Shims):
    """Spark 3.0.1 (reference `shims/spark301`): First/Last boolean API,
    renamed rebase conf, per-version shuffle manager package."""
    VERSION_NAMES = ("3.0.1",)

    def shuffle_manager_class(self) -> str:
        return "spark_rapids_tpu.shims.spark301.RapidsShuffleManager"

    def parquet_rebase_read_key(self) -> str:
        return "spark.sql.legacy.parquet.datetimeRebaseModeInRead"

    def parquet_rebase_write_key(self) -> str:
        return "spark.sql.legacy.parquet.datetimeRebaseModeInWrite"

    def parquet_rebase_default(self) -> str:
        return "EXCEPTION"


class Spark302Shims(Spark301Shims):
    """Spark 3.0.2 (reference `shims/spark302`): identical surface to
    3.0.1 except the advertised version/manager package."""
    VERSION_NAMES = ("3.0.2",)

    def shuffle_manager_class(self) -> str:
        return "spark_rapids_tpu.shims.spark302.RapidsShuffleManager"


class Spark310Shims(Spark301Shims):
    """Spark 3.1.0 (reference `shims/spark310`): accelerated
    columnar→row transition, map-index-range shuffle reads (AQE skew
    splits), renamed rebase confs."""
    VERSION_NAMES = ("3.1.0", "3.1.1-SNAPSHOT")

    def columnar_to_row_transition(self, tpu_child):
        from spark_rapids_tpu.plan.transitions import (
            AcceleratedColumnarToRowExec)
        return AcceleratedColumnarToRowExec(tpu_child)

    def supports_map_index_ranges(self) -> bool:
        return True

    def shuffle_manager_class(self) -> str:
        return "spark_rapids_tpu.shims.spark310.RapidsShuffleManager"

    def make_shuffle_exchange(self, partitioning, child,
                              can_change_num_partitions: bool = True):
        # 3.1 ShuffleExchangeLike: AQE honors canChangeNumPartitions
        # (repartition-by-user must keep its partition count)
        ex = super().make_shuffle_exchange(partitioning, child)
        ex.can_change_num_partitions = can_change_num_partitions
        return ex


ALL_SHIMS = (Spark300Shims, Spark300dbShims, Spark301Shims, Spark302Shims,
             Spark310Shims)
