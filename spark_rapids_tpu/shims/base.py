"""Version shim surface (reference `SparkShims.scala:57-136`).

The reference abstracts Spark 3.0.0/3.0.1/3.0.2/3.1.0/Databricks API drift
behind a ~25-method `SparkShims` trait with per-version implementations
discovered by a `ServiceLoader` (`ShimLoader.scala:26-61`).  The TPU build
keeps the same contract: everything version-variant — transition execs,
First/Last aggregate construction, AQE map-output range reads, file
partition packing, the per-version shuffle-manager class name — routes
through a `SparkShims` instance resolved from the session's Spark version.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True, order=True)
class ShimVersion:
    """Parsed Spark version (reference `SparkShimVersion` /
    `DatabricksShimVersion` in `SparkShims.scala:24-36`)."""
    major: int
    minor: int
    patch: int
    databricks: bool = False

    def __str__(self):
        base = f"{self.major}.{self.minor}.{self.patch}"
        return base + ("-databricks" if self.databricks else "")

    @staticmethod
    def parse(s: str) -> "ShimVersion":
        db = "databricks" in s or "-db" in s
        m = re.match(r"^(\d+)\.(\d+)\.(\d+)", s)
        if not m:
            raise ValueError(f"cannot parse Spark version {s!r}")
        return ShimVersion(int(m.group(1)), int(m.group(2)),
                           int(m.group(3)), db)


class SparkShims:
    """Base shim: the Spark 3.0.0 behavior set.  Later versions subclass
    and override only what drifted (mirrors how `shims/spark30*` carry
    per-version copies of version-sensitive classes)."""

    #: exact version strings this shim serves (reference
    #: `SparkShimServiceProvider.matchesVersion`)
    VERSION_NAMES: tuple = ()

    @property
    def version(self) -> ShimVersion:
        return ShimVersion.parse(self.VERSION_NAMES[0])

    # -- transitions --------------------------------------------------------
    def columnar_to_row_transition(self, tpu_child):
        """Device-exit transition exec.  3.1.0 swaps in an accelerated
        variant (reference `SparkShims.getGpuColumnarToRowTransition`,
        spark310 shim)."""
        from spark_rapids_tpu.plan.transitions import ColumnarToRowExec
        return ColumnarToRowExec(tpu_child)

    # -- expression construction drift --------------------------------------
    def make_first_last(self, child, last: bool, ignore_nulls: bool):
        """First/Last aggregate constructor (API changed in 3.0.1:
        `ignoreNulls` became a plain boolean — reference shims carry
        per-version `GpuFirst`/`GpuLast`).  The 3.0.0 form models the
        literal-expression API by validating a literal-like value."""
        from spark_rapids_tpu.exprs.aggregates import First, Last
        ctor = Last if last else First
        return ctor(child, ignore_nulls=bool(ignore_nulls))

    # -- shuffle / AQE ------------------------------------------------------
    def shuffle_manager_class(self) -> str:
        """Fully-qualified per-version shuffle manager (reference
        `shims/spark300/.../spark300/RapidsShuffleManager.scala`)."""
        return ("spark_rapids_tpu.shims.spark300.RapidsShuffleManager")

    def supports_map_index_ranges(self) -> bool:
        """Spark 3.0.x `getMapSizesByExecutorId` cannot address partial
        mapper ranges; 3.1.0 can (AQE skew-split reads)."""
        return False

    def get_map_sizes(self, registry, shuffle_id: int,
                      start_map: int, end_map: Optional[int],
                      start_part: int, end_part: int):
        """Map-output lookup for a reducer range (reference
        `SparkShims.getMapSizesByExecutorId`).  Returns
        [(map_id, part_id, size_bytes)] for blocks in range."""
        statuses = registry.outputs_for(shuffle_id)
        all_maps = (max(statuses) + 1) if statuses else 0
        hi = all_maps if end_map is None else end_map
        if (start_map, hi) != (0, all_maps) \
                and not self.supports_map_index_ranges():
            raise NotImplementedError(
                f"Spark {self.version} cannot fetch partial mapper ranges")
        out = []
        for map_id in range(start_map, hi):
            if map_id not in statuses:
                continue
            sizes = statuses[map_id].partition_sizes
            for part_id in range(start_part, end_part):
                if sizes[part_id] > 0:
                    out.append((map_id, part_id, sizes[part_id]))
        return out

    def aqe_shuffle_reader_name(self) -> str:
        """Display/class name of the AQE shuffle reader this version uses
        (upstream `CustomShuffleReaderExec`; Databricks forked its own)."""
        return "CustomShuffleReaderExec"

    # -- file scan ----------------------------------------------------------
    def make_file_partitions(self, files: Sequence, max_bytes: int,
                             open_cost: int = 4 * 1024 * 1024):
        """Pack (path, size) file splits into partitions (reference
        `SparkShims.createFilePartition` / `getFileScanRDD` drift).  Spark
        3.0.x packs greedily by size + open cost."""
        parts, cur, cur_bytes = [], [], 0
        for f in sorted(files, key=lambda f: -f[1]):
            est = f[1] + open_cost
            if cur and cur_bytes + est > max_bytes:
                parts.append(cur)
                cur, cur_bytes = [], 0
            cur.append(f)
            cur_bytes += est
        if cur:
            parts.append(cur)
        return parts

    # -- config drift -------------------------------------------------------
    def parquet_rebase_read_key(self) -> str:
        """Hybrid-calendar rebase conf key; Spark 3.0.0 shipped the
        boolean-era name, renamed to the mode conf in 3.0.1."""
        return "spark.sql.legacy.parquet.rebaseDateTimeInRead"

    def parquet_rebase_write_key(self) -> str:
        return "spark.sql.legacy.parquet.rebaseDateTimeInWrite"

    def parquet_rebase_default(self) -> str:
        """Default mode when the key is unset: 3.0.0's boolean keys
        default to false (read/write verbatim = CORRECTED); 3.0.1+
        mode keys default to EXCEPTION."""
        return "CORRECTED"

    def parquet_rebase_read_mode(self, conf) -> str:
        from spark_rapids_tpu.io import rebase as RB
        return RB.normalize_mode(conf.get(
            self.parquet_rebase_read_key(), self.parquet_rebase_default()))

    def parquet_rebase_write_mode(self, conf) -> str:
        from spark_rapids_tpu.io import rebase as RB
        return RB.normalize_mode(conf.get(
            self.parquet_rebase_write_key(),
            self.parquet_rebase_default()))

    # -- join construction drift --------------------------------------------
    BUILD_LEFT = "left"
    BUILD_RIGHT = "right"

    def build_side_of(self, join_type, preferred: str = "right") -> str:
        """Build-side resolution (reference `SparkShims.getBuildSide`:
        BuildLeft/BuildRight MOVED packages in Spark 3.1, so engine code
        must never import them directly — the shim owns the mapping).
        Semi/anti joins always build the right side."""
        from spark_rapids_tpu.exec.joins import JoinType as JT
        if join_type in (JT.LEFT_SEMI, JT.LEFT_ANTI):
            return self.BUILD_RIGHT
        return preferred

    def make_nested_loop_join(self, join_type, left, right, condition,
                              target_size_bytes: int = 0):
        """Nested-loop join constructor (reference
        `getGpuBroadcastNestedLoopJoinShim`: the exec's constructor
        signature drifts per version; targetSizeBytes threading changed)."""
        from spark_rapids_tpu.exec.joins import NestedLoopJoinExec
        j = NestedLoopJoinExec(left, right, condition, join_type)
        j.target_size_bytes = target_size_bytes
        return j

    # -- exchange construction drift ----------------------------------------
    def make_shuffle_exchange(self, partitioning, child,
                              can_change_num_partitions: bool = True):
        """Shuffle exchange constructor (reference
        `getGpuShuffleExchangeExec`): Spark 3.0 has no
        canChangeNumPartitions — AQE may always coalesce; 3.1's
        ShuffleExchangeLike carries the flag (spark310 override)."""
        from spark_rapids_tpu.shuffle.exchange import ShuffleExchangeExec
        ex = ShuffleExchangeExec(partitioning, child)
        ex.can_change_num_partitions = True  # 3.0 semantics
        return ex

    def make_broadcast_exchange(self, child):
        """Broadcast exchange constructor (reference
        `getGpuBroadcastExchangeExec`; 3.1 wraps BroadcastExchangeLike)."""
        from spark_rapids_tpu.shuffle.exchange import BroadcastExchangeExec
        return BroadcastExchangeExec(child)

    # -- AQE rule injection ---------------------------------------------------
    def make_query_stage_prep_rule(self, conf, factory):
        """Build the prep rule for THIS version (conf-resolved, so the
        plugin can defer shim lookup into the builder; Databricks wraps
        the rule under its forked name)."""
        return factory(conf)

    # -- file scan construction ----------------------------------------------
    def plan_file_partitions(self, files, max_bytes: int, open_cost: int,
                             min_partitions: int = 1):
        """FilePartition planning (reference `createFilePartition` +
        `getPartitionSplitFiles`: Databricks packs whole files only)."""
        from spark_rapids_tpu.io.scan import plan_file_partitions
        return plan_file_partitions(files, max_bytes, open_cost,
                                    min_partitions=min_partitions)

    def copy_scan_with_small_file_opt(self, scan_exec, enabled: bool):
        """Rebuild a file scan exec with the multi-file (small-file
        coalescing) reader toggled (reference
        `copyFileSourceScanExec(supportsSmallFileOpt)`)."""
        import copy as _copy
        from spark_rapids_tpu.io.exec import TpuFileSourceScanExec
        sd = _copy.copy(scan_exec.scan)
        sd.small_file_opt = enabled
        return TpuFileSourceScanExec(sd, scan_exec.pushed_filter,
                                     scan_exec.conf)

    # -- rule extensions ----------------------------------------------------
    def extra_exec_rules(self) -> dict:
        """Per-version exec replacement rules added on top of the common
        set (reference `SparkShims.getExecs`)."""
        return {}

    def extra_expr_rules(self) -> dict:
        return {}
