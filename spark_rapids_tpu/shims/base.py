"""Version shim surface (reference `SparkShims.scala:57-136`).

The reference abstracts Spark 3.0.0/3.0.1/3.0.2/3.1.0/Databricks API drift
behind a ~25-method `SparkShims` trait with per-version implementations
discovered by a `ServiceLoader` (`ShimLoader.scala:26-61`).  The TPU build
keeps the same contract: everything version-variant — transition execs,
First/Last aggregate construction, AQE map-output range reads, file
partition packing, the per-version shuffle-manager class name — routes
through a `SparkShims` instance resolved from the session's Spark version.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True, order=True)
class ShimVersion:
    """Parsed Spark version (reference `SparkShimVersion` /
    `DatabricksShimVersion` in `SparkShims.scala:24-36`)."""
    major: int
    minor: int
    patch: int
    databricks: bool = False

    def __str__(self):
        base = f"{self.major}.{self.minor}.{self.patch}"
        return base + ("-databricks" if self.databricks else "")

    @staticmethod
    def parse(s: str) -> "ShimVersion":
        db = "databricks" in s or "-db" in s
        m = re.match(r"^(\d+)\.(\d+)\.(\d+)", s)
        if not m:
            raise ValueError(f"cannot parse Spark version {s!r}")
        return ShimVersion(int(m.group(1)), int(m.group(2)),
                           int(m.group(3)), db)


class SparkShims:
    """Base shim: the Spark 3.0.0 behavior set.  Later versions subclass
    and override only what drifted (mirrors how `shims/spark30*` carry
    per-version copies of version-sensitive classes)."""

    #: exact version strings this shim serves (reference
    #: `SparkShimServiceProvider.matchesVersion`)
    VERSION_NAMES: tuple = ()

    @property
    def version(self) -> ShimVersion:
        return ShimVersion.parse(self.VERSION_NAMES[0])

    # -- transitions --------------------------------------------------------
    def columnar_to_row_transition(self, tpu_child):
        """Device-exit transition exec.  3.1.0 swaps in an accelerated
        variant (reference `SparkShims.getGpuColumnarToRowTransition`,
        spark310 shim)."""
        from spark_rapids_tpu.plan.transitions import ColumnarToRowExec
        return ColumnarToRowExec(tpu_child)

    # -- expression construction drift --------------------------------------
    def make_first_last(self, child, last: bool, ignore_nulls: bool):
        """First/Last aggregate constructor (API changed in 3.0.1:
        `ignoreNulls` became a plain boolean — reference shims carry
        per-version `GpuFirst`/`GpuLast`).  The 3.0.0 form models the
        literal-expression API by validating a literal-like value."""
        from spark_rapids_tpu.exprs.aggregates import First, Last
        ctor = Last if last else First
        return ctor(child, ignore_nulls=bool(ignore_nulls))

    # -- shuffle / AQE ------------------------------------------------------
    def shuffle_manager_class(self) -> str:
        """Fully-qualified per-version shuffle manager (reference
        `shims/spark300/.../spark300/RapidsShuffleManager.scala`)."""
        return ("spark_rapids_tpu.shims.spark300.RapidsShuffleManager")

    def supports_map_index_ranges(self) -> bool:
        """Spark 3.0.x `getMapSizesByExecutorId` cannot address partial
        mapper ranges; 3.1.0 can (AQE skew-split reads)."""
        return False

    def get_map_sizes(self, registry, shuffle_id: int,
                      start_map: int, end_map: Optional[int],
                      start_part: int, end_part: int):
        """Map-output lookup for a reducer range (reference
        `SparkShims.getMapSizesByExecutorId`).  Returns
        [(map_id, part_id, size_bytes)] for blocks in range."""
        statuses = registry.outputs_for(shuffle_id)
        all_maps = (max(statuses) + 1) if statuses else 0
        hi = all_maps if end_map is None else end_map
        if (start_map, hi) != (0, all_maps) \
                and not self.supports_map_index_ranges():
            raise NotImplementedError(
                f"Spark {self.version} cannot fetch partial mapper ranges")
        out = []
        for map_id in range(start_map, hi):
            if map_id not in statuses:
                continue
            sizes = statuses[map_id].partition_sizes
            for part_id in range(start_part, end_part):
                if sizes[part_id] > 0:
                    out.append((map_id, part_id, sizes[part_id]))
        return out

    def aqe_shuffle_reader_name(self) -> str:
        """Display/class name of the AQE shuffle reader this version uses
        (upstream `CustomShuffleReaderExec`; Databricks forked its own)."""
        return "CustomShuffleReaderExec"

    # -- file scan ----------------------------------------------------------
    def make_file_partitions(self, files: Sequence, max_bytes: int,
                             open_cost: int = 4 * 1024 * 1024):
        """Pack (path, size) file splits into partitions (reference
        `SparkShims.createFilePartition` / `getFileScanRDD` drift).  Spark
        3.0.x packs greedily by size + open cost."""
        parts, cur, cur_bytes = [], [], 0
        for f in sorted(files, key=lambda f: -f[1]):
            est = f[1] + open_cost
            if cur and cur_bytes + est > max_bytes:
                parts.append(cur)
                cur, cur_bytes = [], 0
            cur.append(f)
            cur_bytes += est
        if cur:
            parts.append(cur)
        return parts

    # -- config drift -------------------------------------------------------
    def parquet_rebase_read_key(self) -> str:
        """Hybrid-calendar rebase conf key; Spark 3.0.0 shipped the
        boolean-era name, renamed to the mode conf in 3.0.1."""
        return "spark.sql.legacy.parquet.rebaseDateTimeInRead"

    def parquet_rebase_write_key(self) -> str:
        return "spark.sql.legacy.parquet.rebaseDateTimeInWrite"

    def parquet_rebase_default(self) -> str:
        """Default mode when the key is unset: 3.0.0's boolean keys
        default to false (read/write verbatim = CORRECTED); 3.0.1+
        mode keys default to EXCEPTION."""
        return "CORRECTED"

    def parquet_rebase_read_mode(self, conf) -> str:
        from spark_rapids_tpu.io import rebase as RB
        return RB.normalize_mode(conf.get(
            self.parquet_rebase_read_key(), self.parquet_rebase_default()))

    def parquet_rebase_write_mode(self, conf) -> str:
        from spark_rapids_tpu.io import rebase as RB
        return RB.normalize_mode(conf.get(
            self.parquet_rebase_write_key(),
            self.parquet_rebase_default()))

    # -- rule extensions ----------------------------------------------------
    def extra_exec_rules(self) -> dict:
        """Per-version exec replacement rules added on top of the common
        set (reference `SparkShims.getExecs`)."""
        return {}

    def extra_expr_rules(self) -> dict:
        return {}
