"""API-surface audit (reference `api_validation/.../ApiValidation.scala:17-60`
+ `auditAllVersions.sh`).

The reference reflection-diffs every Gpu exec's constructor signature
against the Spark exec it replaces, per supported Spark version, to catch
silent API drift between the plugin and Spark releases.  The TPU analog
audits the replacement registry against the plan- and exec-layer classes:

- every registered exec rule converts a real `CpuNode` subclass and its
  converter is callable with (meta, children);
- every `CpuNode` subclass that represents a physical op either has a
  replacement rule or is a known intentional gap;
- every TPU exec class reachable from a rule implements the columnar
  execution protocol (`output_schema`, `execute_columnar`);
- every expression rule names an `Expression` subclass that exists;
- each shim version loads and exposes the full `SparkShims` surface.

Run `audit_all_versions()` in CI; it returns a report with an empty
`problems` list when the surface is consistent.
"""
from __future__ import annotations

import dataclasses
import inspect
from typing import Callable

from spark_rapids_tpu.plan import nodes as N


@dataclasses.dataclass
class AuditReport:
    version: str
    checked: int = 0
    problems: list = dataclasses.field(default_factory=list)

    def ok(self) -> bool:
        return not self.problems

    def __str__(self):
        head = f"[{self.version}] {self.checked} checks, " \
               f"{len(self.problems)} problems"
        return "\n".join([head] + [f"  - {p}" for p in self.problems])


#: CpuNode subclasses that intentionally have no TPU replacement (plan
#: infrastructure, not physical operators users hit)
KNOWN_UNREPLACED = {"CpuNode"}

#: the SparkShims surface every shim must provide (reference
#: `SparkShims.scala:57-136`'s ~25-method trait)
SHIM_SURFACE = (
    "columnar_to_row_transition", "make_first_last",
    "shuffle_manager_class", "supports_map_index_ranges",
    "get_map_sizes", "aqe_shuffle_reader_name", "make_file_partitions",
    "parquet_rebase_read_key", "extra_exec_rules", "extra_expr_rules",
)


def _all_cpu_nodes() -> list[type]:
    import spark_rapids_tpu.io.exec  # registers scan/write nodes
    import spark_rapids_tpu.pyudf.exec  # registers pandas-udf nodes
    out = []

    def walk(cls):
        out.append(cls)
        for sub in cls.__subclasses__():
            walk(sub)
    walk(N.CpuNode)
    return out


def audit_exec_rules(report: AuditReport) -> None:
    from spark_rapids_tpu.plan.overrides import (EXEC_RULES,
                                                 _ensure_io_rules,
                                                 _register_pyudf_rules)
    _ensure_io_rules()
    _register_pyudf_rules()
    from spark_rapids_tpu.exec.base import TpuExec
    cpu_nodes = _all_cpu_nodes()
    transition_names = {"ColumnarToRowExec", "AcceleratedColumnarToRowExec",
                        "BringBackToHost"}
    for cls in cpu_nodes:
        report.checked += 1
        if cls in EXEC_RULES:
            continue
        if cls.__name__ in KNOWN_UNREPLACED | transition_names:
            continue
        if inspect.isabstract(cls):
            continue
        report.problems.append(
            f"CpuNode {cls.__name__} has no exec replacement rule")
    for cls, rule in EXEC_RULES.items():
        report.checked += 1
        if not issubclass(cls, N.CpuNode):
            report.problems.append(
                f"exec rule registered for non-CpuNode {cls!r}")
        conv = rule.convert
        if not callable(conv):
            report.problems.append(
                f"exec rule for {cls.__name__}: converter not callable")
            continue
        try:
            sig = inspect.signature(conv)
            if len([p for p in sig.parameters.values()
                    if p.default is p.empty
                    and p.kind in (p.POSITIONAL_ONLY,
                                   p.POSITIONAL_OR_KEYWORD)]) > 2:
                report.problems.append(
                    f"exec rule for {cls.__name__}: converter must accept "
                    f"(meta, children), got {sig}")
        except (TypeError, ValueError):
            pass


def audit_expr_rules(report: AuditReport) -> None:
    import importlib
    import pkgutil

    from spark_rapids_tpu.plan.overrides import EXPR_RULES
    import spark_rapids_tpu.exprs as E
    from spark_rapids_tpu.exprs.base import Expression

    for mod in pkgutil.iter_modules(E.__path__):
        importlib.import_module(f"spark_rapids_tpu.exprs.{mod.name}")
    from spark_rapids_tpu.exprs.aggregates import AggregateFunction

    known = {}

    def walk(cls):
        known[cls.__name__] = cls
        for sub in cls.__subclasses__():
            walk(sub)
    walk(Expression)
    walk(AggregateFunction)
    for name in EXPR_RULES:
        report.checked += 1
        if name not in known:
            report.problems.append(
                f"expression rule {name!r} names no Expression subclass")


def audit_tpu_exec_protocol(report: AuditReport) -> None:
    """Every instantiable (leaf) TpuExec must override the raising base
    stubs of the columnar protocol — getattr alone always finds the
    stubs, so the check compares against them explicitly."""
    from spark_rapids_tpu.exec.base import TpuExec

    def walk(cls):
        yield cls
        for sub in cls.__subclasses__():
            yield from walk(sub)
    #: placeholders that carry schema only and never execute
    exempt = {"SchemaOnlyExec"}
    for cls in walk(TpuExec):
        if cls.__subclasses__() or cls is TpuExec \
                or cls.__name__ in exempt:
            continue  # abstract-ish intermediates are not audited
        report.checked += 1
        for method in ("output_schema", "execute_columnar"):
            base_stub = getattr(TpuExec, method, None)
            fn = getattr(cls, method, None)
            if fn is None or fn is base_stub:
                report.problems.append(
                    f"TpuExec {cls.__name__} does not implement {method}")


def audit_shim_surface(report: AuditReport, shims) -> None:
    for name in SHIM_SURFACE:
        report.checked += 1
        if not callable(getattr(shims, name, None)):
            report.problems.append(
                f"shim {type(shims).__name__} missing {name}()")


def audit_version(version: str) -> AuditReport:
    from spark_rapids_tpu.shims import get_spark_shims
    report = AuditReport(version)
    shims = get_spark_shims(version)
    audit_shim_surface(report, shims)
    audit_exec_rules(report)
    audit_expr_rules(report)
    audit_tpu_exec_protocol(report)
    return report


def audit_all_versions() -> list[AuditReport]:
    """`auditAllVersions.sh` analog: one report per supported version."""
    from spark_rapids_tpu.shims import ALL_SHIMS
    return [audit_version(p.VERSION_NAMES[0]) for p in ALL_SHIMS]


if __name__ == "__main__":
    import sys
    reports = audit_all_versions()
    for r in reports:
        print(r)
    sys.exit(0 if all(r.ok() for r in reports) else 1)
