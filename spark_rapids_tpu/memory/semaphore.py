"""TpuSemaphore: limits how many tasks hold the accelerator concurrently
(reference `GpuSemaphore.scala:27-161`, conf
`spark.rapids.sql.concurrentGpuTasks`).

Tasks acquire before their first device use (e.g. after host-side scan
buffering) and release when leaving the device (columnar->row, partition
slicing to host).  Acquisition is per-task refcounted — nested operators in
one task acquire once — with a task-completion hook that force-releases,
like the reference's TaskContext listener.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional


class TaskContext:
    """Minimal task identity carrier (Spark TaskContext stand-in)."""

    _local = threading.local()

    def __init__(self, task_attempt_id: int):
        self.task_attempt_id = task_attempt_id
        self._completion_listeners = []

    def on_task_completion(self, fn) -> None:
        self._completion_listeners.append(fn)

    def complete(self) -> None:
        for fn in self._completion_listeners:
            fn(self)
        self._completion_listeners.clear()
        if getattr(TaskContext._local, "ctx", None) is self:
            TaskContext._local.ctx = None

    @classmethod
    def get(cls) -> Optional["TaskContext"]:
        return getattr(cls._local, "ctx", None)

    @classmethod
    def set_current(cls, ctx: Optional["TaskContext"]) -> None:
        cls._local.ctx = ctx

    def __enter__(self):
        TaskContext.set_current(self)
        return self

    def __exit__(self, *exc):
        self.complete()


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _ilock = threading.Lock()

    def __init__(self, max_concurrent: int):
        assert max_concurrent > 0
        self.max_concurrent = max_concurrent
        self._sem = threading.Semaphore(max_concurrent)
        self._refs: dict[int, int] = {}
        self._lock = threading.Lock()

    # -- singleton (executor-lifetime) --------------------------------------
    @classmethod
    def initialize(cls, max_concurrent: int) -> "TpuSemaphore":
        with cls._ilock:
            cls._instance = cls(max_concurrent)
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls(1)
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._ilock:
            cls._instance = None

    # -----------------------------------------------------------------------
    def acquire_if_necessary(self, ctx: Optional[TaskContext] = None) -> None:
        ctx = ctx or TaskContext.get()
        if ctx is None:
            return  # non-task context (driver-side): no admission control
        tid = ctx.task_attempt_id
        with self._lock:
            if self._refs.get(tid, 0) > 0:
                self._refs[tid] += 1
                return
        self._sem.acquire()
        with self._lock:
            if self._refs.get(tid, 0) > 0:
                # two threads of ONE task (a pipeline producer + its
                # consumer) raced the first acquire: a task holds at
                # most one permit, so give the extra one back
                self._refs[tid] += 1
                self._sem.release()
                return
            first = tid not in self._refs
            self._refs[tid] = 1
        if first:
            ctx.on_task_completion(lambda c: self.release_all(c))

    def release_if_necessary(self, ctx: Optional[TaskContext] = None) -> None:
        ctx = ctx or TaskContext.get()
        if ctx is None:
            return
        tid = ctx.task_attempt_id
        with self._lock:
            n = self._refs.get(tid, 0)
            if n == 0:
                return
            if n > 1:
                self._refs[tid] = n - 1
                return
            del self._refs[tid]
        self._sem.release()

    def release_all(self, ctx: TaskContext) -> None:
        tid = ctx.task_attempt_id
        with self._lock:
            n = self._refs.pop(tid, 0)
        if n > 0:
            self._sem.release()

    def holders(self) -> int:
        with self._lock:
            return len(self._refs)

    def snapshot(self) -> dict[int, int]:
        """Copy of the per-task refcount table (task_attempt_id ->
        holds) for the watchdog's diagnostic dump: after a cancelled
        query releases everything, this must come back empty."""
        with self._lock:
            return dict(self._refs)

    def holds(self, ctx: Optional[TaskContext] = None) -> int:
        """Refcount held by the given (default: current) task — 0 means
        it does not hold the accelerator.  Test-facing: the pipeline
        suite asserts a producer parked on a full prefetch queue holds
        nothing."""
        ctx = ctx or TaskContext.get()
        if ctx is None:
            return 0
        with self._lock:
            return self._refs.get(ctx.task_attempt_id, 0)

    @contextmanager
    def held(self, ctx: Optional[TaskContext] = None):
        self.acquire_if_necessary(ctx)
        try:
            yield
        finally:
            self.release_if_necessary(ctx)

    @contextmanager
    def yielded(self, ctx: Optional[TaskContext] = None):
        """Fully release this task's hold for the duration of the body
        (a synchronous spill / memory wait), restoring the same
        refcount afterwards — so concurrent tasks can use the
        accelerator while this task blocks on memory (the reference
        releases the GPU semaphore around DeviceMemoryEventHandler's
        synchronous spill for the same reason).  No-op outside a task
        context or when the task holds nothing."""
        ctx = ctx or TaskContext.get()
        if ctx is None:
            yield
            return
        tid = ctx.task_attempt_id
        with self._lock:
            n = self._refs.pop(tid, 0)
        if n > 0:
            self._sem.release()
        try:
            yield
        finally:
            if n > 0:
                self._sem.acquire()
                with self._lock:
                    self._refs[tid] = n
