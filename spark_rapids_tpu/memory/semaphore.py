"""TpuSemaphore: limits how many tasks hold the accelerator concurrently
(reference `GpuSemaphore.scala:27-161`, conf
`spark.rapids.sql.concurrentGpuTasks`).

Tasks acquire before their first device use (e.g. after host-side scan
buffering) and release when leaving the device (columnar->row, partition
slicing to host).  Acquisition is per-task refcounted — nested operators in
one task acquire once — with a task-completion hook that force-releases,
like the reference's TaskContext listener.

Grant policy (the multi-query serving layer's fair share): permits are
NOT handed out by raw wakeup race.  Each waiter is tagged with its
query (via the TaskContext's `query_ctx`); a freed permit goes first to
tasks re-acquiring after a `yielded()` spill (they keep their original
queue position — parking to spill must not cost a starving query its
turn), then to the waiting QUERY holding the fewest permits (ties
broken FIFO by arrival).  One heavy query with many ready tasks can
therefore never starve an interactive query's single task: the moment
the light query has fewer holds, its waiter is next.  `snapshot()`
exposes the holder table, per-query holds, the live waiter list, and
`longestWaitMs` so a watchdog dump shows who is starving whom.
"""
from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from typing import Optional


class TaskContext:
    """Minimal task identity carrier (Spark TaskContext stand-in).

    Dynamic attributes threaded through execution: `cancel_token` (the
    query's CancelToken, utils/watchdog.py) and `query_ctx` (the
    owning QueryContext, exec/scheduler.py) — helper threads sharing a
    task inherit both with the context object."""

    _local = threading.local()

    def __init__(self, task_attempt_id: int):
        self.task_attempt_id = task_attempt_id
        self._completion_listeners = []

    def on_task_completion(self, fn) -> None:
        self._completion_listeners.append(fn)

    def complete(self) -> None:
        for fn in self._completion_listeners:
            fn(self)
        self._completion_listeners.clear()
        if getattr(TaskContext._local, "ctx", None) is self:
            TaskContext._local.ctx = None

    @classmethod
    def get(cls) -> Optional["TaskContext"]:
        return getattr(cls._local, "ctx", None)

    @classmethod
    def set_current(cls, ctx: Optional["TaskContext"]) -> None:
        cls._local.ctx = ctx

    def __enter__(self):
        TaskContext.set_current(self)
        return self

    def __exit__(self, *exc):
        self.complete()


class _Waiter:
    __slots__ = ("seq", "group", "reacquire", "enqueued", "thread")

    def __init__(self, seq: int, group, reacquire: bool):
        self.seq = seq
        self.group = group
        self.reacquire = reacquire
        self.enqueued = time.monotonic()
        self.thread = threading.current_thread().name


#: bounded-poll granularity for cancellable permit waits
_POLL_S = 0.05


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _ilock = threading.Lock()

    def __init__(self, max_concurrent: int):
        assert max_concurrent > 0
        self.max_concurrent = max_concurrent
        self._permits = max_concurrent
        self._cv = threading.Condition()
        self._refs: dict[int, int] = {}
        self._holder_group: dict[int, object] = {}   # tid -> group
        self._group_holds: dict[object, int] = {}    # group -> permits
        self._waiters: list[_Waiter] = []
        self._seq = itertools.count(1)
        self._longest_wait_ms = 0
        self._wait_count = 0

    # -- fair-share bookkeeping ---------------------------------------------
    @staticmethod
    def _group_of(ctx: "TaskContext"):
        """The fair-share group a task charges its permit to: its
        query, else (driver-less/test tasks) the task itself."""
        qc = getattr(ctx, "query_ctx", None)
        if qc is None:
            from spark_rapids_tpu.exec import scheduler as S
            qc = S.current()
        if qc is not None:
            return qc.query_id
        return ("task", ctx.task_attempt_id)

    def _select_next(self) -> Optional[_Waiter]:
        """The waiter the next free permit belongs to.  Re-acquirers
        (yielded around a spill) first, in their original order; then
        the query with the fewest current holds, FIFO within it."""
        if not self._waiters:
            return None
        re = [w for w in self._waiters if w.reacquire]
        if re:
            return min(re, key=lambda w: w.seq)
        return min(self._waiters,
                   key=lambda w: (self._group_holds.get(w.group, 0),
                                  w.seq))

    def _wait_for_permit(self, group, reacquire: bool = False) -> None:
        """Block (cancellably) until this waiter is granted a permit;
        on return one permit is held and charged to `group`."""
        from spark_rapids_tpu.utils import watchdog as W
        token = W.current_token()
        w = _Waiter(next(self._seq), group, reacquire)
        blocked = False
        with self._cv:
            self._waiters.append(w)
            try:
                while self._permits <= 0 or self._select_next() is not w:
                    blocked = True
                    if token.cancelled:
                        token.check()   # raises TpuQueryTimeout
                    self._cv.wait(_POLL_S)
                self._permits -= 1
                self._group_holds[group] = \
                    self._group_holds.get(group, 0) + 1
            finally:
                self._waiters.remove(w)
                # our departure may change _select_next for the rest
                self._cv.notify_all()
            if blocked:
                waited_ms = int((time.monotonic() - w.enqueued) * 1e3)
                self._wait_count += 1
                if waited_ms > self._longest_wait_ms:
                    self._longest_wait_ms = waited_ms
        if blocked:
            from spark_rapids_tpu.utils import profile as P
            P.event(P.EV_SEMAPHORE_WAIT, group=str(group),
                    wait_ms=waited_ms, reacquire=reacquire)

    def _return_permit(self, group) -> None:
        with self._cv:
            self._permits += 1
            n = self._group_holds.get(group, 0) - 1
            if n > 0:
                self._group_holds[group] = n
            else:
                self._group_holds.pop(group, None)
            self._cv.notify_all()

    # -- singleton (executor-lifetime) --------------------------------------
    @classmethod
    def initialize(cls, max_concurrent: int) -> "TpuSemaphore":
        with cls._ilock:
            cls._instance = cls(max_concurrent)
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls(1)
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._ilock:
            cls._instance = None

    # -----------------------------------------------------------------------
    def acquire_if_necessary(self, ctx: Optional[TaskContext] = None) -> None:
        ctx = ctx or TaskContext.get()
        if ctx is None:
            return  # non-task context (driver-side): no admission control
        tid = ctx.task_attempt_id
        group = self._group_of(ctx)
        with self._cv:
            if self._refs.get(tid, 0) > 0:
                self._refs[tid] += 1
                return
        self._wait_for_permit(group)
        with self._cv:
            if self._refs.get(tid, 0) > 0:
                # two threads of ONE task (a pipeline producer + its
                # consumer) raced the first acquire: a task holds at
                # most one permit, so give the extra one back
                self._refs[tid] += 1
                self._permits += 1
                n = self._group_holds.get(group, 0) - 1
                if n > 0:
                    self._group_holds[group] = n
                else:
                    self._group_holds.pop(group, None)
                self._cv.notify_all()
                return
            first = tid not in self._refs
            self._refs[tid] = 1
            self._holder_group[tid] = group
        if first:
            ctx.on_task_completion(lambda c: self.release_all(c))

    def release_if_necessary(self, ctx: Optional[TaskContext] = None) -> None:
        ctx = ctx or TaskContext.get()
        if ctx is None:
            return
        tid = ctx.task_attempt_id
        with self._cv:
            n = self._refs.get(tid, 0)
            if n == 0:
                return
            if n > 1:
                self._refs[tid] = n - 1
                return
            del self._refs[tid]
            group = self._holder_group.pop(tid, None)
        self._return_permit(group)

    def release_all(self, ctx: TaskContext) -> None:
        tid = ctx.task_attempt_id
        with self._cv:
            n = self._refs.pop(tid, 0)
            group = self._holder_group.pop(tid, None)
        if n > 0:
            self._return_permit(group)

    def holders(self) -> int:
        with self._cv:
            return len(self._refs)

    def available_permits(self) -> int:
        """Free permits right now (test/diagnostic probe)."""
        with self._cv:
            return self._permits

    def waiting_count(self) -> int:
        """Tasks currently blocked waiting for a permit (telemetry
        gauge; snapshot() renders the full who-waits-on-whom table)."""
        with self._cv:
            return len(self._waiters)

    def wait_stats(self) -> dict:
        """Blocked-acquire counters for the telemetry registry."""
        with self._cv:
            return {"longest_wait_ms": self._longest_wait_ms,
                    "wait_count": self._wait_count}

    def snapshot(self) -> dict:
        """Diagnostic copy for the watchdog dump: the per-task refcount
        table, per-query permit holds, the live waiter list (who is
        starving), and the longest blocked acquire observed
        (`longestWaitMs`) — after a cancelled query releases
        everything, `refs` must come back empty."""
        with self._cv:
            return {
                "refs": dict(self._refs),
                "queryHolds": {str(g): n
                               for g, n in self._group_holds.items()},
                "waiters": [f"{w.group}"
                            f"{'(reacquire)' if w.reacquire else ''}"
                            f"@{w.thread}"
                            f"+{(time.monotonic() - w.enqueued) * 1e3:.0f}ms"
                            for w in self._waiters],
                "longestWaitMs": self._longest_wait_ms,
                "waitCount": self._wait_count,
            }

    def holds(self, ctx: Optional[TaskContext] = None) -> int:
        """Refcount held by the given (default: current) task — 0 means
        it does not hold the accelerator.  Test-facing: the pipeline
        suite asserts a producer parked on a full prefetch queue holds
        nothing."""
        ctx = ctx or TaskContext.get()
        if ctx is None:
            return 0
        with self._cv:
            return self._refs.get(ctx.task_attempt_id, 0)

    @contextmanager
    def held(self, ctx: Optional[TaskContext] = None):
        self.acquire_if_necessary(ctx)
        try:
            yield
        finally:
            self.release_if_necessary(ctx)

    @contextmanager
    def yielded(self, ctx: Optional[TaskContext] = None):
        """Fully release this task's hold for the duration of the body
        (a synchronous spill / memory wait), restoring the same
        refcount afterwards — so concurrent tasks can use the
        accelerator while this task blocks on memory (the reference
        releases the GPU semaphore around DeviceMemoryEventHandler's
        synchronous spill for the same reason).  Re-acquisition is
        queue-position-preserving: a task parked here outranks every
        waiter that arrived after it (`_select_next` serves reacquire
        waiters first), so spilling never costs a query its turn.
        No-op outside a task context or when the task holds nothing."""
        ctx = ctx or TaskContext.get()
        if ctx is None:
            yield
            return
        tid = ctx.task_attempt_id
        with self._cv:
            n = self._refs.pop(tid, 0)
            group = self._holder_group.pop(tid, None)
        if n > 0:
            self._return_permit(group)
        try:
            yield
        finally:
            if n > 0:
                self._wait_for_permit(group, reacquire=True)
                with self._cv:
                    self._refs[tid] = n
                    self._holder_group[tid] = group
