"""Out-of-core execution support: spilled runs + the degradation decision.

ROADMAP item 6 (bounded-HBM graceful degradation): when an operator's
working set cannot fit the conf-capped HBM budget
(`spark.rapids.memory.hbmBudgetBytes`), sort / hash join / hash aggregate
stop split-retrying toward the `minSplitRows` floor and switch to external
algorithms that stream state through the existing device→host→disk spill
tiers.  This module is the shared substrate those three lanes use:

- `should_go_external(est_bytes)` — the degradation decision, driven by
  real accounting: the per-operator window (`oocore.windowFraction` of
  `DeviceManager.budget`) plus a live `try_reserve` probe, never a guess.
- `spill_run(batch)` / `SpilledRun.read()` — one unit of spilled operator
  state (a sorted run, a grace-hash partition piece, a merged partial-agg
  block), serialized and pushed down the host→disk chain with optional
  replicas, every hop landing on the movement ledger's spill edges.
- Corruption recovery: a `SpillCorruption` on re-read quarantines the
  poisoned file (provenance-logged), falls back to a replica if one was
  written, else to a bounded recompute closure if the producer supplied
  one — and only then fails, descriptively (satellite: a corrupt spill
  re-read must not kill the query when a recovery path exists).

Theseus (PAPERS.md) frames the design: an accelerator engine's scalability
story is how it degrades past device memory, not how fast it runs inside
it.  The reference stack's analog rails are RapidsBufferStore spill
chaining + RmmRapidsRetryIterator; here out-of-core is the OUTER ring
around the OOM split-retry lattice — retry shrinks batches inside the
window, oocore bounds how much state is in the window at all.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.serde import deserialize_batch, serialize_batch
from spark_rapids_tpu.memory.buffer import BufferId, TableMeta
from spark_rapids_tpu.memory.stores import SpillCorruption
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils import movement as MV
from spark_rapids_tpu.utils import profile as P

log = logging.getLogger("spark_rapids_tpu.oocore")

#: movement-ledger site prefix for out-of-core run traffic, so the
#: reconciliation tests can split oocore spill bytes from pressure-spill
#: bytes sharing the same EDGE_SPILL edge
SITE_PREFIX = "oocore:"

#: external-sort merge fan-in target: runs flush at window/MERGE_FAN_IN
#: so one merge group of this many runs fits back inside the window —
#: maxRecursionDepth merge passes then cover MERGE_FAN_IN**depth runs
MERGE_FAN_IN = 8

# process-wide run accounting (the SpillCallback.bytes_spilled analog
# for the out-of-core lane): the second leg of the three-way
# reconciliation — movement-ledger oocore spill edges == this counter
# == the per-node spillRunBytes metric sums
_ACCT_LOCK = threading.Lock()
_RUN_BYTES = [0]
_RUN_COUNT = [0]


def reset_run_accounting() -> None:
    with _ACCT_LOCK:
        _RUN_BYTES[0] = 0
        _RUN_COUNT[0] = 0


def run_bytes_spilled() -> int:
    """Serialized bytes written as out-of-core runs process-wide
    (replica copies included) since the last reset."""
    with _ACCT_LOCK:
        return _RUN_BYTES[0]


def runs_spilled() -> int:
    with _ACCT_LOCK:
        return _RUN_COUNT[0]


# ---------------------------------------------------------------------------
# degradation decision
def window_bytes(conf: Optional[C.RapidsConf] = None,
                 dm=None) -> int:
    """Bytes one operator may hold in HBM at a time: the working window
    external sort/join/agg size their runs, merge fan-ins, and grace
    partitions against."""
    conf = conf or C.get_active_conf()
    if dm is None:
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        dm = DeviceManager.peek()
    if dm is None:
        return 1 << 62  # no device manager: effectively unbounded
    frac = float(conf[C.OOCORE_WINDOW_FRACTION])
    return max(1, int(dm.budget * frac))


def should_go_external(est_bytes: int,
                       conf: Optional[C.RapidsConf] = None,
                       dm=None) -> bool:
    """The degradation decision.  True when `est_bytes` of operator
    working set should stream through the spill tiers instead of
    materializing in HBM.  Two gates, both from real accounting:

    1. the estimate exceeds the per-operator window (windowFraction of
       the conf-capped `DeviceManager.budget`), and
    2. a live `try_reserve` probe confirms the arena really has no
       headroom for it right now — a generous arena with idle budget
       does not degrade on a pessimistic estimate.
    """
    conf = conf or C.get_active_conf()
    if not bool(conf[C.OOCORE_ENABLED]):
        return False
    if dm is None:
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        dm = DeviceManager.peek()
    if dm is None:
        return False
    if est_bytes <= window_bytes(conf, dm):
        return False
    if dm.try_reserve(est_bytes):
        dm.release_reservation(est_bytes)
        return False
    return True


# ---------------------------------------------------------------------------
# spilled runs
class SpilledRun:
    """Handle to one unit of spilled operator state: the primary copy
    plus any replicas, all registered in the buffer catalog and resident
    at whatever tier (host arena, falling through to disk) took them."""

    __slots__ = ("bids", "meta", "nbytes", "num_rows", "label",
                 "recompute", "_freed")

    def __init__(self, bids: list[BufferId], meta: TableMeta, nbytes: int,
                 num_rows: int, label: str,
                 recompute: Optional[Callable[[], ColumnarBatch]]):
        self.bids = bids
        self.meta = meta
        #: serialized size of ONE copy (what a merge window budgets for)
        self.nbytes = nbytes
        self.num_rows = num_rows
        self.label = label
        self.recompute = recompute
        self._freed = False

    def read(self, metrics=None) -> ColumnarBatch:
        """Materialize the run back to a device batch, recovering from
        spill corruption via replicas / recompute (see module doc)."""
        from spark_rapids_tpu.memory.env import ResourceEnv
        env = ResourceEnv.get()
        corrupt = 0
        for i, bid in enumerate(self.bids):
            if not env.catalog.is_registered(bid):
                continue  # quarantined by an earlier read of this run
            try:
                with env.catalog.acquired(bid) as buf:
                    batch = buf.get_columnar_batch()
                if corrupt and metrics is not None:
                    metrics.add(M.NUM_SPILL_CORRUPTIONS_RECOVERED, 1)
                if corrupt:
                    P.event(P.EV_OOCORE_CORRUPT_RECOVERED,
                            op=self.label, via=f"replica{i}")
                return batch
            except SpillCorruption as e:
                corrupt += 1
                self._quarantine(env, bid, e)
        if self.recompute is not None:
            batch = self.recompute()
            if corrupt:
                if metrics is not None:
                    metrics.add(M.NUM_SPILL_CORRUPTIONS_RECOVERED, 1)
                P.event(P.EV_OOCORE_CORRUPT_RECOVERED,
                        op=self.label, via="recompute")
            return batch
        raise SpillCorruption(
            f"out-of-core run {self.label} ({self.num_rows} rows, "
            f"{self.nbytes} bytes) unreadable: all {len(self.bids)} "
            f"cop{'ies' if len(self.bids) > 1 else 'y'} failed CRC "
            f"verification and no recompute lineage is available — "
            f"raise spark.rapids.memory.oocore.runReplicas to keep a "
            f"redundant copy of spilled runs")

    def _quarantine(self, env, bid: BufferId, err: Exception) -> None:
        """Provenance-logged quarantine of a corrupt copy: the poisoned
        file is set aside (never unlinked, never re-readable) and the
        buffer leaves the catalog."""
        from spark_rapids_tpu.utils import residency as RES
        site = RES.buffer_site(bid)
        qpath = None
        if hasattr(env.disk_store, "quarantine"):
            qpath = env.disk_store.quarantine(bid)
        if qpath is None:
            env.catalog.remove(bid)  # not at disk tier: just drop it
        log.warning(
            "quarantined corrupt spill of out-of-core run %s "
            "(buffer %s, provenance %s) at %s: %s",
            self.label, bid, site, qpath, err)
        P.event(P.EV_OOCORE_CORRUPT_QUARANTINE, op=self.label,
                site=site, path=str(qpath))

    def free(self) -> None:
        """Drop every copy from whatever tier holds it (and its spill
        file, for disk-resident copies)."""
        if self._freed:
            return
        self._freed = True
        from spark_rapids_tpu.memory.env import ResourceEnv
        env = ResourceEnv.peek()
        if env is None:
            return
        for bid in self.bids:
            env.catalog.remove(bid)


def spill_run(batch: ColumnarBatch, *, label: str, metrics=None,
              conf: Optional[C.RapidsConf] = None,
              recompute: Optional[Callable[[], ColumnarBatch]] = None
              ) -> SpilledRun:
    """Serialize `batch` and push it down the host→disk spill chain as
    one out-of-core run (plus `oocore.runReplicas - 1` replica copies).
    Records one movement-ledger spill edge per copy (site
    `oocore:device->host|disk`) and charges the exec's `spillRunBytes`.
    """
    from spark_rapids_tpu.memory.buffer import meta_for_batch
    from spark_rapids_tpu.memory.env import ResourceEnv
    conf = conf or C.get_active_conf()
    env = ResourceEnv.get()
    blob = serialize_batch(batch)
    meta = meta_for_batch(batch)
    copies = max(1, int(conf[C.OOCORE_RUN_REPLICAS]))
    bids = []
    for _ in range(copies):
        bid = BufferId(env.catalog.next_table_id())
        t0 = time.perf_counter_ns()
        # spill_priority 0 keeps runs ahead of hot shuffle buffers in
        # the host arena's eviction order — they are cold by design
        buf = env.host_store.add_blob(bid, blob, meta, spill_priority=0.0)
        # add_blob records no ledger edge (shuffle receives reuse it);
        # an out-of-core run IS a spill hop — record the hop that
        # actually happened, host or fell-through-to-disk
        if MV.ledger() is not None:
            MV.record(MV.EDGE_SPILL, len(blob),
                      site=f"{SITE_PREFIX}device->{buf.tier.name.lower()}",
                      raw_bytes=len(blob),
                      dur_ns=time.perf_counter_ns() - t0)
        bids.append(bid)
        with _ACCT_LOCK:
            _RUN_BYTES[0] += len(blob)
            _RUN_COUNT[0] += 1
        if metrics is not None:
            metrics.add(M.SPILL_RUN_BYTES, len(blob))
    P.event(P.EV_OOCORE_SPILL_RUN, op=label, nbytes=len(blob) * copies,
            rows=batch.num_rows, copies=copies)
    return SpilledRun(bids, meta, len(blob), batch.num_rows, label,
                      recompute)


def read_run(run: SpilledRun, metrics=None) -> ColumnarBatch:
    return run.read(metrics)


__all__ = [
    "SpilledRun", "spill_run", "read_run", "should_go_external",
    "window_bytes", "run_bytes_spilled", "runs_spilled",
    "reset_run_accounting", "deserialize_batch",
]
