"""Spill-priority policy constants (reference `SpillPriorities.scala`):
lower priority spills first.  Shuffle output written early in a stage is the
best candidate (likely not needed again soon on this chip); actively-used
operator intermediates spill last.
"""

# shuffle map output: spill first, ascending with write order so the
# oldest-written partitions go before fresher ones
OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY = -1e9

# broadcast build tables are reread by every stream batch: keep on device
BROADCAST_PRIORITY = 1e9

# operator intermediates default to neutral
ACTIVE_BATCH_PRIORITY = 0.0

# received shuffle blocks about to be read
INPUT_FROM_SHUFFLE_PRIORITY = -1e8


def shuffle_output_priority(seq: int) -> float:
    """Monotonic priority for successive shuffle writes."""
    return OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY + seq
