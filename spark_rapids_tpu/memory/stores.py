"""Tiered buffer stores: device (HBM) -> host (arena) -> disk.

Reference parallels: `RapidsBufferStore.scala:39-341` (abstract store with
spill-priority tracking + `setSpillStore` chaining + `synchronousSpill`),
`RapidsDeviceMemoryStore.scala`, `RapidsHostMemoryStore.scala` (pool carved
by AddressSpaceAllocator), `RapidsDiskStore.scala` (disk block manager
files).

TPU twist: the device tier holds live jax Arrays (HBM); spilling serializes
the batch (columnar/serde.py) and pushes the blob down the chain.  Reading a
spilled buffer re-uploads to HBM.  The spill-candidate order is kept in the
native HashedPriorityQueue.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.serde import deserialize_batch, serialize_batch
from spark_rapids_tpu.memory.buffer import (
    BufferId, SpillableBuffer, StorageTier, TableMeta)
from spark_rapids_tpu.memory.native import (
    AddressSpaceAllocator, HashedPriorityQueue, HostArena,
    SpillCorruptionError)
from spark_rapids_tpu.utils import residency as RES

#: the descriptive integrity failure a corrupted spill file surfaces on
#: re-read (instead of deserializing garbage) — re-exported here since
#: the write/verify sites live in this module's disk tier
SpillCorruption = SpillCorruptionError


# ---------------------------------------------------------------------------
# seeded spill-corruption injection: flips one payload byte in a
# freshly written spill file (AFTER the CRC frame landed, like real
# disk rot), proving the CRC-verified re-read raises SpillCorruption
# rather than handing a poisoned batch downstream.  Keyed per
# (rate, seed) like the OOM injectors, so concurrent queries with
# different injection confs drive independent deterministic streams.
import threading as _threading

_SPILL_INJ_LOCK = _threading.Lock()
_SPILL_INJ_RNGS: dict = {}
_SPILL_INJ_COUNT = [0]
#: spill-file frame header: magic(4) + version(4) + len(8) + crc(4) —
#: the flipped byte must land in the payload, not the header, so the
#: CRC check (not a magic/length check) is what catches it
_SPILL_FRAME_HEADER = 20


def reset_spill_corruption() -> None:
    with _SPILL_INJ_LOCK:
        _SPILL_INJ_RNGS.clear()
        _SPILL_INJ_COUNT[0] = 0


def injected_spill_corruptions() -> int:
    with _SPILL_INJ_LOCK:
        return _SPILL_INJ_COUNT[0]


def _maybe_corrupt_spill_file(path: str, payload_len: int) -> None:
    from spark_rapids_tpu import config as C
    import random
    conf = C.get_active_conf()
    rate = float(conf[C.SPILL_CORRUPT_RATE])
    if rate <= 0 or payload_len <= 0:
        return
    seed = int(conf[C.OOM_INJECT_SEED])
    with _SPILL_INJ_LOCK:
        rng = _SPILL_INJ_RNGS.get((rate, seed))
        if rng is None:
            rng = _SPILL_INJ_RNGS[(rate, seed)] = random.Random(seed)
        if rng.random() >= rate:
            return
        offset = _SPILL_FRAME_HEADER + rng.randrange(payload_len)
        _SPILL_INJ_COUNT[0] += 1
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


class BufferStore:
    """Abstract tier: tracks buffers + spill candidates; chains to the next
    tier via `set_spill_store` (reference RapidsBufferStore.setSpillStore)."""

    tier: StorageTier

    def __init__(self, catalog=None):
        self.catalog = catalog
        self._buffers: dict[BufferId, SpillableBuffer] = {}
        self._handle_of: dict[int, BufferId] = {}
        self._spill_queue = HashedPriorityQueue()
        self._lock = threading.RLock()
        self.spill_store: Optional["BufferStore"] = None
        self.current_size = 0

    def set_spill_store(self, store: "BufferStore") -> None:
        self.spill_store = store

    # -- registration --------------------------------------------------------
    def _track(self, buf: SpillableBuffer) -> None:
        with self._lock:
            self._buffers[buf.id] = buf
            buf.store = self
            self.current_size += buf.size_bytes
            h = id(buf)
            self._handle_of[h] = buf.id
            buf._spill_handle = h
            if buf.is_spillable:
                self._spill_queue.offer(h, buf.spill_priority)
            if self.catalog is not None:
                self.catalog.register(buf)
            # HBM residency ledger (utils/residency.py): every tracked
            # buffer carries provenance — query id, site, tier — from
            # birth to free/spill, so "who holds HBM and why" is
            # answerable without touching the device
            if RES.enabled():
                buf._res_token = RES.track(
                    buf.size_bytes, site=RES.buffer_site(buf.id),
                    tier=self.tier.name.lower(), kind=RES.KIND_STORE)

    def remove(self, bid: BufferId) -> None:
        with self._lock:
            buf = self._buffers.pop(bid, None)
            if buf is None:
                return
            self.current_size -= buf.size_bytes
            h = getattr(buf, "_spill_handle", None)
            if h is not None:
                self._spill_queue.remove(h)
                self._handle_of.pop(h, None)
            self._on_remove(buf)
            buf.free()
            RES.retire(getattr(buf, "_res_token", None))
            buf._res_token = None
            if self.catalog is not None:
                self.catalog.unregister(bid)

    def _on_remove(self, buf: SpillableBuffer) -> None:
        """Tier-specific accounting, called under the store lock exactly
        once per successful removal."""

    def get(self, bid: BufferId) -> Optional[SpillableBuffer]:
        with self._lock:
            return self._buffers.get(bid)

    def stats(self) -> dict:
        """Resident bytes + buffer count for this tier (telemetry
        gauge; `current_size` alone races the buffer table)."""
        with self._lock:
            return {"bytes": self.current_size,
                    "buffers": len(self._buffers)}

    def mark_acquired(self, buf: SpillableBuffer) -> None:
        """Pinned buffers leave the spill queue."""
        h = getattr(buf, "_spill_handle", None)
        if h is not None:
            self._spill_queue.remove(h)

    def mark_released(self, buf: SpillableBuffer) -> None:
        if buf.is_spillable:
            h = getattr(buf, "_spill_handle", None)
            if h is not None:
                self._spill_queue.offer(h, buf.spill_priority)

    def update_priority(self, buf: SpillableBuffer, priority: float) -> None:
        buf.spill_priority = priority
        h = getattr(buf, "_spill_handle", None)
        if h is not None and h in self._spill_queue:
            self._spill_queue.update_priority(h, priority)

    # -- spilling ------------------------------------------------------------
    def synchronous_spill(self, target_size: int) -> int:
        """Spill lowest-priority buffers until `current_size <= target_size`.
        Returns bytes freed (reference RapidsBufferStore.synchronousSpill)."""
        import time

        from spark_rapids_tpu.utils import movement as MV
        freed = 0
        while True:
            with self._lock:
                if self.current_size <= target_size:
                    break
                h = self._spill_queue.poll()
                if h is None:
                    break  # nothing spillable left
                bid = self._handle_of.get(h)
                buf = self._buffers.get(bid) if bid is not None else None
                # claim atomically: a reader that pinned the buffer after
                # it entered the spill queue wins, and the buffer stays
                if buf is None or not buf.try_mark_spilling():
                    continue
            if self.spill_store is not None:
                t0 = time.perf_counter_ns()
                # the next-tier copy inherits the ORIGINAL owner's
                # provenance: a pressure spill triggered by query B
                # must never re-attribute query A's bytes
                with RES.inherit_scope(getattr(buf, "_res_token",
                                               None)):
                    dst = self.spill_store.copy_buffer(buf)
                # one ledger record PER HOP: a device->host->disk
                # migration (host pool full, fell through) lands here
                # as device->disk — the hop that actually happened —
                # never as two overlapping device->host + host->disk
                # records for one copy.  src bytes = this tier's
                # accounted size (what spillBytes/bytes_spilled
                # count); payload = the serialized blob that landed.
                if MV.ledger() is not None:
                    MV.record(
                        MV.EDGE_SPILL, buf.size_bytes,
                        site=f"{self.tier.name.lower()}->"
                             f"{dst.tier.name.lower()}",
                        raw_bytes=dst.size_bytes,
                        dur_ns=time.perf_counter_ns() - t0)
            freed += buf.size_bytes
            self.remove_from_tier_only(buf)
        return freed

    def remove_from_tier_only(self, buf: SpillableBuffer) -> None:
        """Drop from this tier without unregistering from the catalog
        (the buffer lives on in the spill store)."""
        with self._lock:
            if self._buffers.pop(buf.id, None) is not None:
                self.current_size -= buf.size_bytes
                self._on_remove(buf)
            h = getattr(buf, "_spill_handle", None)
            if h is not None:
                self._handle_of.pop(h, None)
            buf.free()
            RES.retire(getattr(buf, "_res_token", None))
            buf._res_token = None

    def copy_buffer(self, buf: SpillableBuffer) -> SpillableBuffer:
        """Materialize `buf`'s payload at this tier (spill receive path)."""
        raise NotImplementedError

    @property
    def spillable_size(self) -> int:
        with self._lock:
            return sum(b.size_bytes for b in self._buffers.values()
                       if b.is_spillable)

    def close(self) -> None:
        with self._lock:
            for bid in list(self._buffers):
                self.remove(bid)


# ---------------------------------------------------------------------------
class DeviceBuffer(SpillableBuffer):
    tier = StorageTier.DEVICE

    def __init__(self, bid: BufferId, batch: ColumnarBatch, meta: TableMeta,
                 spill_priority: float):
        super().__init__(bid, meta, spill_priority)
        self._batch = batch

    def get_columnar_batch(self) -> ColumnarBatch:
        return self._batch

    def get_host_bytes(self) -> bytes:
        return serialize_batch(self._batch)

    def free(self) -> None:
        super().free()
        self._batch = None  # drop HBM references


class DeviceMemoryStore(BufferStore):
    """HBM tier (reference RapidsDeviceMemoryStore.addTable/addBuffer)."""

    tier = StorageTier.DEVICE

    def __init__(self, catalog=None, device_manager=None):
        super().__init__(catalog)
        self.device_manager = device_manager

    def add_batch(self, bid: BufferId, batch: ColumnarBatch,
                  spill_priority: float = 0.0) -> DeviceBuffer:
        from spark_rapids_tpu.memory.buffer import meta_for_batch
        meta = meta_for_batch(batch)
        buf = DeviceBuffer(bid, batch, meta, spill_priority)
        if self.device_manager is not None:
            self.device_manager.track_store_bytes(
                meta.size_bytes, site="device-store.add")
        self._track(buf)
        return buf

    def _on_remove(self, buf: SpillableBuffer) -> None:
        if self.device_manager is not None:
            self.device_manager.track_store_bytes(
                -buf.size_bytes, site="device-store.remove")

    def copy_buffer(self, buf: SpillableBuffer) -> SpillableBuffer:
        batch = buf.get_columnar_batch()
        return self.add_batch(buf.id, batch, buf.spill_priority)


# ---------------------------------------------------------------------------
class HostBuffer(SpillableBuffer):
    tier = StorageTier.HOST

    def __init__(self, bid: BufferId, store: "HostMemoryStore", offset: int,
                 length: int, meta: TableMeta, spill_priority: float):
        super().__init__(bid, meta, spill_priority)
        self._host_store = store
        self._offset = offset
        self._length = length

    def get_host_bytes(self) -> bytes:
        return self._host_store.arena.read(self._offset, self._length)

    def get_columnar_batch(self) -> ColumnarBatch:
        return deserialize_batch(self.get_host_bytes())

    def free(self) -> None:
        super().free()
        self._host_store.arena.allocator.free(self._offset)

    @property
    def size_bytes(self) -> int:
        return self._length


class HostMemoryStore(BufferStore):
    """Host tier: fixed pool carved by the native first-fit allocator
    (reference RapidsHostMemoryStore + AddressSpaceAllocator.scala).  When
    the pool cannot fit a blob, it passes straight down to the spill store
    (the reference's host-store behavior on allocation failure)."""

    tier = StorageTier.HOST

    def __init__(self, size: int, catalog=None):
        super().__init__(catalog)
        self.arena = HostArena(size)

    def copy_buffer(self, buf: SpillableBuffer) -> SpillableBuffer:
        return self._add(buf.id, buf.get_host_bytes, buf.meta,
                         buf.spill_priority,
                         lambda: self.spill_store.copy_buffer(buf))

    def add_blob(self, bid: BufferId, blob: bytes, meta: TableMeta,
                 spill_priority: float = 0.0) -> SpillableBuffer:
        """Store an already-serialized batch (shuffle receive path —
        reference ShuffleReceivedBufferCatalog adds to the host tier)."""
        return self._add(
            bid, lambda: blob, meta, spill_priority,
            lambda: self.spill_store.add_blob(bid, blob, meta,
                                              spill_priority))

    def _add(self, bid: BufferId, get_blob, meta: TableMeta,
             spill_priority: float, fall_through) -> SpillableBuffer:
        blob = get_blob()
        off = self.arena.allocator.allocate(len(blob))
        if off is None:
            # try to make room by spilling our own contents downward
            if self.spill_store is not None:
                self.synchronous_spill(
                    max(0, self.current_size - len(blob)))
                off = self.arena.allocator.allocate(len(blob))
            if off is None:
                if self.spill_store is None:
                    raise MemoryError(
                        f"host store full ({len(blob)} bytes needed)")
                return fall_through()
        self.arena.write(off, blob)
        hb = HostBuffer(bid, self, off, len(blob), meta, spill_priority)
        self._track(hb)
        return hb


# ---------------------------------------------------------------------------
class DiskBlockManager:
    """Maps buffer ids to spill files in a managed temp dir (reference
    RapidsDiskBlockManager over Spark's disk block manager)."""

    def __init__(self, root: Optional[str] = None):
        import tempfile
        self.root = root or tempfile.mkdtemp(prefix="tpu-spill-")
        os.makedirs(self.root, exist_ok=True)

    def path_for(self, bid: BufferId) -> str:
        return os.path.join(
            self.root,
            f"t{bid.table_id}_s{bid.shuffle_id}_m{bid.map_id}"
            f"_p{bid.partition}.bin")

    def cleanup(self) -> None:
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)


class DiskBuffer(SpillableBuffer):
    tier = StorageTier.DISK

    def __init__(self, bid: BufferId, path: str, length: int, meta: TableMeta,
                 spill_priority: float):
        super().__init__(bid, meta, spill_priority)
        self._path = path
        self._length = length

    def get_host_bytes(self) -> bytes:
        # CRC-verified read: corruption surfaces as SpillCorruptionError
        # instead of a poisoned batch (memory/native spill framing)
        import time

        from spark_rapids_tpu.memory.native import spill_read
        from spark_rapids_tpu.utils import movement as MV
        t0 = time.perf_counter_ns()
        blob = spill_read(self._path)
        if MV.ledger() is not None:
            MV.record(MV.EDGE_SPILL, len(blob), site="disk->host",
                      dur_ns=time.perf_counter_ns() - t0)
        return blob

    def get_columnar_batch(self) -> ColumnarBatch:
        return deserialize_batch(self.get_host_bytes())

    def free(self) -> None:
        super().free()
        try:
            os.unlink(self._path)
        except FileNotFoundError:
            pass
        except OSError:
            # teardown race (directory concurrently swept, file still
            # mapped, ...): hand the path to the owning store so
            # close() retries the unlink instead of leaking the spill
            # file on disk forever
            store = self.store
            if store is not None and hasattr(store, "_note_orphan"):
                store._note_orphan(self._path)

    @property
    def size_bytes(self) -> int:
        return self._length

    @property
    def is_spillable(self) -> bool:
        return False  # bottom tier


class DiskStore(BufferStore):
    tier = StorageTier.DISK

    def __init__(self, block_manager: Optional[DiskBlockManager] = None,
                 catalog=None):
        super().__init__(catalog)
        self.block_manager = block_manager or DiskBlockManager()
        #: unlink-failed paths from freed buffers (teardown races) —
        #: close() retries these so nothing leaks on disk
        self._orphans: set[str] = set()
        #: corrupt spill files set aside by quarantine(): preserved
        #: for triage until close(), never re-readable as data
        self._quarantined: set[str] = set()

    def _note_orphan(self, path: str) -> None:
        with self._lock:
            self._orphans.add(path)

    def quarantine(self, bid: BufferId) -> Optional[str]:
        """Corrupt-spill handling (memory/oocore.py): pull the buffer
        out of the store and rename its file to `*.quarantined`, so
        the poisoned bytes survive for triage but can never be
        re-read as data.  Returns the quarantined path, or None when
        the buffer is not resident at this tier."""
        with self._lock:
            buf = self._buffers.pop(bid, None)
            if buf is None:
                return None
            self.current_size -= buf.size_bytes
            h = getattr(buf, "_spill_handle", None)
            if h is not None:
                self._spill_queue.remove(h)
                self._handle_of.pop(h, None)
        qpath = buf._path + ".quarantined"
        try:
            os.replace(buf._path, qpath)
        except OSError:
            qpath = buf._path  # rename failed: track the original
        with self._lock:
            self._quarantined.add(qpath)
        # mark closed WITHOUT DiskBuffer.free()'s unlink — the
        # quarantined file must survive until close()
        SpillableBuffer.free(buf)
        RES.retire(getattr(buf, "_res_token", None))
        buf._res_token = None
        if self.catalog is not None:
            self.catalog.unregister(bid)
        return qpath

    def orphaned_spill_files(self) -> list[str]:
        """Spill files in the block manager's directory that no live
        buffer owns and that are not quarantined — freed-buffer unlink
        leaks.  The teardown leak checks assert this is empty."""
        with self._lock:
            owned = {b._path for b in self._buffers.values()}
            quarantined = set(self._quarantined)
        try:
            names = os.listdir(self.block_manager.root)
        except OSError:
            return []
        out = []
        for name in names:
            p = os.path.join(self.block_manager.root, name)
            if p not in owned and p not in quarantined:
                out.append(p)
        return sorted(out)

    def copy_buffer(self, buf: SpillableBuffer) -> SpillableBuffer:
        return self.add_blob(buf.id, buf.get_host_bytes(), buf.meta,
                             buf.spill_priority)

    def add_blob(self, bid: BufferId, blob: bytes, meta: TableMeta,
                 spill_priority: float = 0.0) -> SpillableBuffer:
        from spark_rapids_tpu.memory.native import spill_write
        path = self.block_manager.path_for(bid)
        # CRC-framed + fsync'd (native runtime.cpp; the role the JVM's
        # checksummed spill writers play in the reference stack)
        spill_write(path, blob)
        # seeded integrity-failure injection (device->disk and
        # host->disk both land here): the re-read must surface
        # SpillCorruption, never a garbage batch
        _maybe_corrupt_spill_file(path, len(blob))
        db = DiskBuffer(bid, path, len(blob), meta, spill_priority)
        self._track(db)
        return db

    def close(self) -> None:
        super().close()
        # explicitly drain quarantined + orphaned files: cleanup()'s
        # ignore_errors rmtree used to hide these leaks — now the
        # directory is emptied file-by-file first, so a post-close
        # scan (or a failed rmtree) can prove it really drained
        with self._lock:
            leftovers = self._orphans | self._quarantined
            self._orphans.clear()
            self._quarantined.clear()
        for p in leftovers | set(self.orphaned_spill_files()):
            try:
                os.unlink(p)
            except OSError:
                pass
        self.block_manager.cleanup()
