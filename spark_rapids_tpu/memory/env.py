"""Executor-side resource environment: catalog + device->host->disk store
chain + spill handler install (reference `GpuShuffleEnv.initStorage`
`GpuShuffleEnv.scala:52-69`, which wires RapidsDeviceMemoryStore ->
RapidsHostMemoryStore -> RapidsDiskStore and installs the RMM event
handler).
"""
from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.device_manager import DeviceManager
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.stores import (
    DeviceMemoryStore, DiskBlockManager, DiskStore, HostMemoryStore)


class ResourceEnv:
    _instance: Optional["ResourceEnv"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[C.RapidsConf] = None,
                 hbm_total: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        conf = conf or C.get_active_conf()
        self.conf = conf
        self.catalog = BufferCatalog()
        self.device_manager = DeviceManager.initialize(conf, hbm_total)
        self.device_store = DeviceMemoryStore(self.catalog,
                                             self.device_manager)
        self.host_store = HostMemoryStore(conf[C.HOST_SPILL_STORAGE],
                                          self.catalog)
        self.disk_store = DiskStore(DiskBlockManager(spill_dir), self.catalog)
        self.device_store.set_spill_store(self.host_store)
        self.host_store.set_spill_store(self.disk_store)
        self.spill_callback = self.device_manager.install_spill_handler(
            self.device_store)
        self.semaphore = TpuSemaphore.initialize(
            conf[C.CONCURRENT_TPU_TASKS])

    @classmethod
    def init(cls, conf: Optional[C.RapidsConf] = None,
             hbm_total: Optional[int] = None,
             spill_dir: Optional[str] = None) -> "ResourceEnv":
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
            DeviceManager.shutdown()
            cls._instance = cls(conf, hbm_total, spill_dir)
            return cls._instance

    @classmethod
    def get(cls) -> "ResourceEnv":
        with cls._lock:
            if cls._instance is None:
                DeviceManager.shutdown()
                cls._instance = cls()
            return cls._instance

    @classmethod
    def peek(cls) -> Optional["ResourceEnv"]:
        """The live environment WITHOUT constructing one (telemetry
        scrapes must never initialize the store chain)."""
        with cls._lock:
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
                cls._instance = None
            DeviceManager.shutdown()
            TpuSemaphore.shutdown()

    def close(self) -> None:
        for store in (self.device_store, self.host_store, self.disk_store):
            store.close()
