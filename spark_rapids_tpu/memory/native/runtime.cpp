// Native runtime primitives for the TPU columnar engine.
//
// The reference keeps its native code in external deps (cuDF/RMM); its
// in-JVM memory bookkeeping lives in AddressSpaceAllocator.scala (first-fit
// address-space allocator carving the pinned/host pool) and
// HashedPriorityQueue.java (O(log n) priority queue with O(1) containment
// for spill-priority tracking).  This library provides the same two
// primitives as C++ with a C ABI, loaded from Python via ctypes
// (spark_rapids_tpu/memory/native/__init__.py).
//
// Build: g++ -O2 -shared -fPIC -o _runtime.so runtime.cpp
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Address-space allocator: first-fit over [0, size) with block splitting and
// free-neighbour coalescing (reference AddressSpaceAllocator.scala behavior).
struct AsaBlock {
  uint64_t size;
  bool free;
};

struct Asa {
  // offset -> block; ordered so neighbours coalesce in O(log n)
  std::map<uint64_t, AsaBlock> blocks;
  uint64_t total;
  uint64_t allocated;
  std::mutex mu;
};

void* asa_create(uint64_t size) {
  Asa* a = new Asa();
  a->total = size;
  a->allocated = 0;
  a->blocks[0] = AsaBlock{size, true};
  return a;
}

void asa_destroy(void* h) { delete static_cast<Asa*>(h); }

// Returns the offset of the allocation, or UINT64_MAX when it does not fit.
uint64_t asa_allocate(void* h, uint64_t size) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  if (size == 0) size = 1;
  for (auto it = a->blocks.begin(); it != a->blocks.end(); ++it) {
    if (!it->second.free || it->second.size < size) continue;
    uint64_t off = it->first;
    uint64_t remain = it->second.size - size;
    it->second.size = size;
    it->second.free = false;
    if (remain > 0) a->blocks[off + size] = AsaBlock{remain, true};
    a->allocated += size;
    return off;
  }
  return UINT64_MAX;
}

// Frees the block at `offset`; returns its size, or UINT64_MAX if unknown.
uint64_t asa_free(void* h, uint64_t offset) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  auto it = a->blocks.find(offset);
  if (it == a->blocks.end() || it->second.free) return UINT64_MAX;
  uint64_t size = it->second.size;
  it->second.free = true;
  a->allocated -= size;
  // coalesce with next
  auto nx = std::next(it);
  if (nx != a->blocks.end() && nx->second.free) {
    it->second.size += nx->second.size;
    a->blocks.erase(nx);
  }
  // coalesce with prev
  if (it != a->blocks.begin()) {
    auto pv = std::prev(it);
    if (pv->second.free) {
      pv->second.size += it->second.size;
      a->blocks.erase(it);
    }
  }
  return size;
}

uint64_t asa_allocated(void* h) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  return a->allocated;
}

uint64_t asa_available(void* h) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  return a->total - a->allocated;
}

// Largest free block — how big an allocation could currently succeed.
uint64_t asa_largest_free(void* h) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  uint64_t best = 0;
  for (auto& kv : a->blocks)
    if (kv.second.free && kv.second.size > best) best = kv.second.size;
  return best;
}

// ---------------------------------------------------------------------------
// Hashed priority queue keyed by int64 id with double priority; lowest
// priority polls first (spill candidates).  FIFO tie-break via sequence
// number, like the reference's insertion-ordered comparator behavior.
struct Hpq {
  // (priority, seq) -> id
  std::map<std::pair<double, uint64_t>, int64_t> q;
  std::unordered_map<int64_t, std::pair<double, uint64_t>> pos;
  uint64_t seq = 0;
  std::mutex mu;
};

void* hpq_create() { return new Hpq(); }
void hpq_destroy(void* h) { delete static_cast<Hpq*>(h); }

void hpq_offer(void* h, int64_t id, double priority) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  auto it = p->pos.find(id);
  if (it != p->pos.end()) p->q.erase(it->second);
  auto key = std::make_pair(priority, p->seq++);
  p->q[key] = id;
  p->pos[id] = key;
}

// Pops the lowest-priority element; INT64_MIN when empty.
int64_t hpq_poll(void* h) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->q.empty()) return INT64_MIN;
  auto it = p->q.begin();
  int64_t id = it->second;
  p->pos.erase(id);
  p->q.erase(it);
  return id;
}

int64_t hpq_peek(void* h) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->q.empty()) return INT64_MIN;
  return p->q.begin()->second;
}

// 1 if removed, 0 if absent.
int hpq_remove(void* h, int64_t id) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  auto it = p->pos.find(id);
  if (it == p->pos.end()) return 0;
  p->q.erase(it->second);
  p->pos.erase(it);
  return 1;
}

int hpq_contains(void* h, int64_t id) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->pos.count(id) ? 1 : 0;
}

void hpq_update_priority(void* h, int64_t id, double priority) {
  hpq_remove(h, id);
  hpq_offer(h, id, priority);
}

uint64_t hpq_size(void* h) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->q.size();
}

// ---------------------------------------------------------------------------
// Pinned-staging arena: one big malloc'd host buffer the Python side reads /
// writes through memoryviews (the PinnedMemoryPool analog — page-locked DMA
// staging is a TPU-runtime concern; here we provide the pool carving +
// stable addresses the stores need).
void* arena_create(uint64_t size) { return std::malloc(size); }
void arena_destroy(void* p) { std::free(p); }
void arena_write(void* p, uint64_t off, const uint8_t* src, uint64_t n) {
  std::memcpy(static_cast<uint8_t*>(p) + off, src, n);
}
void arena_read(void* p, uint64_t off, uint8_t* dst, uint64_t n) {
  std::memcpy(dst, static_cast<uint8_t*>(p) + off, n);
}

}  // extern "C"
