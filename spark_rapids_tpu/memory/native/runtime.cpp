// Native runtime primitives for the TPU columnar engine.
//
// The reference keeps its native code in external deps (cuDF/RMM); its
// in-JVM memory bookkeeping lives in AddressSpaceAllocator.scala (first-fit
// address-space allocator carving the pinned/host pool) and
// HashedPriorityQueue.java (O(log n) priority queue with O(1) containment
// for spill-priority tracking).  This library provides the same two
// primitives as C++ with a C ABI, loaded from Python via ctypes
// (spark_rapids_tpu/memory/native/__init__.py).
//
// Build: g++ -O2 -shared -fPIC -o _runtime.so runtime.cpp
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Address-space allocator: first-fit over [0, size) with block splitting and
// free-neighbour coalescing (reference AddressSpaceAllocator.scala behavior).
struct AsaBlock {
  uint64_t size;
  bool free;
};

struct Asa {
  // offset -> block; ordered so neighbours coalesce in O(log n)
  std::map<uint64_t, AsaBlock> blocks;
  uint64_t total;
  uint64_t allocated;
  std::mutex mu;
};

void* asa_create(uint64_t size) {
  Asa* a = new Asa();
  a->total = size;
  a->allocated = 0;
  a->blocks[0] = AsaBlock{size, true};
  return a;
}

void asa_destroy(void* h) { delete static_cast<Asa*>(h); }

// Returns the offset of the allocation, or UINT64_MAX when it does not fit.
uint64_t asa_allocate(void* h, uint64_t size) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  if (size == 0) size = 1;
  for (auto it = a->blocks.begin(); it != a->blocks.end(); ++it) {
    if (!it->second.free || it->second.size < size) continue;
    uint64_t off = it->first;
    uint64_t remain = it->second.size - size;
    it->second.size = size;
    it->second.free = false;
    if (remain > 0) a->blocks[off + size] = AsaBlock{remain, true};
    a->allocated += size;
    return off;
  }
  return UINT64_MAX;
}

// Frees the block at `offset`; returns its size, or UINT64_MAX if unknown.
uint64_t asa_free(void* h, uint64_t offset) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  auto it = a->blocks.find(offset);
  if (it == a->blocks.end() || it->second.free) return UINT64_MAX;
  uint64_t size = it->second.size;
  it->second.free = true;
  a->allocated -= size;
  // coalesce with next
  auto nx = std::next(it);
  if (nx != a->blocks.end() && nx->second.free) {
    it->second.size += nx->second.size;
    a->blocks.erase(nx);
  }
  // coalesce with prev
  if (it != a->blocks.begin()) {
    auto pv = std::prev(it);
    if (pv->second.free) {
      pv->second.size += it->second.size;
      a->blocks.erase(it);
    }
  }
  return size;
}

uint64_t asa_allocated(void* h) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  return a->allocated;
}

uint64_t asa_available(void* h) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  return a->total - a->allocated;
}

// Largest free block — how big an allocation could currently succeed.
uint64_t asa_largest_free(void* h) {
  Asa* a = static_cast<Asa*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  uint64_t best = 0;
  for (auto& kv : a->blocks)
    if (kv.second.free && kv.second.size > best) best = kv.second.size;
  return best;
}

// ---------------------------------------------------------------------------
// Hashed priority queue keyed by int64 id with double priority; lowest
// priority polls first (spill candidates).  FIFO tie-break via sequence
// number, like the reference's insertion-ordered comparator behavior.
struct Hpq {
  // (priority, seq) -> id
  std::map<std::pair<double, uint64_t>, int64_t> q;
  std::unordered_map<int64_t, std::pair<double, uint64_t>> pos;
  uint64_t seq = 0;
  std::mutex mu;
};

void* hpq_create() { return new Hpq(); }
void hpq_destroy(void* h) { delete static_cast<Hpq*>(h); }

void hpq_offer(void* h, int64_t id, double priority) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  auto it = p->pos.find(id);
  if (it != p->pos.end()) p->q.erase(it->second);
  auto key = std::make_pair(priority, p->seq++);
  p->q[key] = id;
  p->pos[id] = key;
}

// Pops the lowest-priority element; INT64_MIN when empty.
int64_t hpq_poll(void* h) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->q.empty()) return INT64_MIN;
  auto it = p->q.begin();
  int64_t id = it->second;
  p->pos.erase(id);
  p->q.erase(it);
  return id;
}

int64_t hpq_peek(void* h) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->q.empty()) return INT64_MIN;
  return p->q.begin()->second;
}

// 1 if removed, 0 if absent.
int hpq_remove(void* h, int64_t id) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  auto it = p->pos.find(id);
  if (it == p->pos.end()) return 0;
  p->q.erase(it->second);
  p->pos.erase(it);
  return 1;
}

int hpq_contains(void* h, int64_t id) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->pos.count(id) ? 1 : 0;
}

void hpq_update_priority(void* h, int64_t id, double priority) {
  hpq_remove(h, id);
  hpq_offer(h, id, priority);
}

uint64_t hpq_size(void* h) {
  Hpq* p = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->q.size();
}

// ---------------------------------------------------------------------------
// Pinned-staging arena: one big malloc'd host buffer the Python side reads /
// writes through memoryviews (the PinnedMemoryPool analog — page-locked DMA
// staging is a TPU-runtime concern; here we provide the pool carving +
// stable addresses the stores need).
void* arena_create(uint64_t size) { return std::malloc(size); }
void arena_destroy(void* p) { std::free(p); }
void arena_write(void* p, uint64_t off, const uint8_t* src, uint64_t n) {
  std::memcpy(static_cast<uint8_t*>(p) + off, src, n);
}
void arena_read(void* p, uint64_t off, uint8_t* dst, uint64_t n) {
  std::memcpy(dst, static_cast<uint8_t*>(p) + off, n);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Spill-file IO with integrity framing (the role the JVM's checksummed
// shuffle/spill writers play; cuDF-side buffers get this from the
// filesystem layer in the reference).  Format:
//   magic "TPUS" | u32 version | u64 payload_len | u32 crc32 | payload
// Header integers are host-endian; the engine's supported hosts (x86,
// ARM) are little-endian, matching the Python fallback's "<IQI". A
// big-endian port would need explicit LE writes here.
// Written with fsync so a spilled buffer survives a crash of the
// executor process; read verifies length + CRC and reports corruption
// instead of handing poisoned bytes to the engine.
#include <cstdio>
#if defined(__unix__) || defined(__APPLE__)
#include <unistd.h>
#endif

extern "C" {

// C++11 magic-static init: thread-safe even when ctypes calls arrive
// concurrently with the GIL released
static const uint32_t* crc32_table_get() {
  static const uint32_t* table = [] {
    static uint32_t t[256];
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  return table;
}

uint32_t rt_crc32(const uint8_t* data, uint64_t n) {
  const uint32_t* table = crc32_table_get();
  uint32_t c = 0xFFFFFFFFu;
  for (uint64_t i = 0; i < n; i++)
    c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

static const char kSpillMagic[4] = {'T', 'P', 'U', 'S'};
static const uint32_t kSpillVersion = 1;

// returns 0 on success, negative errno-style codes on failure
int64_t spill_write(const char* path, const uint8_t* data, uint64_t n) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  uint32_t crc = rt_crc32(data, n);
  bool ok = std::fwrite(kSpillMagic, 1, 4, f) == 4 &&
            std::fwrite(&kSpillVersion, 4, 1, f) == 1 &&
            std::fwrite(&n, 8, 1, f) == 1 &&
            std::fwrite(&crc, 4, 1, f) == 1 &&
            (n == 0 || std::fwrite(data, 1, n, f) == n);
  if (ok) ok = std::fflush(f) == 0;
#if defined(__unix__) || defined(__APPLE__)
  if (ok) ok = fsync(fileno(f)) == 0;
#endif
  std::fclose(f);
  return ok ? 0 : -2;
}

// returns payload length, or negative code: -1 open, -2 header,
// -3 bad magic/version, -4 size mismatch, -5 crc mismatch
int64_t spill_read_size(const char* path) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char magic[4];
  uint32_t version, crc;
  uint64_t n;
  bool ok = std::fread(magic, 1, 4, f) == 4 &&
            std::fread(&version, 4, 1, f) == 1 &&
            std::fread(&n, 8, 1, f) == 1 &&
            std::fread(&crc, 4, 1, f) == 1;
  // 64-bit tell: long is 32-bit on LLP64 (Windows), so >2GB spill
  // files would misreport size through std::ftell (ADVICE r1)
#if defined(_WIN32)
  int64_t hdr_end = ok ? _ftelli64(f) : 0;
  int64_t file_end = 0;
  if (ok && _fseeki64(f, 0, SEEK_END) == 0) file_end = _ftelli64(f);
#else
  int64_t hdr_end = ok ? static_cast<int64_t>(ftello(f)) : 0;
  int64_t file_end = 0;
  if (ok && fseeko(f, 0, SEEK_END) == 0)
    file_end = static_cast<int64_t>(ftello(f));
#endif
  std::fclose(f);
  if (!ok) return -2;
  if (std::memcmp(magic, kSpillMagic, 4) != 0 || version != kSpillVersion)
    return -3;
  // a corrupted length field must not escape as a huge allocation
  if (file_end - hdr_end != static_cast<int64_t>(n)) return -4;
  return static_cast<int64_t>(n);
}

int64_t spill_read(const char* path, uint8_t* out, uint64_t out_len) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char magic[4];
  uint32_t version, crc;
  uint64_t n;
  bool ok = std::fread(magic, 1, 4, f) == 4 &&
            std::fread(&version, 4, 1, f) == 1 &&
            std::fread(&n, 8, 1, f) == 1 &&
            std::fread(&crc, 4, 1, f) == 1;
  if (!ok) { std::fclose(f); return -2; }
  if (std::memcmp(magic, kSpillMagic, 4) != 0 ||
      version != kSpillVersion) { std::fclose(f); return -3; }
  if (n != out_len) { std::fclose(f); return -4; }
  ok = (n == 0) || std::fread(out, 1, n, f) == n;
  std::fclose(f);
  if (!ok) return -4;
  if (rt_crc32(out, n) != crc) return -5;
  return static_cast<int64_t>(n);
}

}  // extern "C"
