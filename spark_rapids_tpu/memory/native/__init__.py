"""ctypes loader for the native runtime (runtime.cpp).

Compiles the shared library on first import with g++ (toolchain is part of
the supported environment); falls back to pure-Python implementations when
compilation is impossible so the engine still runs.  The native pieces are
the analogs of the reference's in-JVM memory bookkeeping
(`AddressSpaceAllocator.scala`, `HashedPriorityQueue.java`) plus a host
staging arena standing in for the pinned memory pool
(`GpuDeviceManager.scala:243-249`).
"""
from __future__ import annotations

import ctypes
import heapq
import os
import subprocess
import threading

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "runtime.cpp")
_SO = os.path.join(_HERE, "_runtime.so")

_lib = None
_lib_lock = threading.Lock()


def _compile() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def load_native():
    """Load (compiling if needed) the native runtime; None if unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u64, i64, f64, p = (ctypes.c_uint64, ctypes.c_int64,
                            ctypes.c_double, ctypes.c_void_p)
        lib.asa_create.restype = p
        lib.asa_create.argtypes = [u64]
        lib.asa_destroy.argtypes = [p]
        lib.asa_allocate.restype = u64
        lib.asa_allocate.argtypes = [p, u64]
        lib.asa_free.restype = u64
        lib.asa_free.argtypes = [p, u64]
        for fn in ("asa_allocated", "asa_available", "asa_largest_free"):
            getattr(lib, fn).restype = u64
            getattr(lib, fn).argtypes = [p]
        lib.hpq_create.restype = p
        lib.hpq_destroy.argtypes = [p]
        lib.hpq_offer.argtypes = [p, i64, f64]
        lib.hpq_poll.restype = i64
        lib.hpq_poll.argtypes = [p]
        lib.hpq_peek.restype = i64
        lib.hpq_peek.argtypes = [p]
        lib.hpq_remove.restype = ctypes.c_int
        lib.hpq_remove.argtypes = [p, i64]
        lib.hpq_contains.restype = ctypes.c_int
        lib.hpq_contains.argtypes = [p, i64]
        lib.hpq_update_priority.argtypes = [p, i64, f64]
        lib.hpq_size.restype = u64
        lib.hpq_size.argtypes = [p]
        lib.arena_create.restype = p
        lib.arena_create.argtypes = [u64]
        lib.arena_destroy.argtypes = [p]
        lib.arena_write.argtypes = [p, u64, ctypes.c_char_p, u64]
        lib.arena_read.argtypes = [p, u64, ctypes.c_char_p, u64]
        lib.rt_crc32.restype = ctypes.c_uint32
        lib.rt_crc32.argtypes = [ctypes.c_char_p, u64]
        lib.spill_write.restype = i64
        lib.spill_write.argtypes = [ctypes.c_char_p, ctypes.c_char_p, u64]
        lib.spill_read_size.restype = i64
        lib.spill_read_size.argtypes = [ctypes.c_char_p]
        lib.spill_read.restype = i64
        lib.spill_read.argtypes = [ctypes.c_char_p, ctypes.c_char_p, u64]
        _lib = lib
        return _lib


_UNFIT = 2**64 - 1


class AddressSpaceAllocator:
    """First-fit address-space allocator (native-backed with Python
    fallback).  `allocate` returns an offset or None when it does not fit."""

    def __init__(self, size: int):
        self.size = size
        self._lib = load_native()
        if self._lib is not None:
            self._h = self._lib.asa_create(size)
            self._sizes = None
        else:
            self._h = None
            self._free: list[tuple[int, int]] = [(0, size)]  # (offset, size)
            self._sizes: dict[int, int] = {}
            self._lock = threading.Lock()

    def allocate(self, size: int):
        size = max(1, size)
        if self._h is not None:
            off = self._lib.asa_allocate(self._h, size)
            return None if off == _UNFIT else off
        with self._lock:
            for i, (off, sz) in enumerate(self._free):
                if sz >= size:
                    if sz > size:
                        self._free[i] = (off + size, sz - size)
                    else:
                        del self._free[i]
                    self._sizes[off] = size
                    return off
            return None

    def free(self, offset: int):
        if self._h is not None:
            sz = self._lib.asa_free(self._h, offset)
            return None if sz == _UNFIT else sz
        with self._lock:
            size = self._sizes.pop(offset, None)
            if size is None:
                return None
            self._free.append((offset, size))
            self._free.sort()
            merged = []
            for off, sz in self._free:
                if merged and merged[-1][0] + merged[-1][1] == off:
                    merged[-1] = (merged[-1][0], merged[-1][1] + sz)
                else:
                    merged.append((off, sz))
            self._free = merged
            return size

    @property
    def allocated(self) -> int:
        if self._h is not None:
            return self._lib.asa_allocated(self._h)
        with self._lock:
            return sum(self._sizes.values())

    @property
    def available(self) -> int:
        return self.size - self.allocated

    def __del__(self):
        if getattr(self, "_h", None) is not None and self._lib is not None:
            try:
                self._lib.asa_destroy(self._h)
            except Exception:
                pass


_EMPTY = -2**63


class HashedPriorityQueue:
    """Priority queue with O(1) containment and priority update, keyed by
    int64 id; lowest priority polls first (spill candidate order)."""

    def __init__(self):
        self._lib = load_native()
        if self._lib is not None:
            self._h = self._lib.hpq_create()
        else:
            self._h = None
            self._heap: list[tuple[float, int, int]] = []
            self._entry: dict[int, tuple[float, int]] = {}
            self._seq = 0
            self._lock = threading.Lock()

    def offer(self, id_: int, priority: float) -> None:
        if self._h is not None:
            self._lib.hpq_offer(self._h, id_, priority)
            return
        with self._lock:
            self._seq += 1
            self._entry[id_] = (priority, self._seq)
            heapq.heappush(self._heap, (priority, self._seq, id_))

    def poll(self):
        if self._h is not None:
            v = self._lib.hpq_poll(self._h)
            return None if v == _EMPTY else v
        with self._lock:
            while self._heap:
                prio, seq, id_ = heapq.heappop(self._heap)
                if self._entry.get(id_) == (prio, seq):
                    del self._entry[id_]
                    return id_
            return None

    def peek(self):
        if self._h is not None:
            v = self._lib.hpq_peek(self._h)
            return None if v == _EMPTY else v
        with self._lock:
            while self._heap:
                prio, seq, id_ = self._heap[0]
                if self._entry.get(id_) == (prio, seq):
                    return id_
                heapq.heappop(self._heap)
            return None

    def remove(self, id_: int) -> bool:
        if self._h is not None:
            return bool(self._lib.hpq_remove(self._h, id_))
        with self._lock:
            return self._entry.pop(id_, None) is not None

    def __contains__(self, id_: int) -> bool:
        if self._h is not None:
            return bool(self._lib.hpq_contains(self._h, id_))
        with self._lock:
            return id_ in self._entry

    def update_priority(self, id_: int, priority: float) -> None:
        if self._h is not None:
            self._lib.hpq_update_priority(self._h, id_, priority)
            return
        self.remove(id_)
        self.offer(id_, priority)

    def __len__(self) -> int:
        if self._h is not None:
            return self._lib.hpq_size(self._h)
        with self._lock:
            return len(self._entry)

    def __del__(self):
        if getattr(self, "_h", None) is not None and self._lib is not None:
            try:
                self._lib.hpq_destroy(self._h)
            except Exception:
                pass


class HostArena:
    """Host staging arena carved by an AddressSpaceAllocator — the pool the
    host memory store writes spilled device payloads into (pinned-pool
    analog; reference RapidsHostMemoryStore + PinnedMemoryPool)."""

    def __init__(self, size: int):
        self.size = size
        self.allocator = AddressSpaceAllocator(size)
        self._lib = load_native()
        if self._lib is not None:
            self._buf = self._lib.arena_create(size)
            if not self._buf:
                self._lib = None
        if self._lib is None:
            self._mem = bytearray(size)

    def write(self, offset: int, data: bytes) -> None:
        if self._lib is not None:
            self._lib.arena_write(self._buf, offset, bytes(data), len(data))
        else:
            self._mem[offset:offset + len(data)] = data

    def read(self, offset: int, n: int) -> bytes:
        if self._lib is not None:
            out = ctypes.create_string_buffer(n)
            self._lib.arena_read(self._buf, offset, out, n)
            return out.raw
        return bytes(self._mem[offset:offset + n])

    def __del__(self):
        if getattr(self, "_lib", None) is not None and \
                getattr(self, "_buf", None):
            try:
                self._lib.arena_destroy(self._buf)
            except Exception:
                pass


# ---------------------------------------------------------------------------
class SpillCorruptionError(IOError):
    """A CRC-framed spill file failed its integrity check."""


_SPILL_ERRORS = {-1: "cannot open", -2: "truncated header",
                 -3: "bad magic/version", -4: "payload size mismatch",
                 -5: "checksum mismatch"}


def spill_write(path: str, blob: bytes) -> None:
    """Write a spill file with CRC framing + fsync (native fast path;
    Python fallback writes the same format so files interoperate)."""
    lib = load_native()
    if lib is not None:
        rc = lib.spill_write(path.encode(), blob, len(blob))
        if rc != 0:
            raise IOError(f"spill write failed ({rc}) for {path}")
        return
    import struct
    import zlib
    crc = zlib.crc32(blob) & 0xFFFFFFFF
    with open(path, "wb") as f:
        f.write(b"TPUS" + struct.pack("<IQI", 1, len(blob), crc) + blob)
        f.flush()
        os.fsync(f.fileno())


def spill_read(path: str) -> bytes:
    """Read + verify a CRC-framed spill file; raises
    SpillCorruptionError on any integrity failure instead of handing
    poisoned bytes to the engine."""
    lib = load_native()
    if lib is not None:
        n = lib.spill_read_size(path.encode())
        if n < 0:
            raise SpillCorruptionError(
                f"spill file {path}: "
                f"{_SPILL_ERRORS.get(n, 'unreadable')}")
        buf = (ctypes.c_char * int(n))()
        rc = lib.spill_read(path.encode(), buf, int(n))
        if rc < 0:
            raise SpillCorruptionError(
                f"spill file {path}: {_SPILL_ERRORS.get(rc, 'bad')}")
        return bytes(buf)
    import struct
    import zlib
    with open(path, "rb") as f:
        hdr = f.read(20)
        if len(hdr) != 20 or hdr[:4] != b"TPUS":
            raise SpillCorruptionError(
                f"spill file {path}: bad magic/version")
        version, n, crc = struct.unpack("<IQI", hdr[4:])
        if version != 1:
            raise SpillCorruptionError(
                f"spill file {path}: bad magic/version")
        # a corrupted length field must not drive a huge allocation
        if n != os.path.getsize(path) - 20:
            raise SpillCorruptionError(
                f"spill file {path}: payload size mismatch")
        blob = f.read(n)
    if len(blob) != n:
        raise SpillCorruptionError(
            f"spill file {path}: payload size mismatch")
    if (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
        raise SpillCorruptionError(f"spill file {path}: checksum mismatch")
    return blob
