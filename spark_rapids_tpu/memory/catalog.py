"""BufferCatalog: id -> buffer across tiers with refcounted acquisition
(reference `RapidsBufferCatalog.scala`: acquireBuffer walks tiers; acquire
pins the buffer so it cannot spill mid-use).
"""
from __future__ import annotations

import itertools
import threading
from contextlib import contextmanager
from typing import Optional

from spark_rapids_tpu.memory.buffer import BufferId, SpillableBuffer


class BufferCatalog:
    def __init__(self):
        self._by_id: dict[BufferId, SpillableBuffer] = {}
        self._lock = threading.RLock()
        self._table_ids = itertools.count()

    def next_table_id(self) -> int:
        return next(self._table_ids)

    def register(self, buf: SpillableBuffer) -> None:
        with self._lock:
            # a buffer moving tiers re-registers under the same id; the
            # newest tier wins (reference updateTier semantics)
            self._by_id[buf.id] = buf

    def unregister(self, bid: BufferId) -> None:
        with self._lock:
            self._by_id.pop(bid, None)

    def acquire_buffer(self, bid: BufferId) -> SpillableBuffer:
        """Pin + return the buffer; caller must `close()` it.  Retries when
        the buffer migrates tiers between lookup and acquire (a spill in
        flight registers the next-tier copy before dropping this one, so a
        short wait always resolves)."""
        import time
        for attempt in range(1000):
            with self._lock:
                buf = self._by_id.get(bid)
            if buf is None:
                raise KeyError(f"unknown buffer {bid}")
            try:
                buf.add_reference()
            except ValueError:
                if attempt > 2:
                    time.sleep(0.001)  # spill mid-copy; wait for next tier
                continue
            if buf.store is not None:
                buf.store.mark_acquired(buf)
            return buf
        raise RuntimeError(f"could not acquire buffer {bid}")

    def release_buffer(self, buf: SpillableBuffer) -> None:
        buf.close()
        if buf.store is not None:
            buf.store.mark_released(buf)

    @contextmanager
    def acquired(self, bid: BufferId):
        buf = self.acquire_buffer(bid)
        try:
            yield buf
        finally:
            self.release_buffer(buf)

    def ids(self) -> list[BufferId]:
        with self._lock:
            return list(self._by_id)

    def is_registered(self, bid: BufferId) -> bool:
        with self._lock:
            return bid in self._by_id

    def remove(self, bid: BufferId) -> None:
        """Fully drop a buffer from whatever tier holds it."""
        with self._lock:
            buf = self._by_id.get(bid)
        if buf is not None and buf.store is not None:
            buf.store.remove(bid)
        else:
            self.unregister(bid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_id)
