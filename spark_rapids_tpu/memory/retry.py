"""OOM split-and-retry harness: the exec layer's route into the HBM
budget (reference parallel: `RmmRapidsRetryIterator.scala` withRetry /
withSplitAndRetry over `GpuOOM`/`SplitAndRetryOOM`, layered on
`DeviceMemoryEventHandler`'s synchronous-spill callback).

TPU twist: XLA/PJRT has no alloc-failure hook, so the arena is accounted
(`DeviceManager.reserve`), not intercepted.  Operators route each
materialization point through `with_split_retry` (splittable inputs) or
`with_retry` (single-batch contracts: window frames, join build sides):

  1. reserve the estimated output footprint before dispatching kernels;
  2. under pressure, spill the device store (`SpillCallback
     .on_alloc_pressure`) with the task's semaphore hold YIELDED so
     concurrent tasks keep the accelerator busy while this one blocks;
  3. if spilling cannot make room, raise `TpuSplitAndRetryOOM`: the
     harness halves the input `ColumnarBatch` and retries each half,
     recursing down to `spark.rapids.memory.retry.minSplitRows`;
  4. past the floor, degrade per `spark.rapids.memory.retry.fallback`:
     `bestEffort` runs the batch unreserved (the accounted arena is
     advisory — XLA's allocator has the final word, and a true OOM
     surfaces as its own error), `error` raises `TpuOutOfCoreError`
     with an actionable message.  Never a silent wrong answer.

Deterministic OOM fault injection (`spark.rapids.memory.faultInjection
.oomRate/.seed/.maxInjections`, mirroring the transport injector in
shuffle/ici_transport.py) forces synthetic reservation failures so the
whole retry/split/fallback lattice is exercised on CPU-mesh CI without a
real 16 GiB HBM.
"""
from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterator, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils import profile as P
from spark_rapids_tpu.utils import residency as RES

log = logging.getLogger(__name__)


class TpuRetryOOM(MemoryError):
    """Reservation failed but spilling made (or may make) room: retry
    the SAME input (reference `GpuRetryOOM`)."""


class TpuSplitAndRetryOOM(TpuRetryOOM):
    """Reservation failed and spilling cannot make room: the input must
    shrink before retrying (reference `GpuSplitAndRetryOOM`)."""


class TpuOutOfCoreError(MemoryError):
    """A batch already at the minimum split size still does not fit the
    accounted budget and the fallback is conf'd off."""


# ---------------------------------------------------------------------------
class OomInjector:
    """Deterministic reservation-failure injection (the memory-layer
    sibling of shuffle's transport FaultInjector).  Each fire picks the
    failure class with a second draw — half retry-class (spill should
    make room), half split-class (input must shrink) — so both harness
    lanes see traffic at any rate.  `max_injections` hard-bounds total
    fires, guaranteeing forward progress in soak loops even at rate
    1.0."""

    def __init__(self, rate: float, seed: int, max_injections: int):
        import random
        self.rate = float(rate)
        self.seed = int(seed)
        self.max_injections = int(max_injections)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected = 0

    def fire(self) -> Optional[str]:
        """None (no injection) | 'retry' | 'split'."""
        with self._lock:
            if 0 < self.max_injections <= self.injected:
                return None
            if self._rng.random() >= self.rate:
                return None
            self.injected += 1
            return "split" if self._rng.random() < 0.5 else "retry"


#: injectors keyed by (rate, seed, max): concurrent queries with
#: DIFFERENT injection confs (a soak's victim query vs its clean
#: peers) each drive their own deterministic stream instead of
#: churning one global injector's state — and a query whose conf
#: carries rate 0 never touches an injector at all, so targeted
#: injection is per-query by construction
_injectors: dict[tuple, OomInjector] = {}
_inj_lock = threading.Lock()


def _get_injector(conf) -> Optional[OomInjector]:
    rate = float(conf[C.OOM_INJECT_RATE])
    if rate <= 0:
        return None
    key = (rate, int(conf[C.OOM_INJECT_SEED]),
           int(conf[C.OOM_INJECT_MAX]))
    with _inj_lock:
        inj = _injectors.get(key)
        if inj is None:
            inj = _injectors[key] = OomInjector(*key)
        return inj


def reset_oom_injection() -> None:
    """Drop the process-global injectors so the next run re-seeds
    (tests call this between runs for determinism)."""
    with _inj_lock:
        _injectors.clear()


def injected_oom_count() -> int:
    with _inj_lock:
        return sum(i.injected for i in _injectors.values())


# ---------------------------------------------------------------------------
def estimate_batch_bytes(batch) -> int:
    """Default output-footprint estimate for a materialization over
    `batch`: the input plus one same-shaped output working copy.
    Advisory, like the rest of the accounted arena — callers with a
    better bound (join expansions, build concats) pass their own."""
    return 2 * batch.device_size_bytes()


def _device_manager():
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    return DeviceManager.get()


def _madd(metrics, name: str, value) -> None:
    if metrics is not None and value:
        metrics.add(name, value)


@contextmanager
def _sem_yielded():
    """Release the current task's semaphore hold while the body (a
    synchronous spill / memory wait) runs, so concurrent tasks make
    progress; no-op outside a task context."""
    from spark_rapids_tpu.memory.semaphore import TaskContext, TpuSemaphore
    ctx = TaskContext.get()
    if ctx is None:
        yield
        return
    with TpuSemaphore.get().yielded(ctx):
        yield


def _blocked_spill(dm, nbytes: int, metrics) -> None:
    """Injected-failure spill: drive the REAL SpillCallback path (so
    injection exercises the same code a true pressure event does), with
    the semaphore yielded and the wall time charged to retryBlockTime."""
    t0 = time.perf_counter_ns()
    cb = dm.spill_callback
    if cb is not None:
        cb.take_thread_freed()  # discard any stale thread residue
    with _sem_yielded(), P.span("retry-block:spill", cat=P.CAT_RETRY):
        if cb is not None:
            cb.on_alloc_pressure(nbytes, dm.budget, dm.reserved_bytes)
    if cb is not None:
        # thread-local attribution: only spills THIS thread's pressure
        # call triggered charge this exec (a concurrent query spilling
        # at the same time no longer cross-charges — the before/after
        # bytes_spilled delta did)
        _madd(metrics, M.SPILL_BYTES, cb.take_thread_freed())
    _madd(metrics, M.RETRY_BLOCK_TIME, time.perf_counter_ns() - t0)


def _blocked_reserve(dm, nbytes: int, metrics) -> bool:
    """Pressure path: `DeviceManager.reserve` spills synchronously; run
    it with the semaphore yielded.  True = room was made (reservation
    held); False = even spilling everything could not fit (reservation
    rolled back)."""
    t0 = time.perf_counter_ns()
    cb = dm.spill_callback
    if cb is not None:
        cb.take_thread_freed()
    with _sem_yielded(), P.span("retry-block:reserve", cat=P.CAT_RETRY):
        ok = dm.reserve(nbytes)
    if cb is not None:
        _madd(metrics, M.SPILL_BYTES, cb.take_thread_freed())
    _madd(metrics, M.RETRY_BLOCK_TIME, time.perf_counter_ns() - t0)
    if not ok:
        dm.release_reservation(nbytes)
    return ok


def _acquire(nbytes: int, dm, inj, metrics, escalate: bool) -> None:
    """One reservation attempt.  Raises TpuRetryOOM (spill made room —
    try again) or TpuSplitAndRetryOOM (shrink the input).  On return the
    caller owns an `nbytes` reservation."""
    kind = inj.fire() if inj is not None else None
    if kind is not None:
        _blocked_spill(dm, nbytes, metrics)
        if kind == "split" or escalate:
            raise TpuSplitAndRetryOOM(
                f"injected reservation failure ({nbytes} bytes)")
        raise TpuRetryOOM(
            f"injected reservation failure ({nbytes} bytes)")
    if dm.try_reserve(nbytes):
        return
    if _blocked_reserve(dm, nbytes, metrics):
        # pressure resolved by spilling: count it as a retry event and
        # proceed with the reservation held
        _madd(metrics, M.NUM_RETRIES, 1)
        return
    raise TpuSplitAndRetryOOM(
        f"cannot reserve {nbytes} bytes within budget {dm.budget} "
        f"(store={dm.store_bytes}, reserved={dm.reserved_bytes}) even "
        "after spilling everything spillable")


#: a single attempt unit escalates injected retry-class failures to
#: split-class after this many consecutive retries, bounding the
#: retry-in-place loop the same way the reference bounds RetryOOM
_MAX_RETRIES_PER_ATTEMPT = 2


def _run_reserved(thunk: Callable[[], object], nbytes: int, metrics,
                  label: str):
    """Reserve -> run -> release, looping on retry-class failures.
    Split-class failures propagate to the caller (who splits or falls
    back)."""
    dm = _device_manager()
    inj = _get_injector(C.get_active_conf())
    retries = 0
    while True:
        try:
            _acquire(nbytes, dm, inj, metrics,
                     escalate=retries >= _MAX_RETRIES_PER_ATTEMPT)
        except TpuSplitAndRetryOOM:
            raise
        except TpuRetryOOM:
            _madd(metrics, M.NUM_RETRIES, 1)
            P.event(P.EV_OOM_RETRY, label=label, bytes=nbytes,
                    retries=retries + 1)
            retries += 1
            continue
        # residency provenance for the held reservation: the exec's
        # label names the site, so the ledger's peak composition says
        # WHICH operator's working set drove the high-water mark
        res_token = None
        if RES.enabled():
            res_token = RES.track(
                nbytes, site=f"reserve:{label.split('[', 1)[0]}",
                tier=RES.TIER_DEVICE, kind=RES.KIND_RESERVATION)
        try:
            return thunk()
        finally:
            dm.release_reservation(nbytes)
            RES.retire(res_token)


def _floor_fallback(thunk: Callable[[], object], metrics, label: str,
                    rows) -> object:
    """Past the split floor (or for unsplittable inputs): degrade per
    conf — run unreserved, or fail with an actionable error."""
    conf = C.get_active_conf()
    mode = str(conf[C.RETRY_FALLBACK]).lower()
    if mode == "error":
        raise TpuOutOfCoreError(
            f"{label}: cannot reserve HBM for a batch (rows={rows}) even "
            f"at the minimum split size ({C.RETRY_MIN_SPLIT_ROWS.key}="
            f"{conf[C.RETRY_MIN_SPLIT_ROWS]}): the operator's working set "
            "exceeds the accounted HBM budget after spilling everything "
            "spillable.  Raise spark.rapids.memory.gpu.allocFraction, "
            "lower spark.rapids.tpu.batchMaxRows, or set "
            f"{C.RETRY_FALLBACK.key}=bestEffort to run the batch "
            "unreserved (XLA's allocator then has the final word).")
    _madd(metrics, M.NUM_OOM_FALLBACKS, 1)
    P.event(P.EV_OOM_FALLBACK, label=label, rows=str(rows))
    log.warning(
        "%s: OOM retry floor reached (%s rows); running the batch "
        "unreserved (best effort) — a true device OOM will surface as "
        "an XLA allocation error", label, rows)
    return thunk()


# ---------------------------------------------------------------------------
def with_retry(body: Callable[[], object], *, out_bytes: int,
               metrics=None, label: str = "op") -> object:
    """Reserve `out_bytes`, then run `body` (reference withRetryNoSplit:
    single-batch contracts that cannot subdivide their input — window
    frames, join build-side concats, final aggregate evaluation).
    Split-class failures go straight to the floor fallback."""
    try:
        return _run_reserved(body, int(out_bytes), metrics, label)
    except TpuSplitAndRetryOOM:
        return _floor_fallback(body, metrics, label, rows="unsplittable")


def with_split_retry(batch, body: Callable[[object], object], *,
                     metrics=None, out_bytes_fn=None,
                     min_rows: Optional[int] = None,
                     label: str = "op") -> Iterator[object]:
    """Run `body` over `batch`, splitting in half and retrying the
    halves on split-class reservation failures (reference withSplitAndRetry
    over RmmRapidsRetryIterator).  Yields one body result per (possibly
    split) piece, in the input's row order.  Pieces at or below
    `min_rows` (default `spark.rapids.memory.retry.minSplitRows`) stop
    splitting and take the floor fallback."""
    conf = C.get_active_conf()
    if min_rows is None:
        min_rows = int(conf[C.RETRY_MIN_SPLIT_ROWS])
    est = out_bytes_fn or estimate_batch_bytes
    pending = [batch]
    while pending:
        b = pending.pop(0)
        try:
            yield _run_reserved(lambda: body(b), int(est(b)), metrics,
                                label)
        except TpuSplitAndRetryOOM:
            pieces = _split_in_half(b, min_rows)
            if pieces is None:
                yield _floor_fallback(lambda: body(b), metrics, label,
                                      rows=b.num_rows)
            else:
                _madd(metrics, M.NUM_SPLIT_RETRIES, 1)
                P.event(P.EV_OOM_SPLIT_RETRY, label=label,
                        rows=b.num_rows)
                pending[:0] = pieces


def _split_in_half(batch, min_rows: int):
    """Halve a batch by rows, or None at the floor.  Reads `num_rows`
    (a sync for lazy batches) — acceptable on the OOM path, which is
    already off the hot path."""
    rows = batch.num_rows
    if rows <= max(int(min_rows), 1):
        return None
    b = batch.dense()
    half = (rows + 1) // 2
    return [b.slice(0, half), b.slice(half, rows - half)]
