"""Device manager: TPU discovery/binding + HBM budget accounting + the
spill-on-pressure handler.

Reference parallels: `GpuDeviceManager.scala` (device acquisition, RMM pool
arithmetic alloc-fraction/max/reserve, pinned pool init, per-task device
setup) and `DeviceMemoryEventHandler.scala` (RMM alloc-failure callback ->
synchronous spill device->host->disk -> retry).

TPU twist (SURVEY.md §7 hard part (c)): XLA/PJRT has no RMM-style
alloc-failure hook, so the arena is *accounted*, not intercepted: stores
report resident bytes, operators call `reserve(nbytes)` before materializing
large outputs, and crossing the budget triggers a preemptive synchronous
spill of the device store.  Real HBM totals come from the PJRT device when
available; a conservative default otherwise.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from spark_rapids_tpu import config as C

log = logging.getLogger("spark_rapids_tpu.device_manager")

_DEFAULT_HBM = 16 * 1024**3  # v5p chip-class default when PJRT has no stats


class SpillCallback:
    """Alloc-pressure callback (DeviceMemoryEventHandler analog): spill the
    device store until `needed` bytes fit, retrying a bounded number of
    times; gives up when nothing is left to spill.

    Accounting: `bytes_spilled` is the process-wide total; the bytes a
    SINGLE pressure call freed accumulate thread-locally so the OOM
    retry harness charges each exec's `spillBytes` metric with the
    spills ITS thread triggered — the old `bytes_spilled` before/after
    delta cross-charged concurrent queries' spills to whichever exec
    happened to be reading the counter (the movement ledger's
    device->host spill totals exposed the mismatch)."""

    MAX_RETRIES = 3

    def __init__(self, device_store):
        self.device_store = device_store
        self.spill_count = 0
        self.bytes_spilled = 0
        self._lock = threading.Lock()
        self._tls = threading.local()

    def take_thread_freed(self) -> int:
        """Bytes freed by pressure calls on THIS thread since the last
        take (the per-exec spillBytes attribution source)."""
        freed = getattr(self._tls, "freed", 0)
        self._tls.freed = 0
        return freed

    def on_alloc_pressure(self, needed: int, budget: int,
                          reserved: int) -> bool:
        """Returns True if the allocation should be retried.  `reserved` is
        outstanding reservations by in-flight operators — the spill target
        must leave room for those commitments too, not just `needed`."""
        for _ in range(self.MAX_RETRIES):
            target = max(0, budget - needed - reserved)
            freed = self.device_store.synchronous_spill(target)
            with self._lock:
                self.spill_count += 1
                self.bytes_spilled += freed
            self._tls.freed = getattr(self._tls, "freed", 0) + freed
            if (self.device_store.current_size + reserved + needed
                    <= budget):
                return True
            if freed == 0:
                return False  # store empty / everything pinned
        return (self.device_store.current_size + reserved + needed
                <= budget)


class DeviceManager:
    """Process singleton (one accelerator per executor, like the
    reference's 1-GPU-per-executor model)."""

    _instance: Optional["DeviceManager"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[C.RapidsConf] = None,
                 hbm_total: Optional[int] = None):
        conf = conf or C.get_active_conf()
        self.conf = conf
        self.device = self._pick_device()
        total = hbm_total or self._query_hbm_total()
        frac = conf[C.HBM_ALLOC_FRACTION]
        reserve = conf[C.HBM_RESERVE]
        # pool arithmetic mirrors GpuDeviceManager.scala:159-196
        self.budget = max(0, int(total * frac) - reserve)
        # conf-capped arena (out-of-core lever): hbmBudgetBytes caps
        # the derived budget so try_reserve headroom — the signal the
        # external sort/join/agg degradation reads — reflects the cap
        cap = int(conf[C.HBM_BUDGET_BYTES])
        if cap > 0:
            self.budget = min(self.budget, cap)
        self.hbm_total = total
        self._store_bytes = 0
        self._reserved = 0
        #: admission ledger (exec/scheduler.py): query_id -> declared
        #: HBM budget.  Coarse, query-lifetime commitments that gate
        #: ADMISSION of further queries; operator-level reserve() keeps
        #: doing the fine-grained real-time accounting within them.
        self._admitted: dict[str, int] = {}
        self._acct = threading.Lock()
        #: store-byte accounting clamped at zero (double-free
        #: indicator): count + the sites already logged once
        self._underflows = 0
        self._underflow_sites: set[str] = set()
        self.spill_callback: Optional[SpillCallback] = None

    # -- singleton lifecycle -------------------------------------------------
    @classmethod
    def initialize(cls, conf: Optional[C.RapidsConf] = None,
                   hbm_total: Optional[int] = None) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(conf, hbm_total)
            return cls._instance

    @classmethod
    def get(cls) -> "DeviceManager":
        return cls.initialize()

    @classmethod
    def peek(cls) -> Optional["DeviceManager"]:
        """The live instance WITHOUT constructing one — telemetry
        scrapes must never boot the device."""
        return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._lock:
            cls._instance = None

    # -- device ---------------------------------------------------------------
    @staticmethod
    def _pick_device():
        import jax
        devs = jax.devices()
        for d in devs:
            if d.platform == "tpu":
                return d
        return devs[0]

    def _query_hbm_total(self) -> int:
        try:
            stats = self.device.memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
        return _DEFAULT_HBM

    def resident_bytes(self) -> int:
        try:
            stats = self.device.memory_stats()
            if stats and "bytes_in_use" in stats:
                return int(stats["bytes_in_use"])
        except Exception:
            pass
        with self._acct:
            return self._store_bytes + self._reserved

    # -- accounting ------------------------------------------------------------
    def track_store_bytes(self, delta: int, site: str = "?") -> None:
        """Adjust accounted store-resident bytes.  Negative drift —
        the total going below zero, i.e. more bytes removed than were
        ever added, a double-free — is clamped at zero and counted
        (`store_bytes_underflow` gauge) instead of silently corrupting
        the admission ledger's headroom math; the offending site is
        logged once."""
        log_site = None
        with self._acct:
            nxt = self._store_bytes + delta
            if nxt < 0:
                self._underflows += 1
                if site not in self._underflow_sites:
                    self._underflow_sites.add(site)
                    log_site = site
                nxt = 0
            self._store_bytes = nxt
        if log_site is not None:
            log.warning(
                "store-byte accounting underflow at site %r (delta %d "
                "past zero): clamped — a double-free is corrupting the "
                "device store's byte tracking", log_site, delta)

    def store_bytes_underflows(self) -> int:
        with self._acct:
            return self._underflows

    @property
    def store_bytes(self) -> int:
        with self._acct:
            return self._store_bytes

    @property
    def reserved_bytes(self) -> int:
        with self._acct:
            return self._reserved

    def install_spill_handler(self, device_store) -> SpillCallback:
        self.spill_callback = SpillCallback(device_store)
        return self.spill_callback

    def try_reserve(self, nbytes: int) -> bool:
        """Fast-path reservation: succeeds only when the projection fits
        the budget WITHOUT spilling (the retry harness brackets the
        spilling `reserve()` path with a semaphore yield, so the
        no-pressure case must not pay that release/reacquire)."""
        with self._acct:
            if self._store_bytes + self._reserved + nbytes <= self.budget:
                self._reserved += nbytes
                return True
        return False

    def reserve(self, nbytes: int) -> bool:
        """Pre-admission check before materializing `nbytes` on device.
        Spills preemptively under pressure.  Returns False only when even
        spilling everything cannot make room (caller may still proceed and
        let XLA OOM — accounting is advisory, like RMM retries)."""
        with self._acct:
            projected = self._store_bytes + self._reserved + nbytes
            if projected <= self.budget:
                self._reserved += nbytes
                return True
            reserved = self._reserved
        if self.spill_callback is not None:
            ok = self.spill_callback.on_alloc_pressure(
                nbytes, self.budget, reserved)
            with self._acct:
                self._reserved += nbytes
            return ok
        with self._acct:
            self._reserved += nbytes
        return False

    def release_reservation(self, nbytes: int) -> None:
        with self._acct:
            self._reserved = max(0, self._reserved - nbytes)

    # -- admission ledger (query-lifetime budget commitments) -----------------
    def try_admit(self, query_id: str, nbytes: int) -> bool:
        """Commit `nbytes` of the budget to `query_id` for its
        lifetime, iff the sum of admitted budgets still fits.  Unlike
        reserve(), admission never spills: a query that does not fit
        WAITS at the front door (or is shed) instead of evicting the
        working sets of queries already running."""
        with self._acct:
            if query_id in self._admitted:
                return True
            if sum(self._admitted.values()) + nbytes <= self.budget:
                self._admitted[query_id] = int(nbytes)
                return True
        return False

    def release_admission(self, query_id: str) -> None:
        with self._acct:
            self._admitted.pop(query_id, None)

    def admissions(self) -> dict[str, int]:
        """Copy of the admission ledger (query_id -> budget bytes)."""
        with self._acct:
            return dict(self._admitted)

    def admitted_bytes(self) -> int:
        with self._acct:
            return sum(self._admitted.values())

    def telemetry_gauges(self) -> dict:
        """One consistent HBM accounting snapshot for the telemetry
        registry: capacity, budget, the store-resident vs reserved
        split, the live total, the admission ledger, and — first-class
        instead of operator-derived — the live admission headroom
        (budget - store - reserved - sum of admitted budgets: what
        `try_admit` actually has left to give, negative when the
        running queries' real footprints outgrow their declarations)
        plus the store-byte underflow counter (utils/telemetry.py)."""
        with self._acct:
            admitted = sum(self._admitted.values())
            return {
                "hbm_total": self.hbm_total,
                "budget": self.budget,
                "store_bytes": self._store_bytes,
                "reserved_bytes": self._reserved,
                "in_use_bytes": self._store_bytes + self._reserved,
                "admitted_bytes": admitted,
                "admitted_queries": len(self._admitted),
                "admission_headroom_bytes": (
                    self.budget - self._store_bytes - self._reserved
                    - admitted),
                "store_bytes_underflow": self._underflows,
            }

    def snapshot(self) -> dict:
        """The gauge set plus the per-query admission detail — the
        one-call accounting view diagnostics (watchdog dumps, the
        profile_query --memory report) print."""
        gauges = self.telemetry_gauges()
        with self._acct:
            gauges["admissions"] = dict(self._admitted)
        return gauges
