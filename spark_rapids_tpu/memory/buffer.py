"""Spillable buffer abstraction (reference `RapidsBuffer.scala`,
`RapidsBufferId`, `MetaUtils.buildDegenerateTableMeta`).

A `SpillableBuffer` is one batch's worth of data pinned at a storage tier
with a refcount: while acquired it cannot spill; released (refcount 0) it
becomes a spill candidate ordered by `spill_priority`.  `TableMeta` is the
host-side descriptor that survives even when the data moves tiers (or, for
degenerate rows-but-no-columns batches, when there is no data at all).
"""
from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch


class StorageTier(enum.IntEnum):
    DEVICE = 0
    HOST = 1
    DISK = 2


@dataclasses.dataclass(frozen=True)
class TableMeta:
    """Descriptor of a stored batch (FlatBuffers TableMeta analog)."""
    schema: T.Schema
    num_rows: int
    size_bytes: int

    @property
    def is_degenerate(self) -> bool:
        return self.size_bytes == 0


@dataclasses.dataclass(frozen=True)
class BufferId:
    """Identifies a buffer across tiers.  Shuffle buffer ids also carry the
    (shuffle_id, map_id, partition) coordinates (ShuffleBufferId analog)."""
    table_id: int
    shuffle_id: int = -1
    map_id: int = -1
    partition: int = -1


class SpillableBuffer:
    """Base buffer: subclasses hold the payload for one tier."""

    tier: StorageTier

    def __init__(self, bid: BufferId, meta: TableMeta, spill_priority: float):
        self.id = bid
        self.meta = meta
        self.spill_priority = spill_priority
        self._refcount = 0
        self._lock = threading.Lock()
        self._closed = False
        self._spilling = False
        self.store = None  # owning BufferStore, set on add

    # -- refcounting (acquire pins against spilling) ------------------------
    def add_reference(self) -> None:
        with self._lock:
            if self._closed or self._spilling:
                raise ValueError(f"buffer {self.id} freed or spilling")
            self._refcount += 1

    def try_mark_spilling(self) -> bool:
        """Atomically claim the buffer for spilling; fails if a reader
        pinned it since the spill-queue check.  Once claimed, acquisition
        attempts fail until the catalog resolves the next-tier copy."""
        with self._lock:
            if self._refcount > 0 or self._closed or self._spilling:
                return False
            self._spilling = True
            return True

    def close(self) -> None:
        with self._lock:
            assert self._refcount > 0, "close without acquire"
            self._refcount -= 1

    @property
    def refcount(self) -> int:
        with self._lock:
            return self._refcount

    @property
    def is_spillable(self) -> bool:
        with self._lock:
            return (self._refcount == 0 and not self._closed
                    and not self._spilling)

    # -- payload access ------------------------------------------------------
    @property
    def size_bytes(self) -> int:
        return self.meta.size_bytes

    def get_columnar_batch(self) -> ColumnarBatch:
        """Materialize as a device batch (possibly reading up the tiers)."""
        raise NotImplementedError

    def get_host_bytes(self) -> bytes:
        """Serialized payload (spill/shuffle wire form)."""
        raise NotImplementedError

    def free(self) -> None:
        """Release storage.  Only the owning store calls this."""
        with self._lock:
            self._closed = True


class DegenerateBuffer(SpillableBuffer):
    """Rows-but-no-columns batch — metadata only, never spills
    (reference DegenerateRapidsBuffer)."""

    tier = StorageTier.DEVICE

    def __init__(self, bid: BufferId, meta: TableMeta):
        super().__init__(bid, meta, spill_priority=float("inf"))

    @property
    def is_spillable(self) -> bool:
        return False

    def get_columnar_batch(self) -> ColumnarBatch:
        from spark_rapids_tpu.columnar.batch import empty_batch
        b = empty_batch(self.meta.schema)
        return ColumnarBatch(b.schema, b.columns, self.meta.num_rows)

    def get_host_bytes(self) -> bytes:
        return b""


def meta_for_batch(batch: ColumnarBatch) -> TableMeta:
    return TableMeta(batch.schema, batch.num_rows,
                     batch.device_size_bytes())


def degenerate_meta(schema: T.Schema, num_rows: int) -> TableMeta:
    """rows-only meta (reference MetaUtils.buildDegenerateTableMeta:138)."""
    return TableMeta(schema, num_rows, 0)
