"""Device, memory & spill management (SURVEY.md §2.4).

Exports the tiered-store stack: BufferCatalog + device/host/disk stores with
native-backed (C++) allocator and spill-priority queue, the accounted HBM
DeviceManager with preemptive-spill callback, and the task TpuSemaphore.
"""
from spark_rapids_tpu.memory.buffer import (  # noqa: F401
    BufferId, DegenerateBuffer, SpillableBuffer, StorageTier, TableMeta,
    degenerate_meta, meta_for_batch)
from spark_rapids_tpu.memory.catalog import BufferCatalog  # noqa: F401
from spark_rapids_tpu.memory.device_manager import (  # noqa: F401
    DeviceManager, SpillCallback)
from spark_rapids_tpu.memory.env import ResourceEnv  # noqa: F401
from spark_rapids_tpu.memory.retry import (  # noqa: F401
    TpuOutOfCoreError, TpuRetryOOM, TpuSplitAndRetryOOM, with_retry,
    with_split_retry)
from spark_rapids_tpu.memory.semaphore import (  # noqa: F401
    TaskContext, TpuSemaphore)
from spark_rapids_tpu.memory.stores import (  # noqa: F401
    DeviceMemoryStore, DiskBlockManager, DiskStore, HostMemoryStore)
