"""ICI collective shuffle: the accelerated exchange (reference
`shuffle-plugin/` UCX transport, §2.8(b), re-designed for TPU).

UCX gives the reference RDMA pull: reducers fetch blocks from map outputs.
A TPU pod's strength is the opposite shape — synchronous SPMD collectives
over ICI.  So the accelerated shuffle here is a **push all-to-all**:

  per device (shard_map over the data axis):
    1. murmur3 partition ids for local rows (same bits as the CPU path)
    2. stable sort rows by target device; count per target
    3. scatter rows into a [n_dev, quota, ...] send buffer
    4. lax.all_to_all over the mesh axis  (XLA lowers to ICI all-to-all)
    5. compact received rows into the local output batch

Static shapes: each (src, dst) pair ships exactly `quota` padded rows.
quota = local capacity (worst case: every local row targets one device),
so no data-dependent shapes ever reach XLA.  Overflowing rows cannot occur
under that worst case.

The returned step function is jit-compiled once per schema/capacity and
reused every round — the compile-cache discipline, now pod-wide.
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax >= 0.6 promoted shard_map to the top-level namespace
    from jax import shard_map
except ImportError:  # jax 0.4.x ships it under experimental
    from jax.experimental.shard_map import shard_map

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.murmur3 import partition_ids as murmur3_pids


def watched_collective(thunk, label: str = "all-to-all",
                       nbytes: int = 0):
    """Run one collective dispatch (and its blocking host readback)
    under a collective-class watchdog heartbeat: an ICI all-to-all
    blocks EVERY mesh participant when one goes dark, so it gets the
    tighter `spark.rapids.sql.watchdog.collectiveTimeout` deadline and
    its own hang-injection site.  A real wedged collective cannot be
    interrupted host-side (the driver is inside the runtime), but the
    watchdog still emits the diagnostic dump naming this dispatch and
    cancels the query so every cooperative wait unwinds.

    `nbytes` (the payload the collective moves over the mesh) feeds
    the query's data-movement ledger — the collective edge of the
    movement report — timed over the dispatch + fence."""
    import time

    from spark_rapids_tpu.utils import movement as MV
    from spark_rapids_tpu.utils import watchdog as W
    with W.heartbeat(f"collective:{label}", kind="collective") as hb:
        W.check_cancelled()
        W.maybe_hang("collective")
        t0 = time.perf_counter_ns()
        out = thunk()
        if nbytes:
            MV.record(MV.EDGE_COLLECTIVE, nbytes, site=label,
                      dur_ns=time.perf_counter_ns() - t0)
        hb.beat()
        return out


def stacked_payload_bytes(arrs) -> int:
    """Ledger convention shared by BOTH collective lanes — the
    hand-rolled mesh exchange (shuffle/exchange.py) and the SPMD
    whole-stage lane (exec/spmd.py): the payload of a mesh collective
    is the total bytes of the stacked arrays ENTERING it (data +
    validity + lengths), regardless of the wire pattern XLA lowers to.
    Using one formula is what lets the two lanes' `collective` edge
    numbers reconcile in tests and bench rounds."""
    total = 0
    for field in arrs:
        for a in field:
            if a is not None:
                total += a.nbytes
    return total


def _local_split(cols, num_rows, key_idx, n_dev, cap):
    """Sort local rows by destination device; return per-dest counts and
    the [n_dev, cap, ...] send buffers."""
    row_mask = jnp.arange(cap) < num_rows
    keys = [cols[i] for i in key_idx]
    pids = murmur3_pids(keys, n_dev)
    pids = jnp.where(row_mask, pids, n_dev)
    order = jnp.argsort(pids, stable=True)
    counts = jnp.bincount(pids, length=n_dev + 1)[:n_dev]
    starts = jnp.concatenate([jnp.zeros(1, counts.dtype),
                              jnp.cumsum(counts)[:-1]])
    # position of each sorted row within its destination block
    sorted_pid = jnp.take(pids, order)
    within = jnp.arange(cap) - jnp.take(starts, jnp.clip(sorted_pid, 0,
                                                         n_dev - 1))
    ok = sorted_pid < n_dev

    def scatter(data):
        src = jnp.take(data, order, axis=0)
        buf = jnp.zeros((n_dev, cap) + data.shape[1:], data.dtype)
        # padded rows go OUT OF RANGE so mode="drop" discards them —
        # mapping them to (0,0) would clobber a real row
        d = jnp.where(ok, sorted_pid, n_dev)
        return buf.at[d, within].set(src, mode="drop")

    return scatter, counts


def exchange_local(local, num_rows, schema: T.Schema, key_idx,
                   n_dev: int, cap: int, axis: str, out_cap=None):
    """The per-device exchange body; call INSIDE shard_map so larger SPMD
    programs (scan->exchange->aggregate in one jit) can fuse around it.

    local: list of (data, validity, lengths|None) local column arrays.
    `out_cap` sizes the compacted received batch; pass n_dev*cap for the
    overflow-proof worst case (every device sends all its rows here) —
    the default (cap) is only safe when the caller pre-padded capacity.
    Returns (list of exchanged (data, validity, lengths|None), total_rows).
    """
    from spark_rapids_tpu.columnar.vector import ColumnVector
    if out_cap is None:
        out_cap = cap
    cols = []
    for f, (data, validity, lengths) in zip(schema.fields, local):
        cols.append(ColumnVector(f.dtype, data, validity, lengths))
    scatter, counts = _local_split(cols, num_rows, key_idx, n_dev, cap)

    recv_counts = jax.lax.all_to_all(
        counts.reshape(n_dev, 1), axis, 0, 0, tiled=False)
    recv_counts = recv_counts.reshape(n_dev)
    starts = jnp.concatenate([jnp.zeros(1, recv_counts.dtype),
                              jnp.cumsum(recv_counts)[:-1]])
    total = recv_counts.sum()
    k = jnp.arange(out_cap)
    src_block = jnp.searchsorted(jnp.cumsum(recv_counts), k, side="right")
    src_block = jnp.clip(src_block, 0, n_dev - 1)
    src_off = k - jnp.take(starts, src_block)
    valid_out = k < total

    out = []
    for data, validity, lengths in local:
        send = scatter(data)
        recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
        gathered = recv[jnp.where(valid_out, src_block, 0),
                        jnp.where(valid_out, src_off, 0)]
        gathered = jnp.where(
            valid_out.reshape((-1,) + (1,) * (data.ndim - 1)),
            gathered, 0)
        vsend = scatter(validity)
        vrecv = jax.lax.all_to_all(vsend, axis, 0, 0, tiled=False)
        vg = vrecv[jnp.where(valid_out, src_block, 0),
                   jnp.where(valid_out, src_off, 0)] & valid_out
        if lengths is not None:
            lsend = scatter(lengths)
            lrecv = jax.lax.all_to_all(lsend, axis, 0, 0, tiled=False)
            lg = lrecv[jnp.where(valid_out, src_block, 0),
                       jnp.where(valid_out, src_off, 0)]
            lg = jnp.where(valid_out, lg, 0)
        else:
            lg = None
        out.append((gathered, vg, lg))
    return out, total


def build_all_to_all_exchange(mesh: Mesh, axis: str,
                              schema: T.Schema,
                              key_indices: Sequence[int],
                              capacity: int, out_capacity=None):
    """Returns a jitted SPMD function:
        (stacked_cols_pytree, num_rows[n_dev]) ->
        (exchanged_cols, new_num_rows[n_dev])
    where stacked arrays have leading dim n_dev sharded over `axis`.

    `out_capacity` (default: capacity) sizes the received batch; pass
    n_dev*capacity for the overflow-proof worst case without having to
    pre-pad the send side.

    Column pytree layout per field: data [n_dev, cap, ...],
    validity [n_dev, cap], lengths or None.
    """
    n_dev = mesh.shape[axis]
    key_idx = tuple(key_indices)

    def per_device(arrs, num_rows):
        # arrs: list of (data, validity, lengths?) with leading dim 1
        # (shard_map gives the local block); squeeze to local views
        local = [tuple(x[0] if x is not None else None for x in a)
                 for a in arrs]
        num_rows = num_rows[0]
        out_local, total = exchange_local(
            local, num_rows, schema, key_idx, n_dev, capacity, axis,
            out_cap=out_capacity)
        out_arrs = [(d[None], v[None], None if l is None else l[None])
                    for d, v, l in out_local]
        return out_arrs, total.astype(jnp.int32)[None]

    specs_per_field = []
    for f in schema.fields:
        if f.dtype.is_string:
            specs_per_field.append((P(axis), P(axis), P(axis)))
        else:
            specs_per_field.append((P(axis), P(axis), None))

    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=([tuple(P(axis) if i < 2 or f.dtype.is_string else None
                         for i in range(3))
                   for f in schema.fields], P(axis)),
        out_specs=([tuple(P(axis) if i < 2 or f.dtype.is_string else None
                          for i in range(3))
                    for f in schema.fields], P(axis)))
    return jax.jit(smapped)


def build_count_exchange(mesh: Mesh, axis: str, schema: T.Schema,
                         key_indices: Sequence[int], capacity: int):
    """Phase-1 of the two-phase exchange (ADVICE r2): a counts-only
    all-to-all so the data phase can size its receive buffers from the
    ACTUAL per-device totals instead of the n_dev*cap worst case.
    Returns a jitted fn: (arrs, num_rows[n_dev]) -> recv_total[n_dev]."""
    n_dev = mesh.shape[axis]
    key_idx = tuple(key_indices)

    def per_device(arrs, num_rows):
        local = [tuple(x[0] if x is not None else None for x in a)
                 for a in arrs]
        from spark_rapids_tpu.columnar.vector import ColumnVector
        cols = [ColumnVector(f.dtype, d, v, l)
                for f, (d, v, l) in zip(schema.fields, local)]
        _, counts = _local_split(cols, num_rows[0], key_idx, n_dev,
                                 capacity)
        recv = jax.lax.all_to_all(counts.reshape(n_dev, 1), axis, 0, 0,
                                  tiled=False).reshape(n_dev)
        return recv.sum().astype(jnp.int32)[None]

    smapped = shard_map(
        per_device, mesh=mesh,
        in_specs=([tuple(P(axis) if i < 2 or f.dtype.is_string else None
                         for i in range(3))
                   for f in schema.fields], P(axis)),
        out_specs=P(axis))
    return jax.jit(smapped)


def stack_batches(batches, capacity: int):
    """Host helper: stack per-device ColumnarBatches into the pytree
    layout build_all_to_all_exchange expects."""
    import numpy as np
    from spark_rapids_tpu.columnar.vector import _pad_chars
    schema = batches[0].schema
    arrs = []
    for ci, f in enumerate(schema.fields):
        vecs = [b.columns[ci] for b in batches]
        if f.dtype.is_string:
            cc = max(v.char_cap for v in vecs)
            vecs = [_pad_chars(v, cc) for v in vecs]
        vecs = [v for v in vecs]
        data = jnp.stack([v.data for v in vecs])
        validity = jnp.stack([v.validity for v in vecs])
        lengths = (jnp.stack([v.lengths for v in vecs])
                   if vecs[0].lengths is not None else None)
        arrs.append((data, validity, lengths))
    num_rows = jnp.asarray([b.num_rows for b in batches], jnp.int32)
    return arrs, num_rows


def unstack_batches(arrs, num_rows, schema: T.Schema):
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.vector import ColumnVector
    n_dev = int(num_rows.shape[0])
    out = []
    for d in range(n_dev):
        cols = []
        for f, (data, validity, lengths) in zip(schema.fields, arrs):
            cols.append(ColumnVector(
                f.dtype, data[d], validity[d],
                None if lengths is None else lengths[d]))
        out.append(ColumnarBatch(schema, cols, int(num_rows[d])))
    return out
