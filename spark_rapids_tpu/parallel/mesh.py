"""Device mesh management (the TPU analog of the reference's
`GpuDeviceManager.scala` device discovery/binding, re-thought for SPMD).

The reference binds ONE GPU per executor process and time-shares it across
tasks.  On TPU the idiomatic scaling unit is a `jax.sharding.Mesh` over
all chips: a single SPMD program owns every device, and "executors" become
mesh axis slices.  We expose one canonical data axis for partition
parallelism; multi-host meshes come from jax.distributed initialization
outside (DCN x ICI topology), which `make_mesh` honors by using the global
device list.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def data_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Leading-axis sharding: element i of the stacked batch lives on
    device i of the data axis."""
    return NamedSharding(mesh, P(axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
