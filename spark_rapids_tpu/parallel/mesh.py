"""Device mesh management (the TPU analog of the reference's
`GpuDeviceManager.scala` device discovery/binding, re-thought for SPMD).

The reference binds ONE GPU per executor process and time-shares it across
tasks.  On TPU the idiomatic scaling unit is a `jax.sharding.Mesh` over
all chips: a single SPMD program owns every device, and "executors" become
mesh axis slices.  We expose one canonical data axis for partition
parallelism; multi-host meshes come from jax.distributed initialization
outside (DCN x ICI topology), which `make_mesh` honors by using the global
device list.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"make_mesh(num_devices={num_devices}) exceeds the "
                f"{len(devs)} visible device(s) on platform "
                f"'{devs[0].platform if devs else '?'}' — a silently "
                "truncated mesh would shard programs over fewer chips "
                "than the caller planned for.  Request at most "
                f"{len(devs)} devices, or (tests) raise the virtual "
                "device count via "
                "--xla_force_host_platform_device_count.")
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


# Shardings are memoized per (mesh, axis): hot dispatch paths (every
# SPMD gang dispatch, every mesh-exchange round) ask for the same
# NamedSharding over and over, and constructing one is not free.  The
# bound keeps dead meshes from being pinned forever; jax Meshes hash by
# device set + axis names, so a rebuilt-but-identical mesh still hits.
@functools.lru_cache(maxsize=128)
def data_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Leading-axis sharding: element i of the stacked batch lives on
    device i of the data axis."""
    return NamedSharding(mesh, P(axis_name))


@functools.lru_cache(maxsize=128)
def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# --- active-mesh registry -------------------------------------------------
# The session-level switch that turns on the accelerated (ICI collective)
# shuffle lane: when a mesh is active, ShuffleExchangeExec routes hash
# exchanges through the mesh all-to-all instead of the local/manager lane —
# the analog of the reference enabling its UCX transport inside the shuffle
# manager (RapidsShuffleInternalManager.scala:199).

_ACTIVE: Optional[tuple[Mesh, str]] = None


def set_active_mesh(mesh: Optional[Mesh],
                    axis_name: str = DATA_AXIS) -> None:
    global _ACTIVE
    _ACTIVE = None if mesh is None else (mesh, axis_name)


def get_active_mesh() -> Optional[tuple[Mesh, str]]:
    return _ACTIVE


@contextmanager
def active_mesh(mesh: Mesh, axis_name: str = DATA_AXIS):
    global _ACTIVE
    prev = _ACTIVE
    set_active_mesh(mesh, axis_name)
    try:
        yield mesh
    finally:
        _ACTIVE = prev
