"""Bitwise expressions (reference `bitwise.scala`): and/or/xor/not/shifts.

Shift semantics match Java/Spark: the shift distance is masked to the bit
width of the value (x << 33 on int32 == x << 1)."""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, UnaryExpression, promote)


@dataclasses.dataclass(eq=False)
class _BitwiseBin(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.common_type(self.left.data_type(schema),
                             self.right.data_type(schema))

    def do_columnar(self, l, r, ctx):
        dt = T.common_type(l.dtype, r.dtype)
        l, r = promote(l, dt), promote(r, dt)
        return ColumnVector(dt, self.op(l.data, r.data),
                            l.validity & r.validity)


class BitwiseAnd(_BitwiseBin):
    def op(self, a, b): return a & b


class BitwiseOr(_BitwiseBin):
    def op(self, a, b): return a | b


class BitwiseXor(_BitwiseBin):
    def op(self, a, b): return a ^ b


@dataclasses.dataclass(eq=False)
class BitwiseNot(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        return ColumnVector(c.dtype, ~c.data, c.validity)


def _mask_shift(data, shift):
    bits = data.dtype.itemsize * 8
    return (shift & (bits - 1)).astype(data.dtype)


@dataclasses.dataclass(eq=False)
class ShiftLeft(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return self.left.data_type(schema)

    def do_columnar(self, l, r, ctx):
        s = _mask_shift(l.data, r.data)
        return ColumnVector(l.dtype, lax.shift_left(l.data, s),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class ShiftRight(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return self.left.data_type(schema)

    def do_columnar(self, l, r, ctx):
        s = _mask_shift(l.data, r.data)
        return ColumnVector(l.dtype, lax.shift_right_arithmetic(l.data, s),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class ShiftRightUnsigned(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return self.left.data_type(schema)

    def do_columnar(self, l, r, ctx):
        s = _mask_shift(l.data, r.data)
        return ColumnVector(l.dtype, lax.shift_right_logical(l.data, s),
                            l.validity & r.validity)
