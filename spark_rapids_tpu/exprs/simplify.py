"""Peephole expression simplification.

The udf-compiler lowers `s.find(sub) >= 0` to
`Subtract(StringLocate(sub, s, 1), 1) >= 0` (compiler.py "find"), which
evaluates the POSITION machinery — UTF-8 char-start detection, a
[rows, char_cap] cumsum, argmax — only to test presence.  `Contains`
answers the same question with the match matrix alone; at q27's
2M-review scale the difference is most of the UDF's runtime.  Spark's
own optimizer normalizes the equivalent Catalyst shapes; the reference
compiles `Contains` directly when the source uses it
(udf-compiler/.../CatalystExpressionBuilder.scala analog).

Rules (F = 0-based find result with -1 for absent, L = 1-based locate
with 0 for absent; both share null semantics with Contains — null input
propagates through the comparison and through Contains identically):

  F >= 0, F > -1, F != -1   ->  Contains(s, sub)
  F < 0, F <= -1, F == -1   ->  Not(Contains(s, sub))
  F == 0                    ->  StartsWith(s, sub)
  L >= 1, L > 0             ->  Contains(s, sub)
  L < 1, L <= 0, L == 0     ->  Not(Contains(s, sub))
  L == 1                    ->  StartsWith(s, sub)
"""
from __future__ import annotations

from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import string_fns as S
from spark_rapids_tpu.exprs.base import Expression, Literal


def _int_literal(e) -> int | None:
    if isinstance(e, Literal) and isinstance(e.value, int) \
            and not isinstance(e.value, bool):
        return e.value
    return None


def _as_find(e):
    """Match F (0-based find, -1 absent) or L (1-based locate, 0
    absent) over a literal pattern with start=1; return
    (string, pattern, absent_value)."""
    if isinstance(e, A.Subtract) and _int_literal(e.right) == 1 \
            and isinstance(e.left, S.StringLocate):
        loc = e.left
        absent = -1
    elif isinstance(e, S.StringLocate):
        loc = e
        absent = 0
    else:
        return None
    if not isinstance(loc.substr, Literal) or loc.substr.value is None:
        return None
    if loc.start is not None and _int_literal(loc.start) != 1:
        return None
    return loc.child, loc.substr, absent


_FLIP = {P.GreaterThan: P.LessThan, P.GreaterThanOrEqual: P.LessThanOrEqual,
         P.LessThan: P.GreaterThan, P.LessThanOrEqual: P.GreaterThanOrEqual,
         P.EqualTo: P.EqualTo}


def _simplify_one(e: Expression) -> Expression:
    cls = type(e)
    if cls is P.Not and isinstance(e.child, P.Not):
        # `find(x) != -1` compiles to Not(EqualTo) and the inner rewrite
        # yields Not(Contains); collapse the double negation
        return e.child.child
    if cls not in _FLIP:
        return e
    lhs, rhs = e.left, e.right
    k = _int_literal(rhs)
    if k is None:
        # literal-on-the-left form: flip into find CMP k
        k = _int_literal(lhs)
        if k is None:
            return e
        lhs, cls = rhs, _FLIP[cls]
    m = _as_find(lhs)
    if m is None:
        return e
    s, sub, absent = m
    contains = S.Contains(s, sub)
    # positions are >= absent+1 when present, == absent when missing
    if cls in (P.GreaterThan, P.GreaterThanOrEqual):
        thr = k if cls is P.GreaterThanOrEqual else k + 1  # pos >= thr
        if thr == absent + 1:
            return contains
    elif cls in (P.LessThan, P.LessThanOrEqual):
        thr = k if cls is P.LessThanOrEqual else k - 1     # pos <= thr
        if thr == absent:
            return P.Not(contains)
    elif cls is P.EqualTo:
        if k == absent:
            return P.Not(contains)
        if k == absent + 1:
            return S.StartsWith(s, sub)
    return e


def simplify(e: Expression) -> Expression:
    """Bottom-up peephole pass; identity-preserving on no-ops
    (map_children returns self when nothing changes)."""
    return _simplify_one(e.map_children(simplify))
