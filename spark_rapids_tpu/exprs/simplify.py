"""Peephole expression simplification + cross-operator DAG cleanup.

Two layers live here:

* **Peephole rules** (`simplify`): bottom-up rewrites of one tree —
  the find/locate -> Contains family below, plus the fusion-era rules:
  double-cast collapse (`Cast(Cast(x, t), t)` and identity casts of
  bound references), boolean-literal folds (`And(x, false)` is false
  under Kleene logic even when x is null), literal integer comparison
  folding, and double-negation.  Whole-stage fusion (plan/fusion.py)
  runs these across the COMPOSED expression DAG of a fused stage, so
  a constant or a redundant cast introduced at one operator and
  consumed at another folds away before the kernel compiles.
* **Common-subexpression dedup** (`dedup_common_subexprs`): across a
  LIST of bound trees (a fused stage's predicates + outputs), every
  non-trivial subtree appearing more than once is wrapped in a
  `SharedExpr` slot; inside a kernel trace the slot evaluates once
  and every other occurrence reads the traced value from
  `EvalContext.shared`.  XLA would CSE the HLO anyway — the dedup
  buys trace time and keeps the composed DAG's size proportional to
  its distinct work.

The udf-compiler lowers `s.find(sub) >= 0` to
`Subtract(StringLocate(sub, s, 1), 1) >= 0` (compiler.py "find"), which
evaluates the POSITION machinery — UTF-8 char-start detection, a
[rows, char_cap] cumsum, argmax — only to test presence.  `Contains`
answers the same question with the match matrix alone; at q27's
2M-review scale the difference is most of the UDF's runtime.  Spark's
own optimizer normalizes the equivalent Catalyst shapes; the reference
compiles `Contains` directly when the source uses it
(udf-compiler/.../CatalystExpressionBuilder.scala analog).

Rules (F = 0-based find result with -1 for absent, L = 1-based locate
with 0 for absent; both share null semantics with Contains — null input
propagates through the comparison and through Contains identically):

  F >= 0, F > -1, F != -1   ->  Contains(s, sub)
  F < 0, F <= -1, F == -1   ->  Not(Contains(s, sub))
  F == 0                    ->  StartsWith(s, sub)
  L >= 1, L > 0             ->  Contains(s, sub)
  L < 1, L <= 0, L == 0     ->  Not(Contains(s, sub))
  L == 1                    ->  StartsWith(s, sub)
"""
from __future__ import annotations

import dataclasses
import operator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import string_fns as S
from spark_rapids_tpu.exprs.base import (
    Alias, BoundReference, Expression, Literal, fingerprint)


def _int_literal(e) -> int | None:
    if isinstance(e, Literal) and isinstance(e.value, int) \
            and not isinstance(e.value, bool):
        return e.value
    return None


def _as_find(e):
    """Match F (0-based find, -1 absent) or L (1-based locate, 0
    absent) over a literal pattern with start=1; return
    (string, pattern, absent_value)."""
    if isinstance(e, A.Subtract) and _int_literal(e.right) == 1 \
            and isinstance(e.left, S.StringLocate):
        loc = e.left
        absent = -1
    elif isinstance(e, S.StringLocate):
        loc = e
        absent = 0
    else:
        return None
    if not isinstance(loc.substr, Literal) or loc.substr.value is None:
        return None
    if loc.start is not None and _int_literal(loc.start) != 1:
        return None
    return loc.child, loc.substr, absent


_FLIP = {P.GreaterThan: P.LessThan, P.GreaterThanOrEqual: P.LessThanOrEqual,
         P.LessThan: P.GreaterThan, P.LessThanOrEqual: P.GreaterThanOrEqual,
         P.EqualTo: P.EqualTo}


def _bool_literal(e):
    if isinstance(e, Literal) and e.dtype == T.BOOL \
            and isinstance(e.value, bool):
        return e.value
    return None


_CMP_OPS = {P.GreaterThan: operator.gt, P.GreaterThanOrEqual: operator.ge,
            P.LessThan: operator.lt, P.LessThanOrEqual: operator.le,
            P.EqualTo: operator.eq}


def _simplify_cast(e: Expression) -> Expression:
    """Double-cast / identity-cast collapse.  Conservative: ANSI casts
    carry overflow checks and are never collapsed."""
    from spark_rapids_tpu.exprs.cast import Cast
    if not isinstance(e, Cast) or getattr(e, "ansi", False):
        return e
    c = e.child
    if isinstance(c, Cast) and not getattr(c, "ansi", False) \
            and c.to == e.to:
        # cast(cast(x as t) as t): the outer cast is identity on t
        return Cast(c.child, e.to)
    if isinstance(c, BoundReference) and c.dtype == e.to:
        return c  # identity cast of a column
    return e


def _simplify_one(e: Expression) -> Expression:
    cls = type(e)
    if cls.__name__ == "Cast":
        return _simplify_cast(e)
    if cls is P.Not:
        if isinstance(e.child, P.Not):
            # `find(x) != -1` compiles to Not(EqualTo) and the inner
            # rewrite yields Not(Contains); collapse the double negation
            return e.child.child
        b = _bool_literal(e.child)
        if b is not None:
            return Literal(not b, T.BOOL)
    if cls in (P.And, P.Or):
        absorbing = cls is P.Or  # Or(x, true)=true; And(x, false)=false
        for lit_side, other in ((e.left, e.right), (e.right, e.left)):
            b = _bool_literal(lit_side)
            if b is None:
                continue
            if b == absorbing:
                # absorbing element holds under Kleene logic even when
                # the other side is null
                return lit_side
            return other  # identity element: And(x, true) / Or(x, false)
    if cls not in _FLIP:
        return e
    lk, rk = _int_literal(e.left), _int_literal(e.right)
    if lk is not None and rk is not None:
        # cross-operator constant folding: a literal comparison born
        # from composing two operators' expressions folds to a bool
        return Literal(bool(_CMP_OPS[cls](lk, rk)), T.BOOL)
    lhs, rhs = e.left, e.right
    k = _int_literal(rhs)
    if k is None:
        # literal-on-the-left form: flip into find CMP k
        k = _int_literal(lhs)
        if k is None:
            return e
        lhs, cls = rhs, _FLIP[cls]
    m = _as_find(lhs)
    if m is None:
        return e
    s, sub, absent = m
    contains = S.Contains(s, sub)
    # positions are >= absent+1 when present, == absent when missing
    if cls in (P.GreaterThan, P.GreaterThanOrEqual):
        thr = k if cls is P.GreaterThanOrEqual else k + 1  # pos >= thr
        if thr == absent + 1:
            return contains
    elif cls in (P.LessThan, P.LessThanOrEqual):
        thr = k if cls is P.LessThanOrEqual else k - 1     # pos <= thr
        if thr == absent:
            return P.Not(contains)
    elif cls is P.EqualTo:
        if k == absent:
            return P.Not(contains)
        if k == absent + 1:
            return S.StartsWith(s, sub)
    return e


def simplify(e: Expression) -> Expression:
    """Bottom-up peephole pass; identity-preserving on no-ops
    (map_children returns self when nothing changes)."""
    return _simplify_one(e.map_children(simplify))


# ---------------------------------------------------------------------------
# common-subexpression dedup (used on fused-stage composed DAGs)
@dataclasses.dataclass(eq=False)
class SharedExpr(Expression):
    """CSE slot: evaluates its child ONCE per kernel trace (memoized in
    `EvalContext.shared` by slot id); every other occurrence of the
    same slot reads the traced value back.  Slots are assigned
    deterministically in first-appearance order, so two structurally
    equal fused stages fingerprint equal and share compiled kernels."""
    child: Expression
    slot: int

    def data_type(self, schema):
        return self.child.data_type(schema)

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return SharedExpr(kids[0], self.slot)

    def eval(self, ctx):
        memo = getattr(ctx, "shared", None)
        if memo is None:
            return self.child.eval(ctx)
        v = memo.get(self.slot)
        if v is None:
            v = self.child.eval(ctx)
            memo[self.slot] = v
        return v

    def __repr__(self):
        return f"shared#{self.slot}({self.child!r})"


def _cse_trivial(e: Expression) -> bool:
    # leaves cost nothing to re-evaluate; sharing them is pure overhead
    return isinstance(e, (Literal, BoundReference)) or not e.children()


def dedup_common_subexprs(exprs: list) -> list:
    """CSE across a list of (bound) expression trees: every non-trivial
    subtree whose structural fingerprint appears more than once —
    within one tree or across trees — is wrapped in a `SharedExpr`
    slot.  The rewrite is top-down, so the HIGHEST duplicated subtree
    gets the slot and its interior is rewritten once beneath it."""
    counts: dict = {}

    def scan(e: Expression) -> None:
        if not _cse_trivial(e):
            fp = fingerprint(e)
            counts[fp] = counts.get(fp, 0) + 1
        for c in e.children():
            scan(c)

    for e in exprs:
        scan(e)
    slots: dict = {}

    def rewrite(e: Expression) -> Expression:
        if not _cse_trivial(e):
            fp = fingerprint(e)
            if counts.get(fp, 0) > 1:
                slot = slots.get(fp)
                if slot is None:
                    slot = slots[fp] = len(slots)
                return SharedExpr(e.map_children(rewrite), slot)
        return e.map_children(rewrite)

    return [rewrite(e) for e in exprs]


def is_identity_projection(bound_exprs, in_schema, out_schema) -> bool:
    """True when a bound projection is a no-op — output i is input
    column i (through any Alias chain) with the same name and dtype —
    so the fusion pass can collapse the node entirely."""
    if len(bound_exprs) != len(in_schema.fields) or \
            len(out_schema.fields) != len(in_schema.fields):
        return False
    for i, (e, fi, fo) in enumerate(zip(bound_exprs, in_schema.fields,
                                        out_schema.fields)):
        while isinstance(e, Alias):
            e = e.child
        if not (isinstance(e, BoundReference) and e.ordinal == i):
            return False
        if fi.name != fo.name or fi.dtype != fo.dtype:
            return False
    return True
