"""Expression protocol (reference `GpuExpressions.scala:69-93`).

`Expression.eval(ctx)` returns a `ColumnVector` whose arrays are JAX values —
evaluation happens *inside* a jitted kernel built by the exec layer, so the
whole expression tree fuses into one XLA computation (the TPU answer to
cuDF's kernel-per-op launches: XLA fuses elementwise chains into single
VPU loops over the batch).

Null semantics follow Spark: most ops propagate nulls (result validity =
AND of child validities); special cases (IsNull, Coalesce, And/Or Kleene
logic) override `eval` entirely.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import (
    ColumnVector, bucket_char_cap)


@dataclasses.dataclass(eq=False)
class EvalContext:
    """Per-kernel evaluation context: the input columns (traced), static
    capacity, and the traced valid-row mask.

    `pending_checks` collects (label, traced bool scalar) pairs raised
    by ANSI-mode expressions during trace (True = error); kernels return
    them alongside their outputs and the exec registers them as
    deferred checks (utils/checks.py) resolved at the collect boundary
    — the engine's analog of the reference's ANSI runtime exceptions
    (GpuCast.scala:188 ansiMode)."""
    columns: list[ColumnVector]
    capacity: int
    num_rows: Any  # traced int32 scalar
    row_mask: Any  # traced bool[capacity]
    pending_checks: list = dataclasses.field(default_factory=list)
    #: per-trace memo for CSE slots (exprs/simplify.py SharedExpr):
    #: a deduped subtree evaluates once per kernel trace, and every
    #: other occurrence reads the traced value back from here
    shared: dict = dataclasses.field(default_factory=dict)


class Expression:
    """Base of the columnar expression tree."""

    def data_type(self, input_schema: T.Schema) -> T.DataType:
        raise NotImplementedError

    def children(self) -> Sequence["Expression"]:
        return ()

    def eval(self, ctx: EvalContext) -> ColumnVector:
        raise NotImplementedError

    def bind(self, schema: T.Schema) -> "Expression":
        """Resolve column names to positions (reference
        `GpuBoundAttribute.scala:97` GpuBindReferences)."""
        return self.map_children(lambda c: c.bind(schema))

    def map_children(self, fn) -> "Expression":
        kids = self.children()
        if not kids:
            return self
        new = [fn(c) for c in kids]
        if all(n is o for n, o in zip(new, kids)):
            return self  # identity-preserving: rewrites can detect no-ops
        return self.with_children(new)

    def with_children(self, new_children) -> "Expression":
        raise NotImplementedError(type(self))

    def fingerprint(self) -> tuple:
        """Structural identity of the (bound) tree — the compile-cache
        scope: two expressions with equal fingerprints trace to the same
        XLA computation, so rebuilt plans (AQE re-plans, per-query plan
        trees over the same schema) reuse executables instead of
        recompiling."""
        return fingerprint(self)

    # sugar -----------------------------------------------------------------
    def __add__(self, o): return _binop("Add", self, _lit(o))
    def __sub__(self, o): return _binop("Subtract", self, _lit(o))
    def __mul__(self, o): return _binop("Multiply", self, _lit(o))
    def __truediv__(self, o): return _binop("Divide", self, _lit(o))
    def __mod__(self, o): return _binop("Remainder", self, _lit(o))
    def __gt__(self, o): return _binop("GreaterThan", self, _lit(o))
    def __ge__(self, o): return _binop("GreaterThanOrEqual", self, _lit(o))
    def __lt__(self, o): return _binop("LessThan", self, _lit(o))
    def __le__(self, o): return _binop("LessThanOrEqual", self, _lit(o))
    def eq(self, o): return _binop("EqualTo", self, _lit(o))
    def ne(self, o):
        from spark_rapids_tpu.exprs.predicates import Not
        return Not(_binop("EqualTo", self, _lit(o)))
    # __eq__/__ne__ build expressions too (all expr dataclasses use eq=False
    # so these aren't shadowed); `col("a") == 0` therefore works like Spark
    def __eq__(self, o): return _binop("EqualTo", self, _lit(o))
    def __ne__(self, o):
        from spark_rapids_tpu.exprs.predicates import Not
        return Not(_binop("EqualTo", self, _lit(o)))
    __hash__ = object.__hash__
    def __and__(self, o):
        from spark_rapids_tpu.exprs.predicates import And
        return And(self, _lit(o))
    def __or__(self, o):
        from spark_rapids_tpu.exprs.predicates import Or
        return Or(self, _lit(o))
    def alias(self, name: str) -> "Alias":
        return Alias(self, name)
    def cast(self, dt: T.DataType, ansi: bool = False):
        from spark_rapids_tpu.exprs.cast import Cast
        return Cast(self, dt, ansi)


def _lit(v):
    return v if isinstance(v, Expression) else Literal.of(v)


def _binop(name, l, r):
    from spark_rapids_tpu.exprs import arithmetic, predicates
    for mod in (arithmetic, predicates):
        if hasattr(mod, name):
            return getattr(mod, name)(l, r)
    raise KeyError(name)


# ---------------------------------------------------------------------------
@dataclasses.dataclass(eq=False)
class AttributeReference(Expression):
    """Unresolved column-by-name; becomes BoundReference at bind time."""
    name: str

    def data_type(self, schema: T.Schema) -> T.DataType:
        return schema.field(self.name).dtype

    def bind(self, schema: T.Schema) -> Expression:
        return BoundReference(schema.index(self.name),
                              schema.field(self.name).dtype)

    def eval(self, ctx):
        raise RuntimeError(f"unbound attribute {self.name}")

    def __repr__(self):
        return self.name


def col(name: str) -> AttributeReference:
    return AttributeReference(name)


@dataclasses.dataclass(eq=False)
class BoundReference(Expression):
    """Positional column reference (reference GpuBoundReference)."""
    ordinal: int
    dtype: T.DataType

    def data_type(self, schema) -> T.DataType:
        return self.dtype

    def bind(self, schema):
        return self

    def eval(self, ctx: EvalContext) -> ColumnVector:
        return ctx.columns[self.ordinal]

    def __repr__(self):
        return f"input[{self.ordinal}]"


@dataclasses.dataclass(eq=False)
class Literal(Expression):
    """Typed literal, broadcast to the batch capacity at eval (XLA fuses the
    broadcast away).  Reference `literals.scala` GpuLiteral."""
    value: Any
    dtype: T.DataType

    @staticmethod
    def of(v: Any, dtype: Optional[T.DataType] = None) -> "Literal":
        if dtype is None:
            if v is None:
                raise TypeError("null literal needs explicit dtype")
            if isinstance(v, bool):
                dtype = T.BOOL
            elif isinstance(v, int):
                dtype = T.INT32 if -2**31 <= v < 2**31 else T.INT64
            elif isinstance(v, float):
                dtype = T.FLOAT64
            elif isinstance(v, str):
                dtype = T.STRING
            else:
                raise TypeError(f"unsupported literal {v!r}")
        return Literal(v, dtype)

    def data_type(self, schema) -> T.DataType:
        return self.dtype

    def bind(self, schema):
        return self

    def eval(self, ctx: EvalContext) -> ColumnVector:
        cap = ctx.capacity
        if self.value is None:
            validity = jnp.zeros(cap, bool)
            if self.dtype.is_string:
                return ColumnVector(self.dtype,
                                    jnp.zeros((cap, 8), jnp.uint8), validity,
                                    jnp.zeros(cap, jnp.int32))
            return ColumnVector(self.dtype,
                                jnp.zeros(cap, self.dtype.storage_dtype),
                                validity)
        validity = ctx.row_mask
        if self.dtype.is_string:
            raw = np.frombuffer(str(self.value).encode("utf-8"), np.uint8)
            cc = bucket_char_cap(len(raw))
            host = np.zeros((1, cc), np.uint8)
            host[0, : len(raw)] = raw
            data = jnp.broadcast_to(jnp.asarray(host), (cap, cc))
            lengths = jnp.where(validity, np.int32(len(raw)), 0)
            return ColumnVector(self.dtype, data, validity,
                                lengths.astype(jnp.int32))
        data = jnp.full(cap, self.value, self.dtype.storage_dtype)
        return ColumnVector(self.dtype, data, validity)

    def __repr__(self):
        return f"lit({self.value!r})"


def lit(v: Any, dtype: Optional[T.DataType] = None) -> Literal:
    return Literal.of(v, dtype)


@dataclasses.dataclass(eq=False)
class Alias(Expression):
    child: Expression
    name: str

    def data_type(self, schema):
        return self.child.data_type(schema)

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return Alias(kids[0], self.name)

    def eval(self, ctx):
        return self.child.eval(ctx)

    def __repr__(self):
        return f"{self.child!r} AS {self.name}"


def output_name(e: Expression, idx: int) -> str:
    if isinstance(e, Alias):
        return e.name
    if isinstance(e, AttributeReference):
        return e.name
    return f"col{idx}"


# -- helper bases -----------------------------------------------------------
class UnaryExpression(Expression):
    """Null-propagating unary op (reference GpuUnaryExpression)."""
    child: Expression

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return type(self)(kids[0])

    def eval(self, ctx: EvalContext) -> ColumnVector:
        c = self.child.eval(ctx)
        return self.do_columnar(c, ctx)

    def do_columnar(self, c: ColumnVector, ctx: EvalContext) -> ColumnVector:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self.child!r})"


class BinaryExpression(Expression):
    """Null-propagating binary op (reference GpuBinaryExpression)."""
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def with_children(self, kids):
        return type(self)(kids[0], kids[1])

    def eval(self, ctx: EvalContext) -> ColumnVector:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        return self.do_columnar(l, r, ctx)

    def do_columnar(self, l, r, ctx) -> ColumnVector:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self.left!r}, {self.right!r})"


def numeric_result_type(schema, *exprs) -> T.DataType:
    dts = [e.data_type(schema) for e in exprs]
    out = dts[0]
    for dt in dts[1:]:
        out = T.common_type(out, dt)
    return out


def promote(v: ColumnVector, dt: T.DataType) -> ColumnVector:
    if v.dtype == dt:
        return v
    return ColumnVector(dt, v.data.astype(dt.storage_dtype), v.validity)


# ---------------------------------------------------------------------------
def fingerprint(obj) -> tuple:
    """Structural fingerprint of expression trees / dataclass specs, used
    to scope the global kernel compile cache (exec/base.py KernelCache):
    two plan nodes whose bound expressions fingerprint equal produce the
    same traced computation for a given batch signature."""
    import dataclasses as _dc
    if obj is None:
        return ("~",)
    if isinstance(obj, (list, tuple)):
        return ("seq",) + tuple(fingerprint(x) for x in obj)
    if isinstance(obj, Expression) or _dc.is_dataclass(obj):
        out = [type(obj).__name__]
        if _dc.is_dataclass(obj):
            for f in _dc.fields(obj):
                out.append(fingerprint(getattr(obj, f.name)))
        else:  # non-dataclass Expression: fall back to child recursion
            out.append(tuple(fingerprint(c) for c in obj.children()))
        return tuple(out)
    if isinstance(obj, T.DataType):
        return ("dt", str(obj))
    if isinstance(obj, T.Schema):
        return ("schema",) + tuple(
            (f.name, str(f.dtype)) for f in obj.fields)
    if isinstance(obj, (str, int, float, bool, bytes)):
        return ("v", type(obj).__name__, obj)
    import enum as _enum
    if isinstance(obj, _enum.Enum):
        return ("enum", type(obj).__name__, obj.name)
    if isinstance(obj, np.ndarray) or hasattr(obj, "tobytes"):
        # full content hash: repr() truncates arrays >1000 elements, which
        # would let different array literals share a compiled kernel
        import hashlib
        # tpulint: disable=host-sync -- expression literals are host
        # ndarrays; fingerprint() runs at kernel-cache keying, not in
        # the per-batch loop
        arr = np.asarray(obj)
        h = hashlib.sha1(arr.tobytes()).hexdigest()
        return ("arr", str(arr.dtype), arr.shape, h)
    # other scalar-ish values: repr is stable within a process, which is
    # the cache's lifetime
    return ("r", repr(obj))
