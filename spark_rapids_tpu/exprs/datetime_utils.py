"""Vectorized civil-calendar arithmetic (proleptic Gregorian), used by the
cast and datetime expression kernels.

Implements Howard Hinnant's days<->civil algorithms with pure int ops so the
whole thing lowers to fused XLA integer arithmetic (no host round-trips).
Reference counterpart: cuDF's datetime kernels used via
`datetimeExpressions.scala` / `GpuCast.scala`.
"""
from __future__ import annotations

import jax.numpy as jnp

MICROS_PER_SECOND = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SECOND


def days_to_ymd(days):
    """int32 days-since-epoch -> (year, month, day), vectorized."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                        # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153                     # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1             # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)        # [1, 12]
    year = y + (m <= 2)
    return year, m, d


def ymd_to_days(y, m, d):
    """(year, month, day) -> int32 days-since-epoch, vectorized."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400                           # [0, 399]
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1             # [0, 365]
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy  # [0, 146096]
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def micros_to_date_days(micros):
    """timestamp micros -> date days (floor division, handles pre-epoch)."""
    return (micros // MICROS_PER_DAY).astype(jnp.int32)


def micros_time_of_day(micros):
    """-> (hour, minute, second, microsecond), all non-negative."""
    tod = micros - (micros // MICROS_PER_DAY) * MICROS_PER_DAY
    sec = tod // MICROS_PER_SECOND
    us = tod - sec * MICROS_PER_SECOND
    h = sec // 3600
    mnt = (sec - h * 3600) // 60
    s = sec - h * 3600 - mnt * 60
    return h, mnt, s, us


def day_of_week(days):
    """ISO-ish: 1=Sunday ... 7=Saturday (Spark dayofweek)."""
    # 1970-01-01 was a Thursday (=5 in Spark's 1..7 Sunday-first scheme)
    d = days.astype(jnp.int64)
    return ((d + 4) % 7) + 1


def day_of_year(days):
    y, m, d = days_to_ymd(days)
    jan1 = ymd_to_days(y, jnp.ones_like(m), jnp.ones_like(d))
    return (days.astype(jnp.int64) - jan1 + 1).astype(jnp.int32)


def quarter(days):
    _, m, _ = days_to_ymd(days)
    return ((m - 1) // 3 + 1).astype(jnp.int32)


def last_day_of_month(days):
    y, m, _ = days_to_ymd(days)
    ny = jnp.where(m == 12, y + 1, y)
    nm = jnp.where(m == 12, 1, m + 1)
    first_next = ymd_to_days(ny, nm, jnp.ones_like(nm))
    return (first_next - 1).astype(jnp.int32)
