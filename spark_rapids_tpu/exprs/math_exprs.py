"""Math expressions (reference `mathExpressions.scala`).

All unary transcendentals produce float64 like Spark.  The reference gates
"improved" float ops behind `spark.rapids.sql.improvedFloatOps.enabled`
(GpuOverrides.scala:648-672); on TPU, XLA's libm lowering is already
correctly rounded enough that both paths share one implementation.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, UnaryExpression, promote)


@dataclasses.dataclass(eq=False)
class _UnaryMath(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.FLOAT64

    def do_columnar(self, c, ctx):
        x = c.data.astype(jnp.float64)
        return ColumnVector(T.FLOAT64, self.op(x), c.validity)


class Sqrt(_UnaryMath):
    def op(self, x): return jnp.sqrt(x)


class Cbrt(_UnaryMath):
    def op(self, x): return jnp.cbrt(x)


class Exp(_UnaryMath):
    def op(self, x): return jnp.exp(x)


class Expm1(_UnaryMath):
    def op(self, x): return jnp.expm1(x)


class Log(_UnaryMath):
    def op(self, x): return jnp.log(x)


class Log1p(_UnaryMath):
    def op(self, x): return jnp.log1p(x)


class Log2(_UnaryMath):
    def op(self, x): return jnp.log2(x)


class Log10(_UnaryMath):
    def op(self, x): return jnp.log10(x)


class Sin(_UnaryMath):
    def op(self, x): return jnp.sin(x)


class Cos(_UnaryMath):
    def op(self, x): return jnp.cos(x)


class Tan(_UnaryMath):
    def op(self, x): return jnp.tan(x)


class Asin(_UnaryMath):
    def op(self, x): return jnp.arcsin(x)


class Acos(_UnaryMath):
    def op(self, x): return jnp.arccos(x)


class Atan(_UnaryMath):
    def op(self, x): return jnp.arctan(x)


class Sinh(_UnaryMath):
    def op(self, x): return jnp.sinh(x)


class Cosh(_UnaryMath):
    def op(self, x): return jnp.cosh(x)


class Tanh(_UnaryMath):
    def op(self, x): return jnp.tanh(x)


class ToDegrees(_UnaryMath):
    def op(self, x): return jnp.degrees(x)


class ToRadians(_UnaryMath):
    def op(self, x): return jnp.radians(x)


class Rint(_UnaryMath):
    def op(self, x): return jnp.rint(x)


@dataclasses.dataclass(eq=False)
class Signum(_UnaryMath):
    child: Expression

    def op(self, x): return jnp.sign(x)


@dataclasses.dataclass(eq=False)
class Ceil(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.INT64

    def do_columnar(self, c, ctx):
        x = jnp.ceil(c.data.astype(jnp.float64))
        return ColumnVector(T.INT64, x.astype(jnp.int64), c.validity)


@dataclasses.dataclass(eq=False)
class Floor(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.INT64

    def do_columnar(self, c, ctx):
        x = jnp.floor(c.data.astype(jnp.float64))
        return ColumnVector(T.INT64, x.astype(jnp.int64), c.validity)


@dataclasses.dataclass(eq=False)
class Pow(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.FLOAT64

    def do_columnar(self, l, r, ctx):
        a = l.data.astype(jnp.float64)
        b = r.data.astype(jnp.float64)
        return ColumnVector(T.FLOAT64, jnp.power(a, b),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class Atan2(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.FLOAT64

    def do_columnar(self, l, r, ctx):
        a = l.data.astype(jnp.float64)
        b = r.data.astype(jnp.float64)
        return ColumnVector(T.FLOAT64, jnp.arctan2(a, b),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class Round(Expression):
    """HALF_UP rounding like Spark's round()."""
    child: Expression
    scale: int = 0

    def data_type(self, schema):
        return self.child.data_type(schema)

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return Round(kids[0], self.scale)

    def eval(self, ctx):
        c = self.child.eval(ctx)
        if c.dtype.is_integral and self.scale >= 0:
            return c
        if c.dtype.is_integral:
            # negative scale on integers: exact integer arithmetic — a
            # float64 round trip corrupts values beyond 2^53
            p = jnp.asarray(10 ** (-self.scale), c.data.dtype)
            half = p // 2
            v = c.data
            adj = jnp.where(v >= 0, v + half, v - half)
            from jax import lax
            out = lax.div(adj, p) * p
            return ColumnVector(c.dtype, out, c.validity)
        x = c.data.astype(jnp.float64)
        mul = 10.0 ** self.scale
        scaled = x * mul
        # HALF_UP: round half away from zero
        r = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5),
                      jnp.ceil(scaled - 0.5))
        out = r / mul
        return ColumnVector(c.dtype, out.astype(c.dtype.storage_dtype),
                            c.validity)


class Cot(_UnaryMath):
    """cot(x) = 1/tan(x) (reference mathExpressions.scala GpuCot)."""
    def op(self, x): return 1.0 / jnp.tan(x)


class Acosh(_UnaryMath):
    """acosh (reference improved-float family GpuAcosh)."""
    def op(self, x): return jnp.arccosh(x)


class Asinh(_UnaryMath):
    def op(self, x): return jnp.arcsinh(x)


class Atanh(_UnaryMath):
    def op(self, x): return jnp.arctanh(x)


@dataclasses.dataclass(eq=False)
class Logarithm(BinaryExpression):
    """log(base, x) (reference GpuLogarithm): ln(x)/ln(base)."""
    left: Expression   # base
    right: Expression  # value

    def data_type(self, schema):
        return T.FLOAT64

    def do_columnar(self, l, r, ctx):
        base = l.data.astype(jnp.float64)
        val = r.data.astype(jnp.float64)
        out = jnp.log(val) / jnp.log(base)
        return ColumnVector(T.FLOAT64, out, l.validity & r.validity)
