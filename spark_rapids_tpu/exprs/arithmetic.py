"""Arithmetic expressions (reference `org/.../rapids/arithmetic.scala`).

Spark parity notes:
  - `/` always yields double; x/0 -> null (non-ANSI).
  - `%` keeps the dividend's sign (Java semantics) -> lax.rem.
  - pmod yields a non-negative result.
  - Integer overflow wraps (Java two's-complement), which jnp int ops match.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, EvalContext, Expression, UnaryExpression,
    numeric_result_type, promote)


def _arith_result(schema, l, r):
    return numeric_result_type(schema, l, r)


@dataclasses.dataclass(eq=False)
class _BinaryArith(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return _arith_result(schema, self.left, self.right)

    def do_columnar(self, l: ColumnVector, r: ColumnVector, ctx):
        dt = T.common_type(l.dtype, r.dtype)
        l, r = promote(l, dt), promote(r, dt)
        validity = l.validity & r.validity
        data = self.op(l.data, r.data)
        return ColumnVector(dt, data, validity)


class Add(_BinaryArith):
    def op(self, a, b):
        return a + b


class Subtract(_BinaryArith):
    def op(self, a, b):
        return a - b


class Multiply(_BinaryArith):
    def op(self, a, b):
        return a * b


@dataclasses.dataclass(eq=False)
class Divide(BinaryExpression):
    """Double division; divide-by-zero -> null (Spark non-ANSI)."""
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.FLOAT64

    def do_columnar(self, l, r, ctx):
        a = l.data.astype(jnp.float64)
        b = r.data.astype(jnp.float64)
        zero = b == 0.0
        validity = l.validity & r.validity & ~zero
        data = a / jnp.where(zero, 1.0, b)
        return ColumnVector(T.FLOAT64, data, validity)


@dataclasses.dataclass(eq=False)
class IntegralDivide(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.INT64

    def do_columnar(self, l, r, ctx):
        a = l.data.astype(jnp.int64)
        b = r.data.astype(jnp.int64)
        zero = b == 0
        validity = l.validity & r.validity & ~zero
        safe_b = jnp.where(zero, 1, b)
        q = lax.div(a, safe_b)  # trunc toward zero = Java / Spark div
        return ColumnVector(T.INT64, q, validity)


@dataclasses.dataclass(eq=False)
class Remainder(BinaryExpression):
    """x % 0 -> null; result sign follows dividend (Java %)."""
    left: Expression
    right: Expression

    def data_type(self, schema):
        return _arith_result(schema, self.left, self.right)

    def do_columnar(self, l, r, ctx):
        dt = T.common_type(l.dtype, r.dtype)
        l, r = promote(l, dt), promote(r, dt)
        if dt.is_floating:
            zero = r.data == 0.0
            validity = l.validity & r.validity & ~zero
            data = lax.rem(l.data, jnp.where(zero, 1.0, r.data))
        else:
            zero = r.data == 0
            validity = l.validity & r.validity & ~zero
            data = lax.rem(l.data, jnp.where(zero, 1, r.data))
        return ColumnVector(dt, data, validity)


@dataclasses.dataclass(eq=False)
class Pmod(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return _arith_result(schema, self.left, self.right)

    def do_columnar(self, l, r, ctx):
        dt = T.common_type(l.dtype, r.dtype)
        l, r = promote(l, dt), promote(r, dt)
        if dt.is_floating:
            zero = r.data == 0.0
            safe = jnp.where(zero, 1.0, r.data)
        else:
            zero = r.data == 0
            safe = jnp.where(zero, 1, r.data)
        rem = lax.rem(l.data, safe)
        data = jnp.where((rem != 0) & ((rem < 0) != (safe < 0)),
                         rem + safe, rem)
        validity = l.validity & r.validity & ~zero
        return ColumnVector(dt, data, validity)


@dataclasses.dataclass(eq=False)
class UnaryMinus(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        return ColumnVector(c.dtype, -c.data, c.validity)


@dataclasses.dataclass(eq=False)
class UnaryPositive(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        return c


@dataclasses.dataclass(eq=False)
class Abs(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        return ColumnVector(c.dtype, jnp.abs(c.data), c.validity)
