"""String expressions (reference `stringFunctions.scala`, 862 LoC).

Everything is vectorized over the uint8[capacity, char_cap] byte tensor —
string kernels run on the VPU as wide integer ops, the TPU answer to
cuDF's warp-per-string kernels.

Unicode notes (Spark parity):
  - length(), substring(), locate() are CHARACTER-based: UTF-8 character
    starts are bytes with (b & 0xC0) != 0x80 — counted vectorized.
  - upper()/lower()/initcap() fold ASCII only (marked incompat, as the
    reference marks several string ops).
  - LIKE supports full %/_ wildcards via a vectorized DP over the
    (literal) pattern.  Regex ops follow the reference's "regex that is
    really a literal" rule (GpuOverrides.scala:343-393): RLike/RegExpReplace
    accept only meta-character-free patterns, handled as plain find.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import (
    ColumnVector, bucket_char_cap, _pad_chars)
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, EvalContext, Expression, Literal, UnaryExpression)


def _char_starts(data, lengths):
    """bool[cap, cc]: byte is the first byte of a UTF-8 character."""
    pos = jnp.arange(data.shape[1])[None, :]
    in_str = pos < lengths[:, None]
    return in_str & ((data & 0xC0) != 0x80)


def _char_count(data, lengths):
    return _char_starts(data, lengths).sum(axis=1).astype(jnp.int32)


def _pack_chars(data, lengths):
    """Compact UTF-8 characters into uint32[cap, cc]: char i's bytes
    left-aligned big-endian in slot i (slot 0 for absent chars).  Lets
    char-wise algorithms (LIKE) compare whole characters at once."""
    cap, cc = data.shape
    starts = _char_starts(data, lengths)
    pos = jnp.arange(cc)[None, :]
    in_str = pos < lengths[:, None]
    char_idx = jnp.cumsum(starts.astype(jnp.int32), axis=1) - 1
    # byte offset within its character: pos - (position of last start <= pos)
    start_pos = jnp.where(starts, pos, -1)
    start_pos = jax_cummax(start_pos)
    shift = jnp.clip(pos - start_pos, 0, 3)
    contrib = data.astype(jnp.uint32) << ((3 - shift).astype(jnp.uint32)
                                          * 8)
    packed = jnp.zeros((cap, cc), jnp.uint32)
    rows = jnp.arange(cap)[:, None]
    tgt = jnp.where(in_str & (char_idx >= 0), char_idx, cc)
    packed = packed.at[rows, tgt].add(contrib * in_str, mode="drop")
    nchars = starts.sum(axis=1).astype(jnp.int32)
    return packed, nchars


def jax_cummax(x):
    return lax.cummax(x, axis=1)


def _pack_literal_chars(text: str) -> list[int]:
    """Pack each character of a host-side literal the same way."""
    out = []
    for ch in text:
        b = ch.encode("utf-8")
        v = 0
        for j, byte in enumerate(b):
            v |= byte << ((3 - j) * 8)
        out.append(v)
    return out


@dataclasses.dataclass(eq=False)
class Length(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.INT32

    def do_columnar(self, c, ctx):
        return ColumnVector(T.INT32, _char_count(c.data, c.lengths),
                            c.validity)


@dataclasses.dataclass(eq=False)
class _CaseFold(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.STRING

    def do_columnar(self, c, ctx):
        return ColumnVector(T.STRING, self.fold(c.data), c.validity,
                            c.lengths)


class Upper(_CaseFold):
    def fold(self, data):
        is_lower = (data >= ord("a")) & (data <= ord("z"))
        return jnp.where(is_lower, data - 32, data).astype(jnp.uint8)


class Lower(_CaseFold):
    def fold(self, data):
        is_upper = (data >= ord("A")) & (data <= ord("Z"))
        return jnp.where(is_upper, data + 32, data).astype(jnp.uint8)


@dataclasses.dataclass(eq=False)
class InitCap(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.STRING

    def do_columnar(self, c, ctx):
        data = c.data
        prev_space = jnp.concatenate(
            [jnp.ones((data.shape[0], 1), bool),
             data[:, :-1] == ord(" ")], axis=1)
        is_lower = (data >= ord("a")) & (data <= ord("z"))
        is_upper = (data >= ord("A")) & (data <= ord("Z"))
        up = jnp.where(prev_space & is_lower, data - 32, data)
        out = jnp.where(~prev_space & is_upper, up + 32, up)
        return ColumnVector(T.STRING, out.astype(jnp.uint8), c.validity,
                            c.lengths)


def _compact_bytes(data, lengths, selected):
    """Keep selected bytes (per row), shifted left; returns (bytes,
    new_lengths).  One argsort per row along the char axis."""
    cc = data.shape[1]
    pos = jnp.arange(cc)[None, :]
    key = jnp.where(selected, pos, cc + pos)
    perm = jnp.argsort(key, axis=1)
    out = jnp.take_along_axis(data, perm, axis=1)
    new_len = selected.sum(axis=1).astype(jnp.int32)
    out = jnp.where(pos < new_len[:, None], out, 0).astype(jnp.uint8)
    return out, new_len


@dataclasses.dataclass(eq=False)
class Substring(Expression):
    """substring(str, pos, len): 1-based character position; negative pos
    counts from the end (Spark semantics)."""
    child: Expression
    pos: Expression
    length: Optional[Expression] = None

    def data_type(self, schema):
        return T.STRING

    def children(self):
        kids = [self.child, self.pos]
        if self.length is not None:
            kids.append(self.length)
        return tuple(kids)

    def with_children(self, kids):
        return Substring(kids[0], kids[1],
                         kids[2] if len(kids) > 2 else None)

    def eval(self, ctx):
        c = self.child.eval(ctx)
        p = self.pos.eval(ctx)
        data, lengths = c.data, c.lengths
        nchars = _char_count(data, lengths)
        starts = _char_starts(data, lengths)
        # char index of each byte (0-based)
        char_idx = jnp.cumsum(starts.astype(jnp.int32), axis=1) - 1
        pos0 = p.data.astype(jnp.int32)
        # Spark: pos 0 behaves like 1; negative counts from end
        # negative pos may land before the string start; the selection
        # window below handles it (chars < 0 don't exist -> empty result,
        # matching Spark's substring('h', -3, 2) = '')
        start = jnp.where(pos0 > 0, pos0 - 1,
                          jnp.where(pos0 < 0, nchars + pos0, 0))
        if self.length is not None:
            ln = self.length.eval(ctx)
            want = jnp.maximum(ln.data.astype(jnp.int32), 0)
            validity = c.validity & p.validity & ln.validity
        else:
            want = jnp.full(ctx.capacity, 2 ** 30, jnp.int32)
            validity = c.validity & p.validity
        pos_b = jnp.arange(data.shape[1])[None, :]
        in_str = pos_b < lengths[:, None]
        sel = in_str & (char_idx >= start[:, None]) & \
            (char_idx < (start + want)[:, None])
        out, new_len = _compact_bytes(data, lengths, sel)
        return ColumnVector(T.STRING, out, validity, new_len)


@dataclasses.dataclass(eq=False)
class _Trim(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.STRING

    def do_columnar(self, c, ctx):
        data, lengths = c.data, c.lengths
        cc = data.shape[1]
        pos = jnp.arange(cc)[None, :]
        in_str = pos < lengths[:, None]
        is_space = (data == ord(" ")) & in_str
        nonspace = in_str & ~is_space
        any_ns = nonspace.any(axis=1)
        # all-space strings: empty window (first past the end)
        first = jnp.where(any_ns, jnp.argmax(nonspace, axis=1), lengths)
        last = jnp.where(any_ns,
                         cc - 1 - jnp.argmax(nonspace[:, ::-1], axis=1), -1)
        lo, hi = self.window(first, last, lengths)
        sel = in_str & (pos >= lo[:, None]) & (pos <= hi[:, None])
        out, new_len = _compact_bytes(data, lengths, sel)
        return ColumnVector(T.STRING, out, c.validity, new_len)


class StringTrim(_Trim):
    def window(self, first, last, lengths):
        return first, last


class StringTrimLeft(_Trim):
    def window(self, first, last, lengths):
        return first, lengths - 1


class StringTrimRight(_Trim):
    def window(self, first, last, lengths):
        return jnp.zeros_like(first), last


@dataclasses.dataclass(eq=False)
class ConcatStrings(Expression):
    """concat(s1, s2, ...): null if ANY input is null (Spark concat)."""
    exprs: tuple

    def data_type(self, schema):
        return T.STRING

    def children(self):
        return self.exprs

    def with_children(self, kids):
        return ConcatStrings(tuple(kids))

    def eval(self, ctx):
        cols = [e.eval(ctx) for e in self.exprs]
        out = cols[0]
        for c in cols[1:]:
            out = _concat2(out, c)
        return out


def _concat2(a: ColumnVector, b: ColumnVector) -> ColumnVector:
    cc = bucket_char_cap(a.char_cap + b.char_cap)
    a2, b2 = _pad_chars(a, cc), _pad_chars(b, cc)
    pos = jnp.arange(cc)[None, :]
    la = a.lengths[:, None]
    from_b_idx = jnp.clip(pos - la, 0, cc - 1)
    bvals = jnp.take_along_axis(b2.data, from_b_idx, axis=1)
    out = jnp.where(pos < la, a2.data, bvals)
    new_len = a.lengths + b.lengths
    out = jnp.where(pos < new_len[:, None], out, 0).astype(jnp.uint8)
    return ColumnVector(T.STRING, out, a.validity & b.validity, new_len)


def _find_pattern(data, lengths, pat: bytes):
    """bool[cap, cc]: literal pattern matches starting at byte position."""
    cc = data.shape[1]
    plen = len(pat)
    if plen == 0:
        pos = jnp.arange(cc)[None, :]
        return pos <= lengths[:, None]
    hit = jnp.ones(data.shape, bool)
    pos = jnp.arange(cc)[None, :]
    for j, ch in enumerate(pat):
        shifted = jnp.roll(data, -j, axis=1)
        hit = hit & (shifted == ch)
    in_range = pos + plen <= lengths[:, None]
    return hit & in_range


@dataclasses.dataclass(eq=False)
class _LiteralPatternPredicate(Expression):
    """Base for StartsWith/EndsWith/Contains with a literal pattern."""
    child: Expression
    pattern: Expression

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.child, self.pattern)

    def with_children(self, kids):
        return type(self)(kids[0], kids[1])

    def _pat_bytes(self) -> bytes:
        if not isinstance(self.pattern, Literal):
            raise TypeError(
                f"{type(self).__name__} requires a literal pattern "
                "(reference restriction, GpuOverrides.scala:343-393)")
        return str(self.pattern.value).encode("utf-8")

    def eval(self, ctx):
        if isinstance(self.pattern, Literal) and self.pattern.value is None:
            return Literal(None, T.BOOL).eval(ctx)
        c = self.child.eval(ctx)
        pat = self._pat_bytes()
        got = self.test(c, pat)
        return ColumnVector(T.BOOL, got, c.validity)


class Contains(_LiteralPatternPredicate):
    def test(self, c, pat):
        return _find_pattern(c.data, c.lengths, pat).any(axis=1)


class StartsWith(_LiteralPatternPredicate):
    def test(self, c, pat):
        hits = _find_pattern(c.data, c.lengths, pat)
        return hits[:, 0] if hits.shape[1] > 0 else \
            jnp.zeros(c.capacity, bool)


class EndsWith(_LiteralPatternPredicate):
    def test(self, c, pat):
        hits = _find_pattern(c.data, c.lengths, pat)
        at = jnp.clip(c.lengths - len(pat), 0, c.char_cap - 1)
        ok = jnp.take_along_axis(hits, at[:, None], axis=1)[:, 0]
        return ok & (c.lengths >= len(pat))


@dataclasses.dataclass(eq=False)
class Like(Expression):
    """SQL LIKE with % and _, CHARACTER-wise: input and pattern are packed
    to one uint32 per UTF-8 character, then a DP over pattern positions
    runs as a lax.scan across character slots (O(pattern) traced ops per
    scan step, not O(chars x pattern) unrolled).  Escape char \\ supported
    like Spark.  Null pattern -> null result."""
    child: Expression
    pattern: Expression

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.child, self.pattern)

    def with_children(self, kids):
        return Like(kids[0], kids[1])

    def _parse_pattern(self):
        if not isinstance(self.pattern, Literal):
            raise TypeError("LIKE requires a literal pattern")
        pat = str(self.pattern.value)
        toks = []  # (kind, packed_char) kind: 'any'(%), 'one'(_), 'ch'
        chars = list(pat)
        i = 0
        while i < len(chars):
            ch = chars[i]
            if ch == "\\" and i + 1 < len(chars):
                toks.append(("ch", _pack_literal_chars(chars[i + 1])[0]))
                i += 2
            elif ch == "%":
                toks.append(("any", 0))
                i += 1
            elif ch == "_":
                toks.append(("one", 0))
                i += 1
            else:
                toks.append(("ch", _pack_literal_chars(ch)[0]))
                i += 1
        return toks

    def eval(self, ctx):
        c = self.child.eval(ctx)
        if isinstance(self.pattern, Literal) and self.pattern.value is None:
            return Literal(None, T.BOOL).eval(ctx)
        toks = self._parse_pattern()
        packed, nchars = _pack_chars(c.data, c.lengths)
        cap, cc = packed.shape
        np_ = len(toks)
        dp0 = jnp.zeros((cap, np_ + 1), bool).at[:, 0].set(True)
        for j, (kind, _) in enumerate(toks):  # leading % match empty
            if kind == "any":
                dp0 = dp0.at[:, j + 1].set(dp0[:, j])
            else:
                break

        def step(dp, xs):
            ch_val, i = xs
            in_str = i < nchars
            cols = [jnp.ones(cap, bool)]  # ndp[:, 0] stays True? no:
            cols[0] = jnp.zeros(cap, bool)
            for j, (kind, pch) in enumerate(toks):
                if kind == "any":
                    cols.append(cols[j] | dp[:, j + 1] | dp[:, j])
                elif kind == "one":
                    cols.append(dp[:, j])
                else:
                    cols.append(dp[:, j] & (ch_val == pch))
            ndp = jnp.stack(cols, axis=1)
            return jnp.where(in_str[:, None], ndp, dp), None

        dp, _ = lax.scan(step, dp0,
                         (packed.T, jnp.arange(cc, dtype=jnp.int32)))
        return ColumnVector(T.BOOL, dp[:, np_], c.validity)


@dataclasses.dataclass(eq=False)
class StringLocate(Expression):
    """locate(substr, str, start=1): 1-based CHARACTER position of first
    occurrence at-or-after start; 0 if absent."""
    substr: Expression
    child: Expression
    start: Optional[Expression] = None

    def data_type(self, schema):
        return T.INT32

    def children(self):
        kids = [self.substr, self.child]
        if self.start is not None:
            kids.append(self.start)
        return tuple(kids)

    def with_children(self, kids):
        return StringLocate(kids[0], kids[1],
                            kids[2] if len(kids) > 2 else None)

    def eval(self, ctx):
        if not isinstance(self.substr, Literal):
            raise TypeError("locate requires a literal substring")
        if self.substr.value is None:
            return Literal(None, T.INT32).eval(ctx)
        c = self.child.eval(ctx)
        pat = str(self.substr.value).encode("utf-8")
        hits = _find_pattern(c.data, c.lengths, pat)
        starts = _char_starts(c.data, c.lengths)
        char_idx = jnp.cumsum(starts.astype(jnp.int32), axis=1) - 1
        if self.start is not None:
            s = self.start.eval(ctx)
            min_char = s.data.astype(jnp.int32) - 1
            validity = c.validity & s.validity
        else:
            min_char = jnp.zeros(ctx.capacity, jnp.int32)
            validity = c.validity
        ok = hits & (char_idx >= min_char[:, None])
        found = ok.any(axis=1)
        first_byte = jnp.argmax(ok, axis=1)
        rows = jnp.arange(ctx.capacity)
        res = jnp.where(found, char_idx[rows, first_byte] + 1, 0)
        # Spark: locate with start < 1 returns 0 unconditionally
        res = jnp.where(min_char < 0, 0, res)
        return ColumnVector(T.INT32, res.astype(jnp.int32), validity)


@dataclasses.dataclass(eq=False)
class StringReplace(Expression):
    """replace(str, search, replacement) with literal search/replacement;
    greedy non-overlapping left-to-right like Java String.replace."""
    child: Expression
    search: Expression
    replacement: Expression

    def data_type(self, schema):
        return T.STRING

    def children(self):
        return (self.child, self.search, self.replacement)

    def with_children(self, kids):
        return StringReplace(*kids)

    def eval(self, ctx):
        if not (isinstance(self.search, Literal)
                and isinstance(self.replacement, Literal)):
            raise TypeError("replace requires literal search/replacement")
        if self.search.value is None or self.replacement.value is None:
            return Literal(None, T.STRING).eval(ctx)
        c = self.child.eval(ctx)
        s = str(self.search.value).encode("utf-8")
        r = str(self.replacement.value).encode("utf-8")
        if len(s) == 0:
            return c
        data, lengths = c.data, c.lengths
        cap, cc = data.shape
        hits = _find_pattern(data, lengths, s)
        # greedy non-overlap: scan positions, accept hit if >= last end
        def step(last_end, i):
            h = hits[:, i] & (i >= last_end)
            new_end = jnp.where(h, i + jnp.int32(len(s)), last_end)
            return new_end.astype(jnp.int32), h
        _, accepted = lax.scan(step, jnp.zeros(cap, jnp.int32),
                               jnp.arange(cc, dtype=jnp.int32))
        accepted = accepted.T  # [cap, cc]
        n_matches = accepted.sum(axis=1).astype(jnp.int32)
        # byte classification: inside a replaced span?
        spans = jnp.zeros((cap, cc), jnp.int32)
        start_flags = accepted.astype(jnp.int32)
        end_positions = jnp.roll(accepted, len(s), axis=1)
        if len(s) > 0:
            end_positions = end_positions.at[:, :len(s)].set(False)
        inside = (jnp.cumsum(start_flags, axis=1)
                  - jnp.cumsum(end_positions.astype(jnp.int32), axis=1)) > 0
        # output length per row
        new_len = lengths + n_matches * (len(r) - len(s))
        out_cc = bucket_char_cap(int(cc if len(r) <= len(s) else
                                     cc * max(1, -(-len(r) // len(s)))))
        pos = jnp.arange(cc)[None, :]
        in_str = pos < lengths[:, None]
        copy = in_str & ~inside
        # output position of each copied byte:
        #   preceding copied bytes + matches_before * len(r)
        copied_before = jnp.cumsum(copy.astype(jnp.int32), axis=1) - \
            copy.astype(jnp.int32)
        matches_before = jnp.cumsum(start_flags, axis=1) - start_flags
        out_pos = copied_before + matches_before * len(r)
        out = jnp.zeros((cap, out_cc), jnp.uint8)
        rows = jnp.arange(cap)[:, None]
        tgt = jnp.where(copy, out_pos, out_cc)
        out = out.at[rows, tgt].set(data, mode="drop")
        # scatter replacement bytes at each accepted match
        rep_base = copied_before + matches_before * len(r)
        for j, ch in enumerate(r):
            tgt_r = jnp.where(accepted, rep_base + j, out_cc)
            out = out.at[rows, tgt_r].set(jnp.uint8(ch), mode="drop")
        poso = jnp.arange(out_cc)[None, :]
        out = jnp.where(poso < new_len[:, None], out, 0).astype(jnp.uint8)
        return ColumnVector(T.STRING, out, c.validity, new_len)


@dataclasses.dataclass(eq=False)
class _Pad(Expression):
    """CHARACTER-based pad/truncate (Spark lpad/rpad): the target length
    and the fill count are counted in UTF-8 characters, never splitting a
    multi-byte character.  The pad-prefix for every possible fill count is
    precomputed host-side (a [tlen+1, bytes] table) and gathered per row.
    Null length/pad literal -> null result."""
    child: Expression
    target_len: Expression
    pad: Expression

    def data_type(self, schema):
        return T.STRING

    def children(self):
        return (self.child, self.target_len, self.pad)

    def with_children(self, kids):
        return type(self)(*kids)

    def eval(self, ctx):
        if not (isinstance(self.target_len, Literal)
                and isinstance(self.pad, Literal)):
            raise TypeError("pad requires literal length and pad string")
        if self.target_len.value is None or self.pad.value is None:
            return Literal(None, T.STRING).eval(ctx)
        c = self.child.eval(ctx)
        tlen = max(int(self.target_len.value), 0)
        pad_str = str(self.pad.value)
        # truncate to tlen CHARACTERS
        starts = _char_starts(c.data, c.lengths)
        char_idx = jnp.cumsum(starts.astype(jnp.int32), axis=1) - 1
        pos = jnp.arange(c.char_cap)[None, :]
        in_str = pos < c.lengths[:, None]
        sel = in_str & (char_idx < tlen)
        tb, tl = _compact_bytes(c.data, c.lengths, sel)
        trunc = ColumnVector(T.STRING, tb, c.validity, tl)
        nchars = _char_count(c.data, c.lengths)
        if not pad_str:
            return trunc
        # host table: prefix of n pad characters for n in [0, tlen]
        cycle = (pad_str * (tlen // max(len(pad_str), 1) + 1))[:tlen]
        prefixes = [cycle[:n].encode("utf-8") for n in range(tlen + 1)]
        width = max(max((len(p) for p in prefixes), default=1), 1)
        tbl = np.zeros((tlen + 1, width), np.uint8)
        tlens = np.zeros(tlen + 1, np.int32)
        for n, p in enumerate(prefixes):
            tbl[n, : len(p)] = np.frombuffer(p, np.uint8)
            tlens[n] = len(p)
        npad = jnp.clip(tlen - nchars, 0, tlen)
        pdata = jnp.asarray(tbl)[npad]
        plens = jnp.asarray(tlens)[npad]
        prefix = ColumnVector(T.STRING, pdata, c.validity, plens)
        return self.compose(prefix, trunc)


class LPad(_Pad):
    def compose(self, prefix, trunc):
        return _concat2(prefix, trunc)


class RPad(_Pad):
    def compose(self, prefix, trunc):
        return _concat2(trunc, prefix)


def RLike(child: Expression, pattern: Expression) -> Expression:
    """Regex match; only literal (meta-free) patterns are supported —
    mirrors the reference's regexp-as-literal rule."""
    if isinstance(pattern, Literal):
        if pattern.value is None:
            return Literal(None, T.BOOL)
        p = str(pattern.value)
        if not any(ch in p for ch in r".^$*+?()[]{}|\\"):
            return Contains(child, pattern)
    raise TypeError(
        "RLike supports only literal patterns without regex "
        "metacharacters (reference GpuOverrides.scala:343-393)")


def RegExpReplace(child: Expression, pattern: Expression,
                  replacement: Expression) -> Expression:
    if isinstance(pattern, Literal):
        if pattern.value is None:
            return Literal(None, T.STRING)
        p = str(pattern.value)
        if not any(ch in p for ch in r".^$*+?()[]{}|\\"):
            return StringReplace(child, pattern, replacement)
    raise TypeError(
        "RegExpReplace supports only literal patterns without regex "
        "metacharacters (reference GpuOverrides.scala:383-393)")


# --------------------------------------------------------------------------
_REGEX_META = r".^$*+?()[]{}|\\"


def _split_part(c: ColumnVector, delim: bytes, n, limit: int
                ) -> ColumnVector:
    """Fused split-then-index kernel: part `n` (0-based, possibly per-row)
    of each string split on a literal delimiter, Java split semantics
    with limit=-1 (trailing empties kept) or limit>0 (last part takes the
    unsplit rest).  The TPU shape of cuDF's split column: no list column
    is ever materialized — the consumer (GetArrayItem) asks for one part
    and gets a string column."""
    cap, cc = c.data.shape
    chars = c.data
    lens = c.lengths
    L = len(delim)
    pos = jnp.arange(cc)[None, :]
    raw = jnp.ones((cap, cc), bool)
    padded = jnp.pad(chars, ((0, 0), (0, L)))
    for t, byte in enumerate(delim):
        raw = raw & (padded[:, t:t + cc] == byte)
    raw = raw & ((pos + L) <= lens[:, None])
    if L == 1:
        vm = raw  # single-byte delimiters cannot overlap
    else:
        next_free = jnp.zeros(cap, jnp.int32)
        cols = []
        for j in range(cc):
            m = raw[:, j] & (j >= next_free)
            cols.append(m)
            next_free = jnp.where(m, j + L, next_free)
        vm = jnp.stack(cols, axis=1)
    mcum = jnp.cumsum(vm, axis=1)
    if limit > 0:
        vm = vm & (mcum <= limit - 1)
        mcum = jnp.cumsum(vm, axis=1)
    nmatches = vm.sum(axis=1).astype(jnp.int32)
    nparts = nmatches + 1

    n = jnp.asarray(n, jnp.int32)
    if n.ndim == 0:
        n = jnp.broadcast_to(n, (cap,))

    def match_pos(k):
        """Position of the k-th (1-based, per-row) valid match."""
        mask = vm & (mcum == k[:, None])
        found = mask.any(axis=1)
        return jnp.where(found, jnp.argmax(mask, axis=1), lens), found

    pk, _ = match_pos(n)
    start = jnp.where(n == 0, 0, pk + L)
    pk1, found1 = match_pos(n + 1)
    end = jnp.where(found1, pk1, lens)
    exists = (n >= 0) & (n < nparts)
    out_len = jnp.clip(end - start, 0, cc)
    idx = jnp.clip(start[:, None] + pos, 0, cc - 1)
    gathered = jnp.take_along_axis(chars, idx, axis=1)
    tvalid = pos < out_len[:, None]
    out = jnp.where(tvalid, gathered, 0).astype(jnp.uint8)
    return ColumnVector(T.STRING, out, c.validity & exists,
                        jnp.where(exists, out_len, 0))


@dataclasses.dataclass(eq=False)
class StringSplit(Expression):
    """split(str, pattern[, limit]) — reference GpuStringSplit
    (stringFunctions.scala:812).  The pattern must be a regex-free
    literal (the regexp-as-literal rule, GpuOverrides.scala:343-393).
    The v0 type matrix has no array columns (same as the reference), so
    a StringSplit is only evaluable when consumed by GetArrayItem
    (`split(s, d)[i]`), which fuses split+index into one kernel; bare
    use is tagged off the TPU at plan time."""
    child: Expression
    pattern: Expression
    limit: Optional[Expression] = None

    def data_type(self, schema):
        return T.STRING  # element type; the array itself never reifies

    def children(self):
        return ((self.child, self.pattern, self.limit)
                if self.limit is not None else (self.child, self.pattern))

    def with_children(self, kids):
        return StringSplit(kids[0], kids[1],
                           kids[2] if len(kids) > 2 else None)

    def literal_pattern(self) -> Optional[str]:
        if not isinstance(self.pattern, Literal) or \
                self.pattern.value is None:
            return None
        p = str(self.pattern.value)
        if not p or any(ch in p for ch in _REGEX_META):
            return None
        return p

    def literal_limit(self) -> Optional[int]:
        if self.limit is None:
            return -1
        if isinstance(self.limit, Literal) and self.limit.value is not None:
            return int(self.limit.value)
        return None

    def eval(self, ctx: EvalContext):
        raise TypeError(
            "StringSplit must be consumed by GetArrayItem (split(s,d)[i]) "
            "— no array columns in the v0 type matrix; the planner tags "
            "bare use for CPU fallback")


@dataclasses.dataclass(eq=False)
class SubstringIndex(Expression):
    """substring_index(str, delim, count) (reference GpuSubstringIndex,
    stringFunctions.scala:561): count>0 keeps everything before the
    count-th delimiter, count<0 everything after the count-th from the
    end.  delim and count must be literals (same restriction as the
    reference's regexp-as-literal discipline)."""
    child: Expression
    delim: Expression
    count: Expression

    def data_type(self, schema):
        return T.STRING

    def children(self):
        return (self.child, self.delim, self.count)

    def with_children(self, kids):
        return SubstringIndex(*kids)

    def literal_args(self):
        d = self.delim.value if isinstance(self.delim, Literal) else None
        n = self.count.value if isinstance(self.count, Literal) else None
        return d, n

    def eval(self, ctx):
        d, n = self.literal_args()
        if d is None or n is None:
            raise NotImplementedError(
                "substring_index needs literal delim/count (plan-time "
                "tagged)")
        c = self.child.eval(ctx)
        data, lengths = c.data, c.lengths
        cc = data.shape[1]
        dbytes = str(d).encode("utf-8")
        L = len(dbytes)
        n = int(n)
        if L == 0 or n == 0:
            # Spark: empty delim or count 0 -> empty string
            zl = jnp.zeros_like(lengths)
            return ColumnVector(T.STRING, jnp.zeros_like(data),
                                c.validity, zl)
        pos_b = jnp.arange(cc)[None, :]
        match = (pos_b + L) <= lengths[:, None]
        for k, b in enumerate(dbytes):
            shifted = jnp.pad(data, ((0, 0), (0, L)))[:, k:k + cc]
            match = match & (shifted == b)
        occ = jnp.cumsum(match.astype(jnp.int32), axis=1)
        total = occ[:, -1]
        big = jnp.int32(cc + L + 1)
        if n > 0:
            has = total >= n
            cut = jnp.argmax(occ >= n, axis=1).astype(jnp.int32)
            cut = jnp.where(has, cut, big)
            sel = pos_b < cut[:, None]
        else:
            k1 = total + n + 1  # 1-based index of the anchor delimiter
            has = k1 >= 1
            cut = jnp.argmax(occ >= k1[:, None], axis=1).astype(jnp.int32)
            start = jnp.where(has, cut + L, 0)
            sel = pos_b >= start[:, None]
        in_str = pos_b < lengths[:, None]
        out, new_len = _compact_bytes(data, lengths, sel & in_str)
        return ColumnVector(T.STRING, out, c.validity, new_len)
