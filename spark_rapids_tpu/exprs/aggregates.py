"""Aggregate functions (reference `AggregateFunctions.scala`:
GpuAggregateExpression / CudfAggregate bridge; Min/Max/Sum/Count/Average/
First/Last).

TPU design: aggregation is *segment ops over sorted groups*.  The exec
sorts rows by group key, computes segment ids, and each AggregateFunction
contributes three stages mirroring the reference's update/merge/evaluate
split so partial (map-side) and final (reduce-side) aggregation distribute
exactly like Spark's:

  update(values per row)    -> per-segment intermediates   [map side]
  merge(intermediates)      -> combined intermediates      [reduce side]
  evaluate(intermediates)   -> final column

All stages are static-shape: `num_segments == capacity`, with invalid rows
routed to segment id == capacity (dropped by XLA scatter semantics).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import Expression, Literal

_INT_MIN = {
    T.TypeId.INT8: -(2 ** 7), T.TypeId.INT16: -(2 ** 15),
    T.TypeId.INT32: -(2 ** 31), T.TypeId.INT64: -(2 ** 63),
    T.TypeId.DATE32: -(2 ** 31), T.TypeId.TIMESTAMP_US: -(2 ** 63),
    T.TypeId.BOOL: 0,
}
_INT_MAX = {
    T.TypeId.INT8: 2 ** 7 - 1, T.TypeId.INT16: 2 ** 15 - 1,
    T.TypeId.INT32: 2 ** 31 - 1, T.TypeId.INT64: 2 ** 63 - 1,
    T.TypeId.DATE32: 2 ** 31 - 1, T.TypeId.TIMESTAMP_US: 2 ** 63 - 1,
    T.TypeId.BOOL: 1,
}


def _segscan(combine_vals, bounds, *vals):
    """Segmented inclusive scan over rows SORTED by group (Blelchian
    flag-reset operator): the carry resets at each segment start, so
    per-group running reductions cost O(n) work and no scatter —
    XLA:TPU serializes scatters, and the binary-search (searchsorted)
    alternative measured ~300ms/call at 2M rows.

    HAND-ROLLED recursive pair-combine (NOT lax.associative_scan):
    XLA:TPU compile time for the scan HLO grows superlinearly with
    length (measured: 1.6s at 64K rows, 16.6s at 512K, minutes at 2M —
    and a [m, cap] matrix carry never finished), while this expansion
    is ~8 plain static-shape ops per level x log2(cap) levels and
    compiles in seconds at any width.  It also takes ANY number of
    value operands at no extra compile cost, where the multi-operand
    associative_scan blew up on tuple carries (the round-4 finding).

    `combine_vals(a_vals, b_vals)` combines two ADJACENT spans' value
    tuples (left, right)."""

    def rec(f, vs):
        k = f.shape[0]
        if k == 1:
            return vs
        if k % 2:
            # odd length: the appended row starts its own segment, so
            # it never contaminates a carry; sliced off on the way out
            f = jnp.concatenate([f, jnp.ones(1, f.dtype)])
            vs = tuple(jnp.concatenate([v, v[-1:]]) for v in vs)
            return tuple(v[:k] for v in rec(f, vs))
        h = k // 2
        f2 = f.reshape(h, 2)
        fa, fb = f2[:, 0], f2[:, 1]
        va = tuple(v.reshape((h, 2) + v.shape[1:])[:, 0] for v in vs)
        vb = tuple(v.reshape((h, 2) + v.shape[1:])[:, 1] for v in vs)
        merged = combine_vals(va, vb)
        v_pair = tuple(jnp.where(fb, b, m) for b, m in zip(vb, merged))
        vp = rec(fa | fb, v_pair)
        # exclusive carry into pair i = inclusive result of pair i-1
        # (pair 0 has none: masked below, the [0:1] filler is arbitrary)
        vx = tuple(jnp.concatenate([v[:1], v[:-1]]) for v in vp)
        no_carry = fa | (jnp.arange(h) == 0)
        comb_e = combine_vals(vx, va)
        out_even = tuple(jnp.where(no_carry, a, c)
                         for a, c in zip(va, comb_e))
        # interleave: out[2i] = even_i, out[2i+1] = pair-inclusive_i
        return tuple(
            jnp.stack([e, o], axis=1).reshape((k,) + e.shape[1:])
            for e, o in zip(out_even, vp))

    return rec(bounds, vals)


_SCAN_OPS = {
    "add": lambda a, b: a + b,
    "min": jnp.minimum,
    "max": jnp.maximum,
}


class ScanBatch:
    """Cross-function segmented-scan batcher.

    Aggregate functions register per-row operands (`seg(op, arr)`) and
    the kernel runs ONE `_segscan` per round over every registered
    operand, each combined with its own op — one pass over the sorted
    rows instead of one `_segscan` PER FUNCTION (measured r4: each
    2M-row scan dispatch costs ~100ms while a stacked multi-operand
    scan runs in roughly one scan's time; a q1-shaped aggregate ran 8
    separate scans over 15 operands before this existed).

    Handles returned by `seg` resolve to per-GROUP results (gathered at
    segment ends) after `run_round()`.  Operands registered by resumed
    generators go into the next round, so a two-stage function (e.g.
    Welford m2 against the group mean) costs the whole kernel two scan
    dispatches, not two per function."""

    def __init__(self, ctx: "AggContext"):
        self._ctx = ctx
        self._ops: list = []        # combine-op name per handle
        self._pend: list = []       # (handle, row array) this round
        self._results: dict = {}    # handle -> per-group result
        # (op, id(arr)) -> (handle, arr).  The array is HELD in the
        # entry: a dedup key must not outlive its object, or a freed
        # round-1 operand's reused id() could alias a later round's
        # operand and hand it another operand's scan result.
        self._dedup: dict = {}

    def seg(self, op: str, arr) -> int:
        key = (op, id(arr))
        hit = self._dedup.get(key)
        if hit is not None:
            return hit[0]
        h = len(self._ops)
        self._ops.append(op)
        self._pend.append((h, arr))
        self._dedup[key] = (h, arr)
        return h

    def run_round(self) -> None:
        if not self._pend:
            return
        idxs = [h for h, _ in self._pend]
        arrs = [a for _, a in self._pend]
        ops = [_SCAN_OPS[self._ops[h]] for h in idxs]

        def combine(a, b):
            return tuple(op(x, y) for op, x, y in zip(ops, a, b))

        runs = _segscan(combine, self._ctx.bounds, *arrs)
        ends = self._ctx.ends
        for h, r in zip(idxs, runs):
            self._results[h] = jnp.take(r, ends)
        self._pend = []

    def result(self, h: int):
        return self._results[h]


def _drive_eager(make_gen, ctx: "AggContext"):
    scans = ScanBatch(ctx)
    gen = make_gen(scans)
    if gen is None:
        raise NotImplementedError
    next(gen)
    while True:
        scans.run_round()
        try:
            next(gen)
        except StopIteration as e:
            return e.value


def run_agg_phase(actx: "AggContext", funcs, inputs_per_f, phase: str):
    """Drive every aggregate function's update/merge with cross-function
    scan batching; returns the per-function output tuples in order.

    Functions exposing the generator protocol (`update_scans` /
    `merge_scans` returning a generator) register their scan operands,
    yield, and resume with results once the shared round has run;
    functions without it fall back to their eager `update`/`merge`."""
    scans = ScanBatch(actx)
    slots: list = []
    live: list = []
    for f, ins in zip(funcs, inputs_per_f):
        gen = (f.update_scans(actx, scans, ins) if phase == "update"
               else f.merge_scans(actx, scans, ins))
        if gen is None:
            outs = (f.update(actx, ins) if phase == "update"
                    else f.merge(actx, ins))
            slots.append(outs)
        else:
            next(gen)
            slots.append(None)
            live.append((len(slots) - 1, gen))
    while live:
        scans.run_round()
        nxt = []
        for i, gen in live:
            try:
                next(gen)
                nxt.append((i, gen))
            except StopIteration as e:
                slots[i] = e.value
        live = nxt
    return slots


def _sorted_seg_sums(ctx: "AggContext", *vals):
    """Per-group sums of several arrays in ONE segmented scan + gathers
    at segment ends.  Additions happen in row order WITHIN each group
    only (no cross-group mixing), so float results are at least as
    deterministic as a hash groupby's, and integer wraparound matches
    Spark's non-ANSI sum.  Invalid rows must already be value-zeroed
    (they share the last group's segment id)."""
    runs = _segscan(lambda a, b: tuple(x + y for x, y in zip(a, b)),
                    ctx.bounds, *vals)
    return tuple(jnp.take(r, ctx.ends) for r in runs)


def _sorted_seg_sum(vals, ctx: "AggContext"):
    return _sorted_seg_sums(ctx, vals)[0]


@dataclasses.dataclass
class AggContext:
    seg_ids: jnp.ndarray     # per sorted row
    capacity: int            # row-side length (input rows)
    row_valid: jnp.ndarray   # sorted row mask
    #: True at each sorted row that STARTS a group (invalid rows never
    #: start one — they ride the last group's segment id)
    bounds: jnp.ndarray
    #: per-SEGMENT index of its last sorted row (out_capacity-length;
    #: entries at or past the group count are arbitrary, must be masked)
    ends: jnp.ndarray
    #: GROUP-side output length.  The exec compacts groups INSIDE the
    #: kernel (ends/outputs at the compact width) so per-group gathers
    #: and output stores never run at full row capacity — a 2M-row
    #: batch with 1K groups paid ~1/3 of its kernel time materializing
    #: full-capacity group outputs before this existed.
    out_capacity: Optional[int] = None

    def __post_init__(self):
        if self.out_capacity is None:
            self.out_capacity = self.capacity


class AggregateFunction:
    """One aggregate; `child` may be None for Count(*)."""
    child: Optional[Expression]

    def input_exprs(self) -> Sequence[Expression]:
        return () if self.child is None else (self.child,)

    def result_type(self, schema: T.Schema) -> T.DataType:
        raise NotImplementedError

    def intermediate_types(self, schema: T.Schema) -> Sequence[T.DataType]:
        raise NotImplementedError

    # FINAL-mode type resolution: a merge-side exec sees only the partial
    # schema (keys + intermediates), where the original input columns are
    # gone — so counts and result types must be derivable positionally.
    @property
    def num_intermediates(self) -> int:
        return 1

    def result_from_intermediates(
            self, inter: Sequence[T.DataType]) -> T.DataType:
        return inter[0]

    def update(self, ctx: AggContext, inputs: Sequence[ColumnVector]
               ) -> Sequence[ColumnVector]:
        """Eager fallback: drives this function's scan generator with a
        private ScanBatch (single-function callers; the group-by kernel
        batches across functions via run_agg_phase)."""
        return _drive_eager(
            lambda s: self.update_scans(ctx, s, inputs), ctx)

    def merge(self, ctx: AggContext, partials: Sequence[ColumnVector]
              ) -> Sequence[ColumnVector]:
        return _drive_eager(
            lambda s: self.merge_scans(ctx, s, partials), ctx)

    # batched-scan protocol (run_agg_phase): return a GENERATOR that
    # registers operands on the shared ScanBatch, yields once per scan
    # round, and `return`s the output tuple — or None to have the
    # kernel fall back to the eager update/merge above.
    def update_scans(self, ctx: AggContext, scans: "ScanBatch",
                     inputs: Sequence[ColumnVector]):
        return None

    def merge_scans(self, ctx: AggContext, scans: "ScanBatch",
                    partials: Sequence[ColumnVector]):
        return None

    def evaluate(self, partials: Sequence[ColumnVector],
                 schema: T.Schema) -> ColumnVector:
        raise NotImplementedError

    def alias(self, name: str):
        return AggAlias(self, name)


@dataclasses.dataclass
class AggAlias:
    func: AggregateFunction
    name: str


def _sum_type(dt: T.DataType) -> T.DataType:
    return T.FLOAT64 if dt.is_floating else T.INT64


@dataclasses.dataclass
class Sum(AggregateFunction):
    """Spark: sum(int*) -> long, sum(float*) -> double; result is null
    only when every input in the group is null."""
    child: Expression

    def result_type(self, schema):
        return _sum_type(self.child.data_type(schema))

    def intermediate_types(self, schema):
        return (self.result_type(schema),)

    def evaluate(self, partials, schema):
        return partials[0]

    def update_scans(self, ctx, scans, inputs):
        (v,) = inputs
        dt = _sum_type(v.dtype)

        def gen():
            acc = v.data.astype(dt.storage_dtype)
            ok = v.validity & ctx.row_valid
            hs = scans.seg("add", jnp.where(ok, acc, 0))
            # count companion scans i32: it only feeds the null flag,
            # and counts are bounded by capacity < 2^31 (64-bit
            # elementwise is 50-100x slower on this chip)
            hc = scans.seg("add", ok.astype(jnp.int32))
            yield
            return (ColumnVector(dt, scans.result(hs),
                                 scans.result(hc) > 0),)
        return gen()

    def merge_scans(self, ctx, scans, partials):
        (p,) = partials

        def gen():
            ok = p.validity & ctx.row_valid
            hs = scans.seg("add", jnp.where(ok, p.data, 0))
            hc = scans.seg("add", ok.astype(jnp.int32))
            yield
            return (ColumnVector(p.dtype, scans.result(hs),
                                 scans.result(hc) > 0),)
        return gen()


@dataclasses.dataclass
class Count(AggregateFunction):
    """Count(expr) counts non-null; Count(None) == COUNT(*)."""
    child: Optional[Expression] = None

    def result_type(self, schema):
        return T.INT64

    def intermediate_types(self, schema):
        return (T.INT64,)

    def evaluate(self, partials, schema):
        return partials[0]

    def update_scans(self, ctx, scans, inputs):
        def gen():
            if self.child is None:
                ok = ctx.row_valid
            else:
                ok = inputs[0].validity & ctx.row_valid
            # i32 scan (counts bounded by capacity), widened at output
            h = scans.seg("add", ok.astype(jnp.int32))
            yield
            c = scans.result(h).astype(jnp.int64)
            return (ColumnVector(T.INT64, c,
                                 jnp.ones(ctx.out_capacity, bool)),)
        return gen()

    def merge_scans(self, ctx, scans, partials):
        (p,) = partials

        def gen():
            ok = p.validity & ctx.row_valid
            h = scans.seg("add", jnp.where(ok, p.data, 0))
            yield
            return (ColumnVector(T.INT64, scans.result(h),
                                 jnp.ones(ctx.out_capacity, bool)),)
        return gen()


def _minmax_numeric_gen(v: ColumnVector, ctx: AggContext,
                        scans: ScanBatch, is_min: bool):
    """Direct segment min/max with Spark NaN semantics (NaN is the largest
    value).  No bit-encode: 64-bit bitcasts don't lower on TPU.

    floats: max — NaN wins whenever present (map NaN -> +inf and track);
            min — NaN loses unless the whole group is NaN.

    Generator (ScanBatch protocol); yields once, returns (red, has).
    Scans run at the column's NATIVE storage width — the old int path
    widened every operand to i64, and 64-bit elementwise ops are
    50-100x slower on this chip."""
    op = "min" if is_min else "max"
    ok = v.validity & ctx.row_valid
    if v.dtype.is_floating:
        nan = jnp.isnan(v.data) & ok
        non_nan = ok & ~nan
        fill = jnp.inf if is_min else -jnp.inf
        hr = scans.seg(op, jnp.where(non_nan, v.data, fill))
        hc, hn = (scans.seg("add", x.astype(jnp.int32))
                  for x in (ok, non_nan))
        yield
        red = scans.result(hr)
        cnt, n_non_nan = scans.result(hc), scans.result(hn)
        has = cnt > 0
        if is_min:
            # all-NaN group -> NaN
            red = jnp.where(has & (n_non_nan == 0), jnp.nan, red)
        else:
            # any NaN -> NaN is the max
            red = jnp.where(cnt > n_non_nan, jnp.nan, red)
        return red.astype(v.dtype.storage_dtype), has
    fill = (_INT_MAX if is_min else _INT_MIN)[v.dtype.id]
    masked = jnp.where(ok, v.data,
                       jnp.asarray(fill, v.data.dtype))
    hr = scans.seg(op, masked)
    hh = scans.seg("add", ok.astype(jnp.int32))
    yield
    return (scans.result(hr).astype(v.dtype.storage_dtype),
            scans.result(hh) > 0)


@dataclasses.dataclass
class _MinMax(AggregateFunction):
    child: Expression

    @property
    def _is_min(self) -> bool:
        raise NotImplementedError

    def result_type(self, schema):
        return self.child.data_type(schema)

    def intermediate_types(self, schema):
        return (self.child.data_type(schema),)

    def update(self, ctx, inputs):
        (v,) = inputs
        if v.dtype.is_string:
            return self._update_string(ctx, v)
        return super().update(ctx, inputs)

    def merge(self, ctx, partials):
        return self.update(ctx, partials)

    def update_scans(self, ctx, scans, inputs):
        (v,) = inputs
        if v.dtype.is_string:
            return None

        def gen():
            red, has = yield from _minmax_numeric_gen(
                v, ctx, scans, self._is_min)
            return (ColumnVector(v.dtype, red, has),)
        return gen()

    def merge_scans(self, ctx, scans, partials):
        return self.update_scans(ctx, scans, partials)

    def evaluate(self, partials, schema):
        return partials[0]

    def _update_string(self, ctx, v: ColumnVector):
        """Strings: argmin/argmax by byte-lexicographic rank.  Lexsort
        rows by (segment, ok-last, value); each segment keeps ALL its
        rows, so the s-th distinct run in the sorted order IS segment s
        and a positional nonzero over run starts yields every segment's
        winner with no scatter (XLA:TPU serializes scatters)."""
        from spark_rapids_tpu.ops.sort_encode import (encode_key_bits,
                                                      packed_lexsort)
        cap = ctx.capacity
        ok = v.validity & ctx.row_valid
        keys = encode_key_bits(v, ascending=self._is_min,
                               nulls_first=False)
        order = packed_lexsort(
            [(ctx.seg_ids.astype(jnp.uint32), 32),
             ((~ok).astype(jnp.uint8), 1)] + keys)
        seg_sorted = jnp.take(ctx.seg_ids, order)
        isfirst = jnp.concatenate(
            [jnp.ones(1, bool), seg_sorted[1:] != seg_sorted[:-1]])
        # position of each segment's first (= winning) sorted row, in
        # segment order — every segment has >= 1 row, so run index == id
        # (group side: compact width, not row capacity)
        from spark_rapids_tpu.ops.sort_encode import masked_positions
        pos = masked_positions(isfirst, ctx.out_capacity,
                               fill_value=cap - 1)
        idx = jnp.take(order, pos).astype(jnp.int32)
        has = _sorted_seg_sum(ok.astype(jnp.int32), ctx) > 0
        # a group whose rows are all null/invalid sorted them first
        # anyway — mask it out via `has`
        out = v.gather(idx, has)
        return (out,)


class Min(_MinMax):
    _is_min = True


class Max(_MinMax):
    _is_min = False


@dataclasses.dataclass
class Average(AggregateFunction):
    """Spark avg -> double; intermediates are (sum: double, count: long)."""
    child: Expression

    def result_type(self, schema):
        return T.FLOAT64

    def intermediate_types(self, schema):
        return (T.FLOAT64, T.INT64)

    num_intermediates = 2

    def result_from_intermediates(self, inter):
        return T.FLOAT64

    def update_scans(self, ctx, scans, inputs):
        (v,) = inputs

        def gen():
            ok = v.validity & ctx.row_valid
            hs = scans.seg(
                "add", jnp.where(ok, v.data.astype(jnp.float64), 0.0))
            hc = scans.seg("add", ok.astype(jnp.int32))
            yield
            always = jnp.ones(ctx.out_capacity, bool)
            return (ColumnVector(T.FLOAT64, scans.result(hs), always),
                    ColumnVector(T.INT64,
                                 scans.result(hc).astype(jnp.int64),
                                 always))
        return gen()

    def merge_scans(self, ctx, scans, partials):
        s_p, c_p = partials

        def gen():
            ok = ctx.row_valid
            hs = scans.seg("add", jnp.where(ok, s_p.data, 0.0))
            hc = scans.seg("add", jnp.where(ok, c_p.data, 0))
            yield
            always = jnp.ones(ctx.out_capacity, bool)
            return (ColumnVector(T.FLOAT64, scans.result(hs), always),
                    ColumnVector(T.INT64, scans.result(hc), always))
        return gen()

    def evaluate(self, partials, schema):
        s, c = partials
        nonzero = c.data > 0
        avg = s.data / jnp.where(nonzero, c.data, 1).astype(jnp.float64)
        return ColumnVector(T.FLOAT64, avg, nonzero)


@dataclasses.dataclass
class _FirstLast(AggregateFunction):
    child: Expression
    ignore_nulls: bool = False

    @property
    def _is_first(self) -> bool:
        raise NotImplementedError

    def result_type(self, schema):
        return self.child.data_type(schema)

    def intermediate_types(self, schema):
        return (self.child.data_type(schema),)

    def update_scans(self, ctx, scans, inputs):
        (v,) = inputs

        def gen():
            cap = ctx.capacity
            ok = ctx.row_valid & (v.validity if self.ignore_nulls
                                  else jnp.ones(cap, bool))
            rows = jnp.arange(cap, dtype=jnp.int32)
            if self._is_first:
                hp = scans.seg("min", jnp.where(ok, rows, cap))
            else:
                hp = scans.seg("max", jnp.where(ok, rows, -1))
            hh = scans.seg("add", ok.astype(jnp.int32))
            yield
            has = scans.result(hh) > 0
            idx = jnp.where(has, scans.result(hp), 0).astype(jnp.int32)
            return (v.gather(idx, has),)
        return gen()

    def merge_scans(self, ctx, scans, partials):
        return self.update_scans(ctx, scans, partials)

    def evaluate(self, partials, schema):
        return partials[0]


class First(_FirstLast):
    _is_first = True


class Last(_FirstLast):
    _is_first = False


def Avg(e: Expression) -> Average:
    return Average(e)


def CountStar() -> Count:
    return Count(None)


@dataclasses.dataclass
class VarianceSamp(AggregateFunction):
    """Spark var_samp -> double; intermediates (count, mean, m2) with a
    Welford/Chan-style merge — the same buffer layout as Spark's
    CentralMomentAgg, and numerically stable where raw (sum, sum_sq)
    intermediates cancel catastrophically (large-magnitude low-variance
    data, e.g. values ~1e8).  Null for groups with fewer than two
    non-null inputs (pandas ddof=1 semantics; reference registers
    GpuStddevSamp-family aggregates over cuDF VARIANCE/STD)."""
    child: Expression

    def result_type(self, schema):
        return T.FLOAT64

    def intermediate_types(self, schema):
        return (T.INT64, T.FLOAT64, T.FLOAT64)

    num_intermediates = 3

    def result_from_intermediates(self, inter):
        return T.FLOAT64

    def update_scans(self, ctx, scans, inputs):
        (v,) = inputs

        def gen():
            ok = v.validity & ctx.row_valid
            x = jnp.where(ok, v.data.astype(jnp.float64), 0.0)
            hs = scans.seg("add", x)
            hc = scans.seg("add", ok.astype(jnp.int32))
            yield
            c = scans.result(hc).astype(jnp.int64)
            mean = scans.result(hs) / \
                jnp.maximum(c, 1).astype(jnp.float64)
            # second round against the group mean: m2 = sum((x-mean)^2)
            d = jnp.where(ok, x - jnp.take(mean, ctx.seg_ids), 0.0)
            hm = scans.seg("add", d * d)
            yield
            always = jnp.ones(ctx.out_capacity, bool)
            return (ColumnVector(T.INT64, c, always),
                    ColumnVector(T.FLOAT64, mean, always),
                    ColumnVector(T.FLOAT64, scans.result(hm), always))
        return gen()

    def merge_scans(self, ctx, scans, partials):
        c_p, mean_p, m2_p = partials

        def gen():
            ok = ctx.row_valid
            cr = jnp.where(ok, c_p.data, 0)
            crf = cr.astype(jnp.float64)
            hc = scans.seg("add", cr)
            hs = scans.seg("add", jnp.where(ok, mean_p.data * crf, 0.0))
            yield
            c = scans.result(hc)
            mean = scans.result(hs) / \
                jnp.maximum(c, 1).astype(jnp.float64)
            # Chan's merge: m2 = sum_i(m2_i + c_i*(mean_i - mean)^2)
            delta = mean_p.data - jnp.take(mean, ctx.seg_ids)
            contrib = jnp.where(ok, m2_p.data + crf * delta * delta, 0.0)
            hm = scans.seg("add", contrib)
            yield
            always = jnp.ones(ctx.out_capacity, bool)
            return (ColumnVector(T.INT64, c, always),
                    ColumnVector(T.FLOAT64, mean, always),
                    ColumnVector(T.FLOAT64, scans.result(hm), always))
        return gen()

    def _var(self, partials):
        c, _mean, m2 = partials
        ok = c.data > 1
        denom = jnp.where(ok, c.data.astype(jnp.float64) - 1.0, 1.0)
        return m2.data / denom, ok

    def evaluate(self, partials, schema):
        var, ok = self._var(partials)
        return ColumnVector(T.FLOAT64, var, ok)


@dataclasses.dataclass
class StddevSamp(VarianceSamp):
    """Spark stddev_samp -> double (sqrt of the sample variance)."""

    def evaluate(self, partials, schema):
        var, ok = self._var(partials)
        return ColumnVector(T.FLOAT64, jnp.sqrt(var), ok)
