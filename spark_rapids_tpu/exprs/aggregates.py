"""Aggregate functions (reference `AggregateFunctions.scala`:
GpuAggregateExpression / CudfAggregate bridge; Min/Max/Sum/Count/Average/
First/Last).

TPU design: aggregation is *segment ops over sorted groups*.  The exec
sorts rows by group key, computes segment ids, and each AggregateFunction
contributes three stages mirroring the reference's update/merge/evaluate
split so partial (map-side) and final (reduce-side) aggregation distribute
exactly like Spark's:

  update(values per row)    -> per-segment intermediates   [map side]
  merge(intermediates)      -> combined intermediates      [reduce side]
  evaluate(intermediates)   -> final column

All stages are static-shape: `num_segments == capacity`, with invalid rows
routed to segment id == capacity (dropped by XLA scatter semantics).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import Expression, Literal

_INT_MIN = {
    T.TypeId.INT8: -(2 ** 7), T.TypeId.INT16: -(2 ** 15),
    T.TypeId.INT32: -(2 ** 31), T.TypeId.INT64: -(2 ** 63),
    T.TypeId.DATE32: -(2 ** 31), T.TypeId.TIMESTAMP_US: -(2 ** 63),
    T.TypeId.BOOL: 0,
}
_INT_MAX = {
    T.TypeId.INT8: 2 ** 7 - 1, T.TypeId.INT16: 2 ** 15 - 1,
    T.TypeId.INT32: 2 ** 31 - 1, T.TypeId.INT64: 2 ** 63 - 1,
    T.TypeId.DATE32: 2 ** 31 - 1, T.TypeId.TIMESTAMP_US: 2 ** 63 - 1,
    T.TypeId.BOOL: 1,
}


def _seg_sum(vals, seg, n):
    return jax.ops.segment_sum(vals, seg, num_segments=n)


def _sorted_seg_sum(vals, seg, n):
    """Segment sum for NON-DECREASING `seg` (the exec feeds rows sorted
    by group key): cumsum + vectorized binary-search gathers instead of
    a scatter, which serializes on TPU.  Invalid rows must already be
    value-zeroed (they may share the last group's id).  Integer sums
    stay exact even if the running cumsum wraps (two's-complement
    wraparound cancels in the difference).  Floats take the scatter
    path: a global cumsum difference cancels catastrophically when group
    magnitudes differ (a ~1e16 group steals every smaller group's
    precision), which is beyond the reordering the variableFloatAgg gate
    licenses."""
    if jnp.issubdtype(vals.dtype, jnp.floating):
        return _seg_sum(vals, seg, n)
    c = jnp.cumsum(vals)
    idx = jnp.arange(n)
    hi = jnp.searchsorted(seg, idx, side="right")
    lo = jnp.searchsorted(seg, idx, side="left")
    last = vals.shape[0] - 1
    chi = jnp.where(hi > 0, jnp.take(c, jnp.clip(hi - 1, 0, last)), 0)
    clo = jnp.where(lo > 0, jnp.take(c, jnp.clip(lo - 1, 0, last)), 0)
    return chi - clo


def _seg_min(vals, seg, n):
    return jax.ops.segment_min(vals, seg, num_segments=n)


def _seg_max(vals, seg, n):
    return jax.ops.segment_max(vals, seg, num_segments=n)


def _drop_invalid(seg_ids, valid, capacity):
    """Invalid rows -> segment id == capacity (out of range => dropped)."""
    return jnp.where(valid, seg_ids, capacity)


@dataclasses.dataclass
class AggContext:
    seg_ids: jnp.ndarray     # per sorted row
    capacity: int            # == num_segments
    row_valid: jnp.ndarray   # sorted row mask


class AggregateFunction:
    """One aggregate; `child` may be None for Count(*)."""
    child: Optional[Expression]

    def input_exprs(self) -> Sequence[Expression]:
        return () if self.child is None else (self.child,)

    def result_type(self, schema: T.Schema) -> T.DataType:
        raise NotImplementedError

    def intermediate_types(self, schema: T.Schema) -> Sequence[T.DataType]:
        raise NotImplementedError

    # FINAL-mode type resolution: a merge-side exec sees only the partial
    # schema (keys + intermediates), where the original input columns are
    # gone — so counts and result types must be derivable positionally.
    @property
    def num_intermediates(self) -> int:
        return 1

    def result_from_intermediates(
            self, inter: Sequence[T.DataType]) -> T.DataType:
        return inter[0]

    def update(self, ctx: AggContext, inputs: Sequence[ColumnVector]
               ) -> Sequence[ColumnVector]:
        raise NotImplementedError

    def merge(self, ctx: AggContext, partials: Sequence[ColumnVector]
              ) -> Sequence[ColumnVector]:
        raise NotImplementedError

    def evaluate(self, partials: Sequence[ColumnVector],
                 schema: T.Schema) -> ColumnVector:
        raise NotImplementedError

    def alias(self, name: str):
        return AggAlias(self, name)


@dataclasses.dataclass
class AggAlias:
    func: AggregateFunction
    name: str


def _sum_type(dt: T.DataType) -> T.DataType:
    return T.FLOAT64 if dt.is_floating else T.INT64


@dataclasses.dataclass
class Sum(AggregateFunction):
    """Spark: sum(int*) -> long, sum(float*) -> double; result is null
    only when every input in the group is null."""
    child: Expression

    def result_type(self, schema):
        return _sum_type(self.child.data_type(schema))

    def intermediate_types(self, schema):
        return (self.result_type(schema),)

    def update(self, ctx, inputs):
        (v,) = inputs
        dt = _sum_type(v.dtype)
        acc = v.data.astype(dt.storage_dtype)
        ok = v.validity & ctx.row_valid
        s = _sorted_seg_sum(jnp.where(ok, acc, 0), ctx.seg_ids,
                            ctx.capacity)
        cnt = _sorted_seg_sum(ok.astype(jnp.int64), ctx.seg_ids,
                              ctx.capacity)
        return (ColumnVector(dt, s, cnt > 0),)

    def merge(self, ctx, partials):
        (p,) = partials
        ok = p.validity & ctx.row_valid
        s = _sorted_seg_sum(jnp.where(ok, p.data, 0), ctx.seg_ids,
                            ctx.capacity)
        cnt = _sorted_seg_sum(ok.astype(jnp.int64), ctx.seg_ids,
                              ctx.capacity)
        return (ColumnVector(p.dtype, s, cnt > 0),)

    def evaluate(self, partials, schema):
        return partials[0]


@dataclasses.dataclass
class Count(AggregateFunction):
    """Count(expr) counts non-null; Count(None) == COUNT(*)."""
    child: Optional[Expression] = None

    def result_type(self, schema):
        return T.INT64

    def intermediate_types(self, schema):
        return (T.INT64,)

    def update(self, ctx, inputs):
        if self.child is None:
            ok = ctx.row_valid
        else:
            ok = inputs[0].validity & ctx.row_valid
        c = _sorted_seg_sum(ok.astype(jnp.int64), ctx.seg_ids,
                            ctx.capacity)
        return (ColumnVector(T.INT64, c, jnp.ones(ctx.capacity, bool)),)

    def merge(self, ctx, partials):
        (p,) = partials
        ok = p.validity & ctx.row_valid
        c = _sorted_seg_sum(jnp.where(ok, p.data, 0), ctx.seg_ids,
                            ctx.capacity)
        return (ColumnVector(T.INT64, c, jnp.ones(ctx.capacity, bool)),)

    def evaluate(self, partials, schema):
        return partials[0]


def _minmax_numeric(v: ColumnVector, ctx: AggContext, is_min: bool):
    """Direct segment min/max with Spark NaN semantics (NaN is the largest
    value).  No bit-encode: 64-bit bitcasts don't lower on TPU.

    floats: max — NaN wins whenever present (map NaN -> +inf and track);
            min — NaN loses unless the whole group is NaN.
    """
    cap = ctx.capacity
    ok = v.validity & ctx.row_valid
    seg = _drop_invalid(ctx.seg_ids, ok, cap)
    cnt = _seg_sum(ok.astype(jnp.int64), seg, cap)
    has = cnt > 0
    if v.dtype.is_floating:
        nan = jnp.isnan(v.data) & ok
        non_nan = ok & ~nan
        seg_nn = _drop_invalid(ctx.seg_ids, non_nan, cap)
        n_non_nan = _seg_sum(non_nan.astype(jnp.int64), seg_nn, cap)
        any_nan = _seg_sum(nan.astype(jnp.int64), seg, cap) > 0
        fill = jnp.inf if is_min else -jnp.inf
        masked = jnp.where(non_nan, v.data, fill)
        red = _seg_min(masked, seg_nn, cap) if is_min else \
            _seg_max(masked, seg_nn, cap)
        if is_min:
            # all-NaN group -> NaN
            red = jnp.where(has & (n_non_nan == 0), jnp.nan, red)
        else:
            # any NaN -> NaN is the max
            red = jnp.where(any_nan, jnp.nan, red)
        return red.astype(v.dtype.storage_dtype), has
    lo = _INT_MIN[v.dtype.id]
    hi = _INT_MAX[v.dtype.id]
    fill = hi if is_min else lo
    masked = jnp.where(ok, v.data.astype(jnp.int64), fill)
    red = _seg_min(masked, seg, cap) if is_min else \
        _seg_max(masked, seg, cap)
    return red.astype(v.dtype.storage_dtype), has


@dataclasses.dataclass
class _MinMax(AggregateFunction):
    child: Expression

    @property
    def _is_min(self) -> bool:
        raise NotImplementedError

    def result_type(self, schema):
        return self.child.data_type(schema)

    def intermediate_types(self, schema):
        return (self.child.data_type(schema),)

    def update(self, ctx, inputs):
        (v,) = inputs
        if v.dtype.is_string:
            return self._update_string(ctx, v)
        red, has = _minmax_numeric(v, ctx, self._is_min)
        return (ColumnVector(v.dtype, red, has),)

    def merge(self, ctx, partials):
        return self.update(ctx, partials)

    def evaluate(self, partials, schema):
        return partials[0]

    def _update_string(self, ctx, v: ColumnVector):
        """Strings: argmin/argmax by byte-lexicographic rank.  Rank rows
        with a per-segment sorted pass: reuse encode keys to lexsort and
        take the first row per segment."""
        from spark_rapids_tpu.ops.sort_encode import (encode_key_bits,
                                                      packed_lexsort)
        cap = ctx.capacity
        ok = v.validity & ctx.row_valid
        # lexsort by (segment, value) -> first row of each segment wins
        keys = encode_key_bits(v, ascending=self._is_min,
                               nulls_first=False)
        seg_key = _drop_invalid(ctx.seg_ids, ok, cap)
        # segment ids are < 2*cap, well inside 32 bits -> packable
        order = packed_lexsort([(seg_key.astype(jnp.uint64), 32)] + keys)
        seg_sorted = jnp.take(seg_key, order)
        isfirst = jnp.concatenate(
            [jnp.ones(1, bool), seg_sorted[1:] != seg_sorted[:-1]])
        isfirst = isfirst & (seg_sorted < cap)
        # scatter winner row index to its segment slot
        win_per_seg = _seg_min(
            jnp.where(isfirst, order, jnp.iinfo(jnp.int64).max),
            jnp.where(isfirst, seg_sorted, cap), cap)
        has = _seg_sum(ok.astype(jnp.int64),
                       _drop_invalid(ctx.seg_ids, ok, cap), cap) > 0
        idx = jnp.where(has, win_per_seg, 0).astype(jnp.int32)
        out = v.gather(idx, has)
        return (out,)


class Min(_MinMax):
    _is_min = True


class Max(_MinMax):
    _is_min = False


@dataclasses.dataclass
class Average(AggregateFunction):
    """Spark avg -> double; intermediates are (sum: double, count: long)."""
    child: Expression

    def result_type(self, schema):
        return T.FLOAT64

    def intermediate_types(self, schema):
        return (T.FLOAT64, T.INT64)

    num_intermediates = 2

    def result_from_intermediates(self, inter):
        return T.FLOAT64

    def update(self, ctx, inputs):
        (v,) = inputs
        ok = v.validity & ctx.row_valid
        s = _sorted_seg_sum(
            jnp.where(ok, v.data.astype(jnp.float64), 0.0),
            ctx.seg_ids, ctx.capacity)
        c = _sorted_seg_sum(ok.astype(jnp.int64), ctx.seg_ids,
                            ctx.capacity)
        always = jnp.ones(ctx.capacity, bool)
        return (ColumnVector(T.FLOAT64, s, always),
                ColumnVector(T.INT64, c, always))

    def merge(self, ctx, partials):
        s_p, c_p = partials
        ok = ctx.row_valid
        s = _sorted_seg_sum(jnp.where(ok, s_p.data, 0.0), ctx.seg_ids,
                            ctx.capacity)
        c = _sorted_seg_sum(jnp.where(ok, c_p.data, 0), ctx.seg_ids,
                            ctx.capacity)
        always = jnp.ones(ctx.capacity, bool)
        return (ColumnVector(T.FLOAT64, s, always),
                ColumnVector(T.INT64, c, always))

    def evaluate(self, partials, schema):
        s, c = partials
        nonzero = c.data > 0
        avg = s.data / jnp.where(nonzero, c.data, 1).astype(jnp.float64)
        return ColumnVector(T.FLOAT64, avg, nonzero)


@dataclasses.dataclass
class _FirstLast(AggregateFunction):
    child: Expression
    ignore_nulls: bool = False

    @property
    def _is_first(self) -> bool:
        raise NotImplementedError

    def result_type(self, schema):
        return self.child.data_type(schema)

    def intermediate_types(self, schema):
        return (self.child.data_type(schema),)

    def update(self, ctx, inputs):
        (v,) = inputs
        cap = ctx.capacity
        ok = ctx.row_valid & (v.validity if self.ignore_nulls
                              else jnp.ones(cap, bool))
        seg = _drop_invalid(ctx.seg_ids, ok, cap)
        rows = jnp.arange(cap, dtype=jnp.int64)
        if self._is_first:
            pick = _seg_min(jnp.where(ok, rows, jnp.iinfo(jnp.int64).max),
                            seg, cap)
        else:
            pick = _seg_max(jnp.where(ok, rows, -1), seg, cap)
        has = _seg_sum(ok.astype(jnp.int64), seg, cap) > 0
        idx = jnp.where(has, pick, 0).astype(jnp.int32)
        return (v.gather(idx, has),)

    def merge(self, ctx, partials):
        return self.update(ctx, partials)

    def evaluate(self, partials, schema):
        return partials[0]


class First(_FirstLast):
    _is_first = True


class Last(_FirstLast):
    _is_first = False


def Avg(e: Expression) -> Average:
    return Average(e)


def CountStar() -> Count:
    return Count(None)


@dataclasses.dataclass
class VarianceSamp(AggregateFunction):
    """Spark var_samp -> double; intermediates (count, mean, m2) with a
    Welford/Chan-style merge — the same buffer layout as Spark's
    CentralMomentAgg, and numerically stable where raw (sum, sum_sq)
    intermediates cancel catastrophically (large-magnitude low-variance
    data, e.g. values ~1e8).  Null for groups with fewer than two
    non-null inputs (pandas ddof=1 semantics; reference registers
    GpuStddevSamp-family aggregates over cuDF VARIANCE/STD)."""
    child: Expression

    def result_type(self, schema):
        return T.FLOAT64

    def intermediate_types(self, schema):
        return (T.INT64, T.FLOAT64, T.FLOAT64)

    num_intermediates = 3

    def result_from_intermediates(self, inter):
        return T.FLOAT64

    def update(self, ctx, inputs):
        (v,) = inputs
        ok = v.validity & ctx.row_valid
        x = jnp.where(ok, v.data.astype(jnp.float64), 0.0)
        c = _sorted_seg_sum(ok.astype(jnp.int64), ctx.seg_ids,
                            ctx.capacity)
        s = _sorted_seg_sum(x, ctx.seg_ids, ctx.capacity)
        mean = s / jnp.maximum(c, 1).astype(jnp.float64)
        # second pass against the group mean: m2 = sum((x - mean)^2)
        d = jnp.where(ok, x - jnp.take(mean, ctx.seg_ids), 0.0)
        m2 = _sorted_seg_sum(d * d, ctx.seg_ids, ctx.capacity)
        always = jnp.ones(ctx.capacity, bool)
        return (ColumnVector(T.INT64, c, always),
                ColumnVector(T.FLOAT64, mean, always),
                ColumnVector(T.FLOAT64, m2, always))

    def merge(self, ctx, partials):
        c_p, mean_p, m2_p = partials
        ok = ctx.row_valid
        cr = jnp.where(ok, c_p.data, 0)
        crf = cr.astype(jnp.float64)
        c = _sorted_seg_sum(cr, ctx.seg_ids, ctx.capacity)
        s = _sorted_seg_sum(jnp.where(ok, mean_p.data * crf, 0.0),
                            ctx.seg_ids, ctx.capacity)
        mean = s / jnp.maximum(c, 1).astype(jnp.float64)
        # Chan's parallel merge: m2 = sum_i(m2_i + c_i*(mean_i - mean)^2)
        delta = mean_p.data - jnp.take(mean, ctx.seg_ids)
        contrib = jnp.where(ok, m2_p.data + crf * delta * delta, 0.0)
        m2 = _sorted_seg_sum(contrib, ctx.seg_ids, ctx.capacity)
        always = jnp.ones(ctx.capacity, bool)
        return (ColumnVector(T.INT64, c, always),
                ColumnVector(T.FLOAT64, mean, always),
                ColumnVector(T.FLOAT64, m2, always))

    def _var(self, partials):
        c, _mean, m2 = partials
        ok = c.data > 1
        denom = jnp.where(ok, c.data.astype(jnp.float64) - 1.0, 1.0)
        return m2.data / denom, ok

    def evaluate(self, partials, schema):
        var, ok = self._var(partials)
        return ColumnVector(T.FLOAT64, var, ok)


@dataclasses.dataclass
class StddevSamp(VarianceSamp):
    """Spark stddev_samp -> double (sqrt of the sample variance)."""

    def evaluate(self, partials, schema):
        var, ok = self._var(partials)
        return ColumnVector(T.FLOAT64, jnp.sqrt(var), ok)
