"""Predicates and comparisons (reference `predicates.scala`, `GpuInSet.scala`).

Spark parity:
  - NaN ordering: NaN is greater than every other value and NaN == NaN.
  - And/Or use Kleene three-valued logic (false AND null = false, etc.).
  - EqualNullSafe (<=>) treats two nulls as equal.
  - String comparisons are lexicographic over UTF-8 bytes.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, UnaryExpression, promote)


def _string_cmp(l: ColumnVector, r: ColumnVector):
    """Lexicographic three-way compare of byte-tensor strings: returns
    (lt, eq) bool arrays.  Vectorized over the char axis."""
    cc = max(l.char_cap, r.char_cap)
    from spark_rapids_tpu.columnar.vector import _pad_chars
    a, b = _pad_chars(l, cc), _pad_chars(r, cc)
    la = a.lengths[:, None]
    lb = b.lengths[:, None]
    pos = jnp.arange(cc)[None, :]
    av = jnp.where(pos < la, a.data.astype(jnp.int32), -1)
    bv = jnp.where(pos < lb, b.data.astype(jnp.int32), -1)
    diff = av != bv
    # first differing position decides; all-equal -> equal
    any_diff = diff.any(axis=1)
    first = jnp.argmax(diff, axis=1)
    rows = jnp.arange(a.capacity)
    lt = jnp.where(any_diff, av[rows, first] < bv[rows, first], False)
    eq = ~any_diff
    return lt, eq


def _compare(l: ColumnVector, r: ColumnVector):
    """Returns (lt, eq) with Spark NaN semantics for floats."""
    if l.dtype.is_string:
        return _string_cmp(l, r)
    dt = l.dtype if l.dtype == r.dtype else T.common_type(l.dtype, r.dtype)
    l, r = promote(l, dt), promote(r, dt)
    a, b = l.data, r.data
    if dt.is_floating:
        na, nb = jnp.isnan(a), jnp.isnan(b)
        eq = jnp.where(na & nb, True, a == b)
        lt = jnp.where(na, False, jnp.where(nb, True, a < b))
        return lt, eq
    return a < b, a == b


@dataclasses.dataclass(eq=False)
class _Comparison(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.BOOL

    def do_columnar(self, l, r, ctx):
        lt, eq = _compare(l, r)
        return ColumnVector(T.BOOL, self.pick(lt, eq),
                            l.validity & r.validity)


class EqualTo(_Comparison):
    def pick(self, lt, eq):
        return eq


class LessThan(_Comparison):
    def pick(self, lt, eq):
        return lt


class LessThanOrEqual(_Comparison):
    def pick(self, lt, eq):
        return lt | eq


class GreaterThan(_Comparison):
    def pick(self, lt, eq):
        return ~(lt | eq)


class GreaterThanOrEqual(_Comparison):
    def pick(self, lt, eq):
        return ~lt


@dataclasses.dataclass(eq=False)
class EqualNullSafe(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.BOOL

    def do_columnar(self, l, r, ctx):
        _, eq = _compare(l, r)
        both_null = ~l.validity & ~r.validity
        one_null = l.validity != r.validity
        data = jnp.where(both_null, True, jnp.where(one_null, False, eq))
        return ColumnVector(T.BOOL, data, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class And(Expression):
    """Kleene: F AND x = F even if x is null."""
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.left, self.right)

    def with_children(self, kids):
        return And(*kids)

    def eval(self, ctx):
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        lv = l.validity & l.data.astype(bool)
        rv = r.validity & r.data.astype(bool)
        lf = l.validity & ~l.data.astype(bool)
        rf = r.validity & ~r.data.astype(bool)
        data = lv & rv
        validity = (lf | rf) | (l.validity & r.validity)
        return ColumnVector(T.BOOL, data, validity)


@dataclasses.dataclass(eq=False)
class Or(Expression):
    """Kleene: T OR x = T even if x is null."""
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.left, self.right)

    def with_children(self, kids):
        return Or(*kids)

    def eval(self, ctx):
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        lt_ = l.validity & l.data.astype(bool)
        rt_ = r.validity & r.data.astype(bool)
        data = lt_ | rt_
        validity = (lt_ | rt_) | (l.validity & r.validity)
        return ColumnVector(T.BOOL, data, validity)


@dataclasses.dataclass(eq=False)
class Not(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.BOOL

    def do_columnar(self, c, ctx):
        return ColumnVector(T.BOOL, ~c.data.astype(bool), c.validity)


@dataclasses.dataclass(eq=False)
class IsNull(Expression):
    child: Expression

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return IsNull(kids[0])

    def eval(self, ctx):
        c = self.child.eval(ctx)
        return ColumnVector(T.BOOL, ~c.validity & ctx.row_mask, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class IsNotNull(Expression):
    child: Expression

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return IsNotNull(kids[0])

    def eval(self, ctx):
        c = self.child.eval(ctx)
        return ColumnVector(T.BOOL, c.validity & ctx.row_mask, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class IsNaN(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.BOOL

    def do_columnar(self, c, ctx):
        # Spark's IsNaN is non-nullable: null input -> false
        return ColumnVector(T.BOOL, jnp.isnan(c.data) & c.validity,
                            ctx.row_mask)


@dataclasses.dataclass(eq=False)
class InSet(Expression):
    """value IN (literal set) — reference `GpuInSet.scala:98`.  The literal
    set is baked into the executable as a constant vector; membership is a
    broadcast-compare-any, which XLA lowers to one fused loop."""
    child: Expression
    values: tuple

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return InSet(kids[0], self.values)

    def eval(self, ctx):
        c = self.child.eval(ctx)
        has_null_in_list = any(v is None for v in self.values)
        vals = [v for v in self.values if v is not None]
        if c.dtype.is_string:
            from spark_rapids_tpu.exprs.base import Literal
            hit = jnp.zeros(c.capacity, bool)
            for v in vals:
                lv = Literal.of(str(v), T.STRING).eval(ctx)
                _, eq = _string_cmp(c, lv)
                hit = hit | eq
        else:
            # tpulint: disable=host-sync -- the IN-list is a python
            # list of plan literals (host), not a device value
            arr = np.asarray(vals, c.dtype.storage_dtype)
            if len(arr) == 0:
                hit = jnp.zeros(c.capacity, bool)
            else:
                hit = (c.data[:, None] == jnp.asarray(arr)[None, :]).any(
                    axis=1)
        # Spark: x IN (...) is null if x is null, or no match and list has null
        validity = c.validity & ~(~hit & has_null_in_list)
        return ColumnVector(T.BOOL, hit, validity)


def In(child: Expression, values) -> InSet:
    return InSet(child, tuple(values))
