"""Datetime expressions (reference `datetimeExpressions.scala` 560 LoC +
`DateUtils.scala`).

All timestamp math is UTC-only, the same guard the reference enforces
(`GpuOverrides.scala:397-409` rejects non-UTC JVM timezones).  Civil-date
arithmetic comes from exprs/datetime_utils.py (vectorized Hinnant
algorithms — pure int ops, fully fused by XLA).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs import datetime_utils as DT
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, Literal, UnaryExpression)


def _as_days(c: ColumnVector):
    if c.dtype.id == T.TypeId.DATE32:
        return c.data
    if c.dtype.id == T.TypeId.TIMESTAMP_US:
        return DT.micros_to_date_days(c.data)
    raise TypeError(f"expected date/timestamp, got {c.dtype}")


@dataclasses.dataclass(eq=False)
class _DateField(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.INT32

    def do_columnar(self, c, ctx):
        days = _as_days(c)
        return ColumnVector(T.INT32,
                            self.field(days).astype(jnp.int32), c.validity)


class Year(_DateField):
    def field(self, days):
        y, _, _ = DT.days_to_ymd(days)
        return y


class Month(_DateField):
    def field(self, days):
        _, m, _ = DT.days_to_ymd(days)
        return m


class DayOfMonth(_DateField):
    def field(self, days):
        _, _, d = DT.days_to_ymd(days)
        return d


class DayOfWeek(_DateField):
    def field(self, days):
        return DT.day_of_week(days)


class DayOfYear(_DateField):
    def field(self, days):
        return DT.day_of_year(days)


class Quarter(_DateField):
    def field(self, days):
        return DT.quarter(days)


class WeekOfYear(_DateField):
    """ISO-8601 week number (Spark weekofyear)."""

    def field(self, days):
        doy = DT.day_of_year(days)
        # ISO day-of-week: Mon=1..Sun=7 ; our day_of_week: Sun=1..Sat=7
        dow_sun1 = DT.day_of_week(days)
        iso_dow = jnp.where(dow_sun1 == 1, 7, dow_sun1 - 1)
        w = (doy - iso_dow + 10) // 7
        y, _, _ = DT.days_to_ymd(days)
        # w == 0 -> last week of previous year
        prev_dec31 = DT.ymd_to_days(y - 1, jnp.full_like(y, 12),
                                    jnp.full_like(y, 31))
        prev_w = ((DT.day_of_year(prev_dec31)
                   - jnp.where(DT.day_of_week(prev_dec31) == 1, 7,
                               DT.day_of_week(prev_dec31) - 1) + 10) // 7)
        # w == 53 but Dec 28 rule says week 1 of next year
        dec28 = DT.ymd_to_days(y, jnp.full_like(y, 12),
                               jnp.full_like(y, 28))
        max_w = ((DT.day_of_year(dec28)
                  - jnp.where(DT.day_of_week(dec28) == 1, 7,
                              DT.day_of_week(dec28) - 1) + 10) // 7)
        out = jnp.where(w < 1, prev_w, jnp.where(w > max_w, 1, w))
        return out


class LastDay(_DateField):
    def data_type(self, schema):
        return T.DATE32

    def do_columnar(self, c, ctx):
        days = _as_days(c)
        return ColumnVector(T.DATE32, DT.last_day_of_month(days),
                            c.validity)


@dataclasses.dataclass(eq=False)
class _TimeField(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.INT32

    def do_columnar(self, c, ctx):
        assert c.dtype.id == T.TypeId.TIMESTAMP_US, \
            f"expected timestamp, got {c.dtype}"
        h, mnt, s, us = DT.micros_time_of_day(c.data)
        return ColumnVector(T.INT32,
                            self.pick(h, mnt, s, us).astype(jnp.int32),
                            c.validity)


class Hour(_TimeField):
    def pick(self, h, mnt, s, us):
        return h


class Minute(_TimeField):
    def pick(self, h, mnt, s, us):
        return mnt


class Second(_TimeField):
    def pick(self, h, mnt, s, us):
        return s


@dataclasses.dataclass(eq=False)
class DateAdd(BinaryExpression):
    left: Expression   # date
    right: Expression  # days to add (int)

    def data_type(self, schema):
        return T.DATE32

    def do_columnar(self, l, r, ctx):
        days = _as_days(l) + r.data.astype(jnp.int32)
        return ColumnVector(T.DATE32, days.astype(jnp.int32),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class DateSub(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.DATE32

    def do_columnar(self, l, r, ctx):
        days = _as_days(l) - r.data.astype(jnp.int32)
        return ColumnVector(T.DATE32, days.astype(jnp.int32),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class DateDiff(BinaryExpression):
    """datediff(end, start) in days."""
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.INT32

    def do_columnar(self, l, r, ctx):
        d = _as_days(l) - _as_days(r)
        return ColumnVector(T.INT32, d.astype(jnp.int32),
                            l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class AddMonths(BinaryExpression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.DATE32

    def do_columnar(self, l, r, ctx):
        y, m, d = DT.days_to_ymd(_as_days(l))
        total = (y * 12 + (m - 1)) + r.data.astype(jnp.int64)
        ny = total // 12
        nm = total - ny * 12 + 1
        # clamp day to last day of target month (Spark/Java semantics)
        first = DT.ymd_to_days(ny, nm, jnp.ones_like(nm))
        last = DT.last_day_of_month(first)
        _, _, last_d = DT.days_to_ymd(last)
        nd = jnp.minimum(d, last_d)
        out = DT.ymd_to_days(ny, nm, nd)
        return ColumnVector(T.DATE32, out, l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class MonthsBetween(BinaryExpression):
    """months_between(end, start): Spark semantics — whole months when
    both are the same day-of-month (and same time) or both the last day
    of their month; otherwise months + (day+time difference)/31, rounded
    to 8 decimals (roundOff=true default)."""
    left: Expression
    right: Expression

    def data_type(self, schema):
        return T.FLOAT64

    @staticmethod
    def _sec_of_day(c):
        if c.dtype.id == T.TypeId.TIMESTAMP_US:
            days = DT.micros_to_date_days(c.data)
            tod = c.data - days.astype(jnp.int64) * DT.MICROS_PER_DAY
            return tod.astype(jnp.float64) / DT.MICROS_PER_SECOND
        return jnp.zeros(c.capacity, jnp.float64)

    def do_columnar(self, l, r, ctx):
        y1, m1, d1 = DT.days_to_ymd(_as_days(l))
        y2, m2, d2 = DT.days_to_ymd(_as_days(r))
        s1 = self._sec_of_day(l)
        s2 = self._sec_of_day(r)
        _, _, ld1 = DT.days_to_ymd(DT.last_day_of_month(_as_days(l)))
        _, _, ld2 = DT.days_to_ymd(DT.last_day_of_month(_as_days(r)))
        both_last = (d1 == ld1) & (d2 == ld2)
        same_point = (d1 == d2) & (s1 == s2)
        months = ((y1 - y2) * 12 + (m1 - m2)).astype(jnp.float64)
        frac = ((d1 - d2).astype(jnp.float64)
                + (s1 - s2) / 86400.0) / 31.0
        out = jnp.where(both_last | same_point, months, months + frac)
        out = jnp.round(out * 1e8) / 1e8  # roundOff=true
        return ColumnVector(T.FLOAT64, out, l.validity & r.validity)


@dataclasses.dataclass(eq=False)
class UnixTimestamp(UnaryExpression):
    """unix_timestamp(ts): seconds since epoch (UTC)."""
    child: Expression

    def data_type(self, schema):
        return T.INT64

    def do_columnar(self, c, ctx):
        if c.dtype.id == T.TypeId.TIMESTAMP_US:
            secs = c.data // DT.MICROS_PER_SECOND
        elif c.dtype.id == T.TypeId.DATE32:
            secs = c.data.astype(jnp.int64) * 86400
        else:
            raise TypeError(
                "unix_timestamp on strings requires a format parse; only "
                "date/timestamp inputs are device-native")
        return ColumnVector(T.INT64, secs.astype(jnp.int64), c.validity)


@dataclasses.dataclass(eq=False)
class FromUnixTime(UnaryExpression):
    """from_unixtime(secs) -> timestamp (the reference emits a formatted
    string; we expose the timestamp — cast to STRING for the text form)."""
    child: Expression

    def data_type(self, schema):
        return T.TIMESTAMP_US

    def do_columnar(self, c, ctx):
        us = c.data.astype(jnp.int64) * DT.MICROS_PER_SECOND
        return ColumnVector(T.TIMESTAMP_US, us, c.validity)


@dataclasses.dataclass(eq=False)
class ToDate(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return T.DATE32

    def do_columnar(self, c, ctx):
        if c.dtype.id == T.TypeId.DATE32:
            return c
        if c.dtype.id == T.TypeId.TIMESTAMP_US:
            return ColumnVector(T.DATE32, DT.micros_to_date_days(c.data),
                                c.validity)
        from spark_rapids_tpu.exprs.cast import _string_to_date
        return _string_to_date(c)


@dataclasses.dataclass(eq=False)
class TruncDate(Expression):
    """trunc(date, fmt) for fmt in year/month/week."""
    child: Expression
    fmt: Expression

    def data_type(self, schema):
        return T.DATE32

    def children(self):
        return (self.child, self.fmt)

    def with_children(self, kids):
        return TruncDate(*kids)

    def eval(self, ctx):
        if not isinstance(self.fmt, Literal):
            raise TypeError("trunc requires a literal format")
        c = self.child.eval(ctx)
        days = _as_days(c)
        f = str(self.fmt.value).lower()
        y, m, d = DT.days_to_ymd(days)
        if f in ("year", "yyyy", "yy"):
            out = DT.ymd_to_days(y, jnp.ones_like(m), jnp.ones_like(d))
        elif f in ("month", "mon", "mm"):
            out = DT.ymd_to_days(y, m, jnp.ones_like(d))
        elif f == "week":
            # Monday of the current week
            dow_sun1 = DT.day_of_week(days)
            iso = jnp.where(dow_sun1 == 1, 7, dow_sun1 - 1)
            out = (days.astype(jnp.int64) - (iso - 1)).astype(jnp.int32)
        else:
            raise ValueError(f"unsupported trunc format {f!r}")
        return ColumnVector(T.DATE32, out, c.validity)


@dataclasses.dataclass(eq=False)
class WeekDay(UnaryExpression):
    """weekday(date): 0=Monday ... 6=Sunday (reference
    datetimeExpressions.scala GpuWeekDay)."""
    child: Expression

    def data_type(self, schema):
        return T.INT32

    def do_columnar(self, c, ctx):
        d = c.data.astype(jnp.int64)
        # 1970-01-01 was a Thursday (weekday 3 in Monday-first scheme)
        out = ((d + 3) % 7).astype(jnp.int32)
        return ColumnVector(T.INT32, out, c.validity)


@dataclasses.dataclass(eq=False)
class ToUnixTimestamp(UnaryExpression):
    """to_unix_timestamp(ts): seconds since epoch — same kernel as
    UnixTimestamp, separate Catalyst expression (reference registers
    both, GpuOverrides.scala datetime region)."""
    child: Expression

    def data_type(self, schema):
        return T.INT64

    def do_columnar(self, c, ctx):
        return UnixTimestamp(self.child).do_columnar(c, ctx)


@dataclasses.dataclass(eq=False)
class TimeAdd(BinaryExpression):
    """timestamp + CalendarInterval (microseconds component only, same
    restriction as the reference GpuTimeAdd: tagged off for month
    intervals — datetimeExpressions.scala)."""
    left: Expression   # timestamp
    right: Expression  # interval micros (int64)

    def data_type(self, schema):
        return T.TIMESTAMP_US

    def do_columnar(self, l, r, ctx):
        us = l.data.astype(jnp.int64) + r.data.astype(jnp.int64)
        return ColumnVector(T.TIMESTAMP_US, us,
                            l.validity & r.validity)
