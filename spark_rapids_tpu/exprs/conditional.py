"""Conditional & null-handling expressions (reference
`conditionalExpressions.scala`, `nullExpressions.scala`)."""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector, align_char_caps
from spark_rapids_tpu.exprs.base import EvalContext, Expression


def _select(cond: jnp.ndarray, a: ColumnVector, b: ColumnVector
            ) -> ColumnVector:
    """where(cond, a, b) over ColumnVectors, string-aware."""
    if a.dtype.is_string:
        a, b = align_char_caps(a, b)
        data = jnp.where(cond[:, None], a.data, b.data)
        lengths = jnp.where(cond, a.lengths, b.lengths)
        validity = jnp.where(cond, a.validity, b.validity)
        return ColumnVector(a.dtype, data, validity, lengths)
    dt = a.dtype if a.dtype == b.dtype else T.common_type(a.dtype, b.dtype)
    from spark_rapids_tpu.exprs.base import promote
    a, b = promote(a, dt), promote(b, dt)
    data = jnp.where(cond, a.data, b.data)
    validity = jnp.where(cond, a.validity, b.validity)
    return ColumnVector(dt, data, validity)


def _branch_type(schema, *exprs) -> T.DataType:
    """Common result type across branches — must agree with what _select
    produces at eval time (numeric promotion)."""
    out = exprs[0].data_type(schema)
    for e in exprs[1:]:
        dt = e.data_type(schema)
        if dt != out:
            out = T.common_type(out, dt)
    return out


@dataclasses.dataclass(eq=False)
class If(Expression):
    predicate: Expression
    true_value: Expression
    false_value: Expression

    def data_type(self, schema):
        return _branch_type(schema, self.true_value, self.false_value)

    def children(self):
        return (self.predicate, self.true_value, self.false_value)

    def with_children(self, kids):
        return If(*kids)

    def eval(self, ctx: EvalContext):
        p = self.predicate.eval(ctx)
        t = self.true_value.eval(ctx)
        f = self.false_value.eval(ctx)
        cond = p.validity & p.data.astype(bool)  # null predicate -> else
        return _select(cond, t, f)


@dataclasses.dataclass(eq=False)
class CaseWhen(Expression):
    branches: tuple  # ((cond, value), ...)
    else_value: Optional[Expression] = None

    def data_type(self, schema):
        vals = [v for _, v in self.branches]
        if self.else_value is not None:
            vals.append(self.else_value)
        return _branch_type(schema, *vals)

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.else_value is not None:
            out.append(self.else_value)
        return tuple(out)

    def with_children(self, kids):
        n = len(self.branches)
        branches = tuple((kids[2 * i], kids[2 * i + 1]) for i in range(n))
        else_v = kids[2 * n] if len(kids) > 2 * n else None
        return CaseWhen(branches, else_v)

    def eval(self, ctx: EvalContext):
        from spark_rapids_tpu.exprs.base import Literal
        dt = None
        evaluated = []
        for cond, val in self.branches:
            c = cond.eval(ctx)
            v = val.eval(ctx)
            dt = v.dtype if dt is None else dt
            evaluated.append((c.validity & c.data.astype(bool), v))
        if self.else_value is not None:
            out = self.else_value.eval(ctx)
        else:
            out = Literal(None, dt).eval(ctx)
        for cond, v in reversed(evaluated):
            out = _select(cond, v, out)
        return out


@dataclasses.dataclass(eq=False)
class Coalesce(Expression):
    exprs: tuple

    def data_type(self, schema):
        return _branch_type(schema, *self.exprs)

    def children(self):
        return self.exprs

    def with_children(self, kids):
        return Coalesce(tuple(kids))

    def eval(self, ctx: EvalContext):
        out = self.exprs[0].eval(ctx)
        for e in self.exprs[1:]:
            v = e.eval(ctx)
            out = _select(out.validity, out, v)
        return out


def Nvl(a: Expression, b: Expression) -> Coalesce:
    return Coalesce((a, b))


@dataclasses.dataclass(eq=False)
class NullIf(Expression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return self.left.data_type(schema)

    def children(self):
        return (self.left, self.right)

    def with_children(self, kids):
        return NullIf(*kids)

    def eval(self, ctx):
        from spark_rapids_tpu.exprs.predicates import EqualTo
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        eq = EqualTo(self.left, self.right).do_columnar(l, r, ctx)
        validity = l.validity & ~(eq.validity & eq.data)
        return ColumnVector(l.dtype, l.data, validity, l.lengths)


@dataclasses.dataclass(eq=False)
class Nvl2(Expression):
    expr: Expression
    not_null_val: Expression
    null_val: Expression

    def data_type(self, schema):
        return self.not_null_val.data_type(schema)

    def children(self):
        return (self.expr, self.not_null_val, self.null_val)

    def with_children(self, kids):
        return Nvl2(*kids)

    def eval(self, ctx):
        e = self.expr.eval(ctx)
        a = self.not_null_val.eval(ctx)
        b = self.null_val.eval(ctx)
        return _select(e.validity, a, b)


@dataclasses.dataclass(eq=False)
class AtLeastNNonNulls(Expression):
    """Reference nullExpressions.scala GpuAtLeastNNonNulls: true when at
    least n of the children are non-null and non-NaN."""
    n: int
    exprs: tuple

    def data_type(self, schema):
        return T.BOOL

    def children(self):
        return self.exprs

    def with_children(self, kids):
        return AtLeastNNonNulls(self.n, tuple(kids))

    def eval(self, ctx: EvalContext):
        count = jnp.zeros(ctx.capacity, jnp.int32)
        for e in self.exprs:
            v = e.eval(ctx)
            ok = v.validity
            if v.dtype.is_floating:
                ok = ok & ~jnp.isnan(v.data)
            count = count + ok.astype(jnp.int32)
        return ColumnVector(T.BOOL, count >= self.n, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class NaNvl(Expression):
    left: Expression
    right: Expression

    def data_type(self, schema):
        return self.left.data_type(schema)

    def children(self):
        return (self.left, self.right)

    def with_children(self, kids):
        return NaNvl(*kids)

    def eval(self, ctx):
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        return _select(~jnp.isnan(l.data), l, r)
