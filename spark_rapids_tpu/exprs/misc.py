"""Misc expressions (reference `GpuMonotonicallyIncreasingID.scala`,
`GpuSparkPartitionID.scala`, `GpuInputFileBlock.scala`,
`GpuRandomExpressions.scala`, `NormalizeNaNAndZero.scala`,
`constraintExpressions.scala`)."""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector
from spark_rapids_tpu.exprs.base import (
    EvalContext, Expression, UnaryExpression)


@dataclasses.dataclass
class TaskContextInfo:
    """Per-partition execution context, set by the engine before a kernel
    evaluates expressions that depend on task identity (the analog of
    Spark's TaskContext + InputFileBlockHolder)."""
    partition_id: int = 0
    row_offset: int = 0          # rows emitted before this batch
    input_file: str = ""
    input_file_offset: int = 0
    input_file_length: int = 0


_ACTIVE_TASK = TaskContextInfo()


def set_task_context(info: TaskContextInfo) -> None:
    global _ACTIVE_TASK
    _ACTIVE_TASK = info


def get_task_context() -> TaskContextInfo:
    return _ACTIVE_TASK


@dataclasses.dataclass(eq=False)
class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row_index_within_partition, like Spark."""

    def data_type(self, schema):
        return T.INT64

    def bind(self, schema):
        return self

    def eval(self, ctx: EvalContext):
        tc = get_task_context()
        base = (tc.partition_id << 33) + tc.row_offset
        data = jnp.arange(ctx.capacity, dtype=jnp.int64) + base
        return ColumnVector(T.INT64, data, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class SparkPartitionID(Expression):
    def data_type(self, schema):
        return T.INT32

    def bind(self, schema):
        return self

    def eval(self, ctx):
        tc = get_task_context()
        data = jnp.full(ctx.capacity, tc.partition_id, jnp.int32)
        return ColumnVector(T.INT32, data, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class InputFileName(Expression):
    def data_type(self, schema):
        return T.STRING

    def bind(self, schema):
        return self

    def eval(self, ctx):
        from spark_rapids_tpu.exprs.base import Literal
        return Literal(get_task_context().input_file, T.STRING).eval(ctx)


@dataclasses.dataclass(eq=False)
class InputFileBlockStart(Expression):
    def data_type(self, schema):
        return T.INT64

    def bind(self, schema):
        return self

    def eval(self, ctx):
        v = get_task_context().input_file_offset
        return ColumnVector(T.INT64, jnp.full(ctx.capacity, v, jnp.int64),
                            ctx.row_mask)


@dataclasses.dataclass(eq=False)
class InputFileBlockLength(Expression):
    def data_type(self, schema):
        return T.INT64

    def bind(self, schema):
        return self

    def eval(self, ctx):
        v = get_task_context().input_file_length
        return ColumnVector(T.INT64, jnp.full(ctx.capacity, v, jnp.int64),
                            ctx.row_mask)


@dataclasses.dataclass(eq=False)
class Rand(Expression):
    """rand(seed): uniform [0,1) via JAX's counter-based PRNG — unlike the
    reference's per-task XORShift, results are reproducible across retries
    because the key derives from (seed, partition, row offset), not
    mutable task state."""
    seed: int = 0

    def data_type(self, schema):
        return T.FLOAT64

    def bind(self, schema):
        return self

    def eval(self, ctx):
        tc = get_task_context()
        key = jax.random.key(
            (self.seed * 1_000_003 + tc.partition_id) & 0x7FFFFFFF)
        key = jax.random.fold_in(key, tc.row_offset)
        data = jax.random.uniform(key, (ctx.capacity,), jnp.float64)
        return ColumnVector(T.FLOAT64, data, ctx.row_mask)


@dataclasses.dataclass(eq=False)
class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize NaN payloads and -0.0 for grouping/join keys
    (reference NormalizeFloatingNumbers)."""
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        x = c.data
        x = jnp.where(jnp.isnan(x), jnp.nan, x)
        x = jnp.where(x == 0.0, 0.0, x)  # -0.0 == 0.0 -> +0.0
        return ColumnVector(c.dtype, x, c.validity)


@dataclasses.dataclass(eq=False)
class KnownFloatingPointNormalized(UnaryExpression):
    """Marker wrapper (reference constraintExpressions.scala)."""
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        return c


@dataclasses.dataclass(eq=False)
class KnownNotNull(UnaryExpression):
    child: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def do_columnar(self, c, ctx):
        return ColumnVector(c.dtype, c.data, ctx.row_mask, c.lengths)
