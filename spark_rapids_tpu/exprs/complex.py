"""Complex-type extractors and inline constructors (reference
`complexTypeExtractors.scala:88` GetArrayItem/GetMapValue, plus Spark's
`complexTypeCreator` CreateArray/CreateMap).

The v0 type matrix has no stored array/map columns — the same limit the
reference has (SURVEY.md §2.6).  What the reference accelerates is the
*extractor over an inline construction*: `split(s, d)[i]`,
`array(a, b, c)[i]`, `map('k1', v1, 'k2', v2)[k]`.  On TPU these fuse
into pure select/kernel shapes with no list column ever materialized —
the static-shape answer to cuDF's list columns:

  - GetArrayItem(StringSplit(...))   -> fused split-part kernel
  - GetArrayItem(CreateArray(...))   -> per-row select over N evaluated
                                        element columns
  - GetMapValue(CreateMap(...))      -> first-key-match select

Bare CreateArray/CreateMap/StringSplit (an actual array value reaching
the output) are tagged off the TPU at plan time.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector, _pad_chars
from spark_rapids_tpu.exprs.base import EvalContext, Expression, promote


@dataclasses.dataclass(eq=False)
class CreateArray(Expression):
    """array(e1, ..., eN): only evaluable through GetArrayItem."""
    elements: tuple

    def __init__(self, elements):
        self.elements = tuple(elements)

    def element_type(self, schema) -> T.DataType:
        dt = self.elements[0].data_type(schema)
        for e in self.elements[1:]:
            d2 = e.data_type(schema)
            if d2 != dt:
                dt = T.common_type(dt, d2)
        return dt

    def data_type(self, schema):
        return self.element_type(schema)

    def children(self):
        return self.elements

    def with_children(self, kids):
        return CreateArray(tuple(kids))

    def eval(self, ctx):
        raise TypeError("CreateArray must be consumed by GetArrayItem "
                        "(no array columns in the v0 type matrix)")


@dataclasses.dataclass(eq=False)
class CreateMap(Expression):
    """map(k1, v1, ..., kN, vN): only evaluable through GetMapValue."""
    entries: tuple  # flat (k1, v1, k2, v2, ...)

    def __init__(self, entries):
        assert len(entries) % 2 == 0 and entries, "map needs k/v pairs"
        self.entries = tuple(entries)

    def value_type(self, schema) -> T.DataType:
        vals = self.entries[1::2]
        dt = vals[0].data_type(schema)
        for e in vals[1:]:
            d2 = e.data_type(schema)
            if d2 != dt:
                dt = T.common_type(dt, d2)
        return dt

    def data_type(self, schema):
        return self.value_type(schema)

    def children(self):
        return self.entries

    def with_children(self, kids):
        return CreateMap(tuple(kids))

    def eval(self, ctx):
        raise TypeError("CreateMap must be consumed by GetMapValue "
                        "(no map columns in the v0 type matrix)")


def _select_columns(masks, cols, dtype, cap):
    """First-true-mask select across N evaluated columns (all same
    promoted dtype).  Strings are selected over padded char tensors."""
    if dtype.is_string:
        cc = max(c.char_cap for c in cols)
        cols = [_pad_chars(c, cc) for c in cols]
        data = jnp.zeros((cap, cc), jnp.uint8)
        lengths = jnp.zeros(cap, jnp.int32)
    else:
        data = jnp.zeros(cap, dtype.storage_dtype)
        lengths = None
    validity = jnp.zeros(cap, bool)
    taken = jnp.zeros(cap, bool)
    for m, c in zip(masks, cols):
        use = m & ~taken
        if dtype.is_string:
            data = jnp.where(use[:, None], c.data, data)
            lengths = jnp.where(use, c.lengths, lengths)
        else:
            data = jnp.where(use, c.data, data)
        validity = jnp.where(use, c.validity, validity)
        taken = taken | use
    return data, validity & taken, lengths, taken


@dataclasses.dataclass(eq=False)
class GetArrayItem(Expression):
    """array[i] (reference complexTypeExtractors.scala:88): out-of-range
    or null index -> null (non-ANSI)."""
    child: Expression
    ordinal: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def children(self):
        return (self.child, self.ordinal)

    def with_children(self, kids):
        return GetArrayItem(kids[0], kids[1])

    def eval(self, ctx: EvalContext) -> ColumnVector:
        from spark_rapids_tpu.exprs.string_fns import (
            StringSplit, _split_part)
        ch = self.child
        nv = self.ordinal.eval(ctx)
        n = nv.data.astype(jnp.int32)
        if isinstance(ch, StringSplit):
            pat = ch.literal_pattern()
            limit = ch.literal_limit()
            sc = ch.child.eval(ctx)
            out = _split_part(sc, pat.encode(), n, limit)
            return ColumnVector(T.STRING, out.data,
                                out.validity & nv.validity, out.lengths)
        if isinstance(ch, CreateArray):
            # per-row select element n
            cols = [e.eval(ctx) for e in ch.elements]
            dt = cols[0].dtype
            for c in cols[1:]:
                if c.dtype != dt:
                    dt = T.common_type(dt, c.dtype)
            cols = [c if c.dtype == dt else promote(c, dt) for c in cols]
            masks = [n == k for k in range(len(cols))]
            data, validity, lengths, _ = _select_columns(
                masks, cols, dt, ctx.capacity)
            return ColumnVector(dt, data, validity & nv.validity, lengths)
        raise TypeError(
            f"GetArrayItem over {type(ch).__name__} is not supported "
            "(no array columns in the v0 type matrix)")


@dataclasses.dataclass(eq=False)
class GetMapValue(Expression):
    """map[key] (reference complexTypeExtractors.scala GetMapValue):
    first entry whose key equals the lookup key; no match -> null."""
    child: Expression
    key: Expression

    def data_type(self, schema):
        return self.child.data_type(schema)

    def children(self):
        return (self.child, self.key)

    def with_children(self, kids):
        return GetMapValue(kids[0], kids[1])

    def eval(self, ctx: EvalContext) -> ColumnVector:
        from spark_rapids_tpu.exprs.predicates import _compare
        ch = self.child
        if not isinstance(ch, CreateMap):
            raise TypeError(
                f"GetMapValue over {type(ch).__name__} is not supported "
                "(no map columns in the v0 type matrix)")
        keyv = self.key.eval(ctx)
        keys = [e.eval(ctx) for e in ch.entries[0::2]]
        vals = [e.eval(ctx) for e in ch.entries[1::2]]
        dt = vals[0].dtype
        for c in vals[1:]:
            if c.dtype != dt:
                dt = T.common_type(dt, c.dtype)
        vals = [c if c.dtype == dt else promote(c, dt) for c in vals]
        masks = []
        for kc in keys:
            _, eq = _compare(kc, keyv)
            masks.append(eq & kc.validity & keyv.validity)
        data, validity, lengths, _ = _select_columns(
            masks, vals, dt, ctx.capacity)
        return ColumnVector(dt, data, validity, lengths)
