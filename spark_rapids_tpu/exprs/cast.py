"""Cast expression (reference `GpuCast.scala:31,188`).

Spark (non-ANSI) cast semantics implemented on-device:
  - float -> int: Java semantics — truncate toward zero, saturate at type
    bounds, NaN -> 0.
  - int -> bool: nonzero is true; bool -> numeric: 1/0.
  - numeric/bool/date -> string: device-side digit/format generation over
    byte tensors (no host round trip).
  - string -> int/long: trimmed decimal parse, invalid -> null.
  - string -> float and string -> timestamp are gated by conf like the
    reference (`spark.rapids.sql.castStringToFloat.enabled` etc.).
  - timestamp <-> date via UTC-day arithmetic (UTC-only, as the reference).

ANSI mode raises on overflow/invalid instead of null/wrap; we implement the
null/wrap path and expose `ansi` to fail at plan time (tagged unsupported)
to stay honest rather than silently differing.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import ColumnVector, bucket_char_cap
from spark_rapids_tpu.exprs import datetime_utils as DT
from spark_rapids_tpu.exprs.base import EvalContext, Expression

_INT_BOUNDS = {
    T.TypeId.INT8: (-(2 ** 7), 2 ** 7 - 1),
    T.TypeId.INT16: (-(2 ** 15), 2 ** 15 - 1),
    T.TypeId.INT32: (-(2 ** 31), 2 ** 31 - 1),
    T.TypeId.INT64: (-(2 ** 63), 2 ** 63 - 1),
}


@dataclasses.dataclass(eq=False)
class Cast(Expression):
    child: Expression
    to: T.DataType
    ansi: bool = False

    def data_type(self, schema):
        return self.to

    def children(self):
        return (self.child,)

    def with_children(self, kids):
        return Cast(kids[0], self.to, self.ansi)

    def eval(self, ctx: EvalContext) -> ColumnVector:
        c = self.child.eval(ctx)
        src, dst = c.dtype, self.to
        if src == dst:
            return c
        if dst.is_string:
            return _to_string(c, ctx)
        if src.is_string:
            return _from_string(c, dst, ctx)
        if dst.id == T.TypeId.BOOL:
            return ColumnVector(T.BOOL, c.data != 0, c.validity)
        if src.id == T.TypeId.BOOL:
            return ColumnVector(
                dst, c.data.astype(dst.storage_dtype), c.validity)
        if src.is_floating and dst.is_integral:
            return _float_to_int(c, dst)
        if src.id == T.TypeId.TIMESTAMP_US and dst.id == T.TypeId.DATE32:
            return ColumnVector(
                T.DATE32, DT.micros_to_date_days(c.data), c.validity)
        if src.id == T.TypeId.DATE32 and dst.id == T.TypeId.TIMESTAMP_US:
            return ColumnVector(
                T.TIMESTAMP_US,
                c.data.astype(jnp.int64) * DT.MICROS_PER_DAY, c.validity)
        if src.id == T.TypeId.TIMESTAMP_US and dst.is_numeric:
            # Spark: timestamp -> long/double is SECONDS since epoch
            secs = c.data.astype(jnp.float64) / DT.MICROS_PER_SECOND
            if dst.is_floating:
                return ColumnVector(dst, secs.astype(dst.storage_dtype),
                                    c.validity)
            return ColumnVector(
                dst, (c.data // DT.MICROS_PER_SECOND).astype(
                    dst.storage_dtype), c.validity)
        if dst.id == T.TypeId.TIMESTAMP_US and src.is_numeric:
            if src.is_floating:
                # Spark doubleToTimestamp: NaN/Infinity -> null
                bad = jnp.isnan(c.data) | jnp.isinf(c.data)
                safe = jnp.where(bad, 0.0, c.data)
                data = (safe * DT.MICROS_PER_SECOND).astype(jnp.int64)
                return ColumnVector(T.TIMESTAMP_US, data,
                                    c.validity & ~bad)
            data = c.data.astype(jnp.int64) * DT.MICROS_PER_SECOND
            return ColumnVector(T.TIMESTAMP_US, data, c.validity)
        # plain numeric widening/narrowing: wraps like Java (non-ANSI)
        return ColumnVector(dst, c.data.astype(dst.storage_dtype), c.validity)

    def __repr__(self):
        return f"cast({self.child!r} as {self.to})"


def _float_to_int(c: ColumnVector, dst: T.DataType) -> ColumnVector:
    lo, hi = _INT_BOUNDS[dst.id if dst.id in _INT_BOUNDS else T.TypeId.INT64]
    x = c.data
    nan = jnp.isnan(x)
    trunc = jnp.trunc(jnp.where(nan, 0.0, x))
    # saturate via explicit selects — jnp.clip(inf) NaNs out, and XLA's
    # f64->s32 convert is lossy at the boundary, so pick exact int bounds
    over = trunc >= float(hi)
    under = trunc <= float(lo)
    safe = jnp.where(over | under, 0.0, trunc).astype(jnp.int64)
    data = jnp.where(over, hi, jnp.where(under, lo, safe))
    return ColumnVector(dst, data.astype(dst.storage_dtype), c.validity)


# --------------------------------------------------------------------------
# to-string kernels: all device-side byte-tensor generation
_MAX_I64_DIGITS = 19


def _int_to_string(values, capacity: int):
    """int64 -> (bytes uint8[cap, 20], lengths int32[cap])."""
    v = values.astype(jnp.int64)
    neg = v < 0
    # abs via where to dodge INT64_MIN overflow: work in uint64
    mag = jnp.where(neg, (-(v + 1)).astype(jnp.uint64) + 1,
                    v.astype(jnp.uint64))
    pows = jnp.asarray([10 ** (18 - k) for k in range(_MAX_I64_DIGITS)],
                       dtype=jnp.uint64)
    digits = (mag[:, None] // pows[None, :]) % 10          # [cap, 19]
    ndig = _MAX_I64_DIGITS - jnp.argmax(digits != 0, axis=1)
    ndig = jnp.where((digits != 0).any(axis=1), ndig, 1)   # "0"
    length = ndig + neg
    width = _MAX_I64_DIGITS + 1
    pos = jnp.arange(width)[None, :]
    # output char j: '-' at j=0 when neg; digit index = 19 - ndig + (j - neg)
    didx = (_MAX_I64_DIGITS - ndig)[:, None] + pos - neg[:, None].astype(
        jnp.int64)
    didx = jnp.clip(didx, 0, _MAX_I64_DIGITS - 1)
    chars = jnp.take_along_axis(digits, didx.astype(jnp.int32), axis=1)
    out = (chars + ord("0")).astype(jnp.uint8)
    out = jnp.where(neg[:, None] & (pos == 0), ord("-"), out)
    out = jnp.where(pos < length[:, None], out, 0).astype(jnp.uint8)
    return out, length.astype(jnp.int32)


def _pad2(x):
    """int -> two ascii digit chars [cap, 2]."""
    x = x.astype(jnp.int64)
    return jnp.stack([x // 10 + ord("0"), x % 10 + ord("0")],
                     axis=1).astype(jnp.uint8)


def _date_to_string(days, capacity: int):
    """date32 -> 'yyyy-MM-dd' byte tensor (width 10; years 0000-9999)."""
    y, m, d = DT.days_to_ymd(days)
    yc = jnp.stack([(y // 1000) % 10, (y // 100) % 10, (y // 10) % 10,
                    y % 10], axis=1) + ord("0")
    dash = jnp.full((capacity, 1), ord("-"), jnp.uint8)
    out = jnp.concatenate([yc.astype(jnp.uint8), dash, _pad2(m), dash,
                           _pad2(d)], axis=1)
    return out, jnp.full(capacity, 10, jnp.int32)


def _timestamp_to_string(micros, capacity: int):
    """timestamp -> 'yyyy-MM-dd HH:mm:ss[.ffffff]' (Spark trims trailing
    zeros of fraction; we emit seconds precision + micros when nonzero)."""
    days = DT.micros_to_date_days(micros)
    date_part, _ = _date_to_string(days, capacity)
    h, mnt, s, us = DT.micros_time_of_day(micros)
    sp = jnp.full((capacity, 1), ord(" "), jnp.uint8)
    colon = jnp.full((capacity, 1), ord(":"), jnp.uint8)
    base = jnp.concatenate([date_part, sp, _pad2(h), colon, _pad2(mnt),
                            colon, _pad2(s)], axis=1)          # width 19
    # fraction: 6 digits + '.', present when us != 0
    digs = jnp.stack([(us // 10 ** (5 - k)) % 10 for k in range(6)],
                     axis=1) + ord("0")
    dot = jnp.full((capacity, 1), ord("."), jnp.uint8)
    frac = jnp.concatenate([dot, digs.astype(jnp.uint8)], axis=1)
    has_frac = us != 0
    # trailing-zero trim: fraction length = 6 - count of trailing zeros
    tz = jnp.zeros(capacity, jnp.int32)
    running = jnp.ones(capacity, bool)
    for k in range(5, -1, -1):
        z = (digs[:, k] - ord("0")) == 0
        running = running & z
        tz = tz + running.astype(jnp.int32)
    frac_len = jnp.where(has_frac, 7 - tz, 0)
    out = jnp.concatenate([base, frac], axis=1)
    pos = jnp.arange(out.shape[1])[None, :]
    length = 19 + frac_len
    out = jnp.where(pos < length[:, None], out, 0).astype(jnp.uint8)
    return out, length.astype(jnp.int32)


def _to_string(c: ColumnVector, ctx) -> ColumnVector:
    cap = c.capacity
    if c.dtype.id == T.TypeId.BOOL:
        width = 5
        t = np.zeros(width, np.uint8)
        t[:4] = np.frombuffer(b"true", np.uint8)
        f = np.frombuffer(b"false", np.uint8)
        data = jnp.where(c.data[:, None],
                         jnp.asarray(t)[None, :], jnp.asarray(f)[None, :])
        lengths = jnp.where(c.data, 4, 5).astype(jnp.int32)
        return ColumnVector(T.STRING, data.astype(jnp.uint8), c.validity,
                            lengths)
    if c.dtype.id == T.TypeId.DATE32:
        data, lengths = _date_to_string(c.data, cap)
        return ColumnVector(T.STRING, data, c.validity, lengths)
    if c.dtype.id == T.TypeId.TIMESTAMP_US:
        data, lengths = _timestamp_to_string(c.data, cap)
        return ColumnVector(T.STRING, data, c.validity, lengths)
    if c.dtype.is_integral:
        data, lengths = _int_to_string(c.data, cap)
        return ColumnVector(T.STRING, data, c.validity, lengths)
    if c.dtype.is_floating:
        # gated like the reference (castFloatToString.enabled): formatting
        # differs from Java's Double.toString shortest-repr; we emit %.6g-ish
        raise NotImplementedError(
            "float->string cast requires "
            "spark.rapids.sql.castFloatToString.enabled handling at plan "
            "time; not supported in kernels yet")
    raise NotImplementedError(f"cast {c.dtype} -> string")


# --------------------------------------------------------------------------
def _from_string(c: ColumnVector, dst: T.DataType, ctx) -> ColumnVector:
    if dst.is_integral and dst.id not in (T.TypeId.DATE32,
                                          T.TypeId.TIMESTAMP_US):
        return _string_to_int(c, dst)
    if dst.is_floating:
        raise NotImplementedError(
            "string->float cast is gated "
            "(spark.rapids.sql.castStringToFloat.enabled)")
    if dst.id == T.TypeId.DATE32:
        return _string_to_date(c)
    raise NotImplementedError(f"cast string -> {dst}")


def _string_to_int(c: ColumnVector, dst: T.DataType) -> ColumnVector:
    """Trimmed decimal parse; invalid or overflowing -> null (Spark)."""
    cc = c.char_cap
    chars = c.data.astype(jnp.int32)                     # [cap, cc]
    lens = c.lengths
    pos = jnp.arange(cc)[None, :]
    in_str = pos < lens[:, None]
    is_space = (chars == ord(" ")) & in_str
    # leading spaces
    lead = jnp.argmax((~is_space) & in_str, axis=1)
    lead = jnp.where((is_space | ~in_str).all(axis=1), lens, lead)
    # trailing spaces: last non-space index
    rev_nonspace = (~is_space) & in_str
    last = (cc - 1) - jnp.argmax(rev_nonspace[:, ::-1], axis=1)
    last = jnp.where(rev_nonspace.any(axis=1), last, -1)
    sign_char = jnp.take_along_axis(chars, lead[:, None],
                                    axis=1)[:, 0]
    has_sign = (sign_char == ord("-")) | (sign_char == ord("+"))
    neg = sign_char == ord("-")
    start = lead + has_sign.astype(jnp.int64)
    ndigits = last - start + 1
    in_digits = (pos >= start[:, None]) & (pos <= last[:, None])
    dig = chars - ord("0")
    digit_ok = (dig >= 0) & (dig <= 9)
    # significant digits (leading zeros allowed, like Long.parseLong)
    sig = in_digits & (dig != 0)
    first_sig = jnp.where(sig.any(axis=1), jnp.argmax(sig, axis=1), last + 1)
    sig_digits = jnp.maximum(last - first_sig + 1, 0)
    # Horner accumulate in uint64: 19 significant digits can't wrap
    # (10^19 - 1 < 2^64), so overflow detection is an exact compare
    acc = jnp.zeros(c.capacity, jnp.uint64)
    for k in range(cc):
        use = in_digits[:, k]
        acc = jnp.where(use, acc * jnp.uint64(10)
                        + dig[:, k].astype(jnp.uint64), acc)
    limit = jnp.where(neg, jnp.uint64(2 ** 63), jnp.uint64(2 ** 63 - 1))
    valid_parse = (ndigits >= 1) & (sig_digits <= 19) & (acc <= limit) & \
        (jnp.where(in_digits, digit_ok, True).all(axis=1))
    acc_i = acc.astype(jnp.int64)  # 2^63 wraps to INT64_MIN, handled below
    val = jnp.where(neg,
                    jnp.where(acc == jnp.uint64(2 ** 63),
                              jnp.int64(-2 ** 63), -acc_i),
                    acc_i)
    lo, hi = _INT_BOUNDS.get(dst.id, _INT_BOUNDS[T.TypeId.INT64])
    in_range = (val >= lo) & (val <= hi)
    validity = c.validity & valid_parse & in_range
    return ColumnVector(dst, val.astype(dst.storage_dtype),
                        validity)


def _string_to_date(c: ColumnVector) -> ColumnVector:
    """Parse 'yyyy-MM-dd' (and 'yyyy-M-d' variants rejected -> null; Spark
    accepts several shapes, we support the canonical one plus yyyy-MM)."""
    cc = c.char_cap
    if cc < 10:
        from spark_rapids_tpu.columnar.vector import _pad_chars
        c = _pad_chars(c, 10)
        cc = 10
    chars = c.data.astype(jnp.int32)
    ok_len = c.lengths == 10
    dig = chars - ord("0")

    def num(sl):
        out = jnp.zeros(c.capacity, jnp.int64)
        for k in sl:
            out = out * 10 + dig[:, k]
        return out

    digits_ok = jnp.ones(c.capacity, bool)
    for k in (0, 1, 2, 3, 5, 6, 8, 9):
        digits_ok = digits_ok & (dig[:, k] >= 0) & (dig[:, k] <= 9)
    dashes_ok = (chars[:, 4] == ord("-")) & (chars[:, 7] == ord("-"))
    y, m, d = num((0, 1, 2, 3)), num((5, 6)), num((8, 9))
    range_ok = (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31)
    days = DT.ymd_to_days(y, m, d)
    # reject impossible dates (e.g. Feb 31): round-trip must reproduce
    # the parsed fields exactly, otherwise ymd_to_days normalized them
    ry, rm, rd = DT.days_to_ymd(days)
    exact = (ry == y) & (rm == m) & (rd == d)
    validity = c.validity & ok_len & digits_ok & dashes_ok & range_ok & exact
    return ColumnVector(T.DATE32, days, validity)
